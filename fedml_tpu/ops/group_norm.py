"""Fused GroupNorm as pallas TPU kernels (fwd + custom VJP).

Why it exists: the s2d round-time attribution
(scripts/sweep_s2d_attrib.py, v5e, 2026-07-31) measured GroupNorm's
MARGINAL cost at ~38% of the full federated round, so a fused
one-VMEM-pass kernel (stats + normalize + affine; backward recomputes
instead of saving temporaries) was the round's designated lever.

Measured OUTCOME — a documented dead end at CIFAR-ResNet shapes
(docs/ROOFLINE.md): the fused-GN round runs 98.2 ms vs 44.1 ms for
XLA's lowering (same config, same params). The ablation's 38% is the
marginal cost of GN *fused into the surrounding conv chains* — XLA
folds the normalize/affine into conv epilogues, so swapping in an
opaque pallas call severs those fusions and forces extra HBM
round-trips per layer that the kernel's own efficiency cannot buy
back. The op stays available (``models.resnet.Norm(kind="gn_fused")``,
param-compatible with ``"gn"``); models default to ``"gn"``.

The reserved use case is now MEASURED, not hypothetical
(scripts/sweep_gn_standalone.py, v5e, 2026-07-31, random cotangent —
an all-ones cotangent lets XLA simplify the mean-subtracted backward
and was rejected as an unfair workload): standalone wide-channel GN
TRAINING steps (fwd+bwd) run 0.67-0.73x of flax's time at C=2048-4096
([64,128,2048]: 165 vs 225 us; [32,128,4096]: 143 vs 214 us) — the
backward's recompute-in-VMEM strategy beats XLA's saved-temporaries
autodiff, which drops to ~150 GB/s. Forward-only, XLA wins everywhere
(1.56-1.92x, sustaining 640-825 GB/s). Boundary: at C=8192 the bwd
kernel's [N-block, S, C] tile exceeds the 16 MB scoped VMEM and fails
to compile — use ``"gn"`` past ~4k channels.

Layout: public API [..., S, C] with ``groups`` dividing C (the caller
flattens spatial dims; models.resnet.Norm does the NHWC reshape).
Internally [N, S, C]: grid over N-blocks, each block resident in VMEM.
Stats are f32 regardless of input dtype (same numerics as flax
``nn.GroupNorm``: normalize in f32, cast on output). Backward is a
single kernel producing dx and accumulating dscale/dbias across the
sequential grid in VMEM scratch (written on the last step) — the TPU
idiom for cross-block reductions.

On non-TPU backends the kernels run in interpreter mode (CPU-mesh
testable); equivalence vs ``nn.GroupNorm`` is pinned in
tests/test_group_norm.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fedml_tpu.parallel.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_n(n: int, s: int, c: int, budget_bytes: int = 1 << 19) -> int:
    """Largest divisor of n whose [bn, S, C] f32 block fits the VMEM
    budget. The budget is PER BUFFER: the kernels hold ~6-8 f32-sized
    live temporaries (x cast, x², xhat, dxhat, products, output), so
    512 KB/buffer keeps the scoped-vmem stack a few MB under the 16 MB
    limit (measured: a 4 MB/buffer budget OOM'd at 31 MB on v5e)."""
    per = s * c * 4
    want = max(1, budget_bytes // max(per, 1))
    for bn in range(min(want, n), 0, -1):
        if n % bn == 0:
            return bn
    return 1


def _group_mats(c, groups):
    """[C, G] 0/1 indicator and its transpose, built with iota — group
    reductions become matmuls (MXU) instead of lane-splitting reshapes,
    which Mosaic lowers badly (observed: compile stall on v5e for the
    [bn, S, G, C/G] reshape formulation)."""
    ci = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0)
    gi = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    return (ci // (c // groups) == gi).astype(jnp.float32)


def _stats_per_channel(x32, groups):
    """Per-(sample, channel) group mean/var broadcast back to channels:
    ([bn, C], [bn, C]) f32 — each channel carries ITS group's stats."""
    bn, s, c = x32.shape
    m = _group_mats(c, groups)          # [C, G]
    denom = s * (c // groups)
    sum_c = jnp.sum(x32, axis=1)        # [bn, C]
    sumsq_c = jnp.sum(x32 * x32, axis=1)
    mu = ((sum_c @ m) @ m.T) / denom    # [bn, C], group-pooled
    ex2 = ((sumsq_c @ m) @ m.T) / denom
    # Clamp like flax's _compute_stats: E[x^2] - mu^2 can cancel below
    # zero for near-constant inputs, and rsqrt(var + eps) would NaN.
    return mu, jnp.maximum(ex2 - mu * mu, 0.0)


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, groups, eps):
    # g_ref/b_ref are [1, C]: TPU block shapes must have their last two
    # dims (8,128)-divisible OR equal to the array dims — a bare [C] with
    # C<128 becomes an illegal (1, C) block once vmap batching inserts a
    # leading grid dim (observed on v5e; interpreter mode does not check).
    x = x_ref[...].astype(jnp.float32)
    mu, var = _stats_per_channel(x, groups)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mu[:, None, :]) * rstd[:, None, :]
    y = y * g_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, g_ref, dx_ref, dg_ref, db_ref,
                dg_acc, db_acc, *, groups, eps):
    i, n_i = pl.program_id(0), pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        dg_acc[...] = jnp.zeros_like(dg_acc)
        db_acc[...] = jnp.zeros_like(db_acc)

    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[0].astype(jnp.float32)  # [C]
    bn, s, c = x.shape
    mu, var = _stats_per_channel(x, groups)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu[:, None, :]) * rstd[:, None, :]      # [bn, S, C]

    db_acc[...] += jnp.sum(dy, axis=(0, 1))[None]
    dg_acc[...] += jnp.sum(dy * xhat, axis=(0, 1))[None]

    dxhat = dy * gamma[None, None, :]
    mm = _group_mats(c, groups)
    denom = s * (c // groups)
    # group means of dxhat and dxhat*xhat, broadcast back per channel
    mean_dxhat = ((jnp.sum(dxhat, axis=1) @ mm) @ mm.T) / denom
    mean_dxhat_xhat = ((jnp.sum(dxhat * xhat, axis=1) @ mm) @ mm.T) / denom
    dx = rstd[:, None, :] * (dxhat
                             - mean_dxhat[:, None, :]
                             - xhat * mean_dxhat_xhat[:, None, :])
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(i == n_i - 1)
    def _finalize():
        dg_ref[...] = dg_acc[...]
        db_ref[...] = db_acc[...]


def _fwd(x3, gamma, beta, groups, eps):
    n, s, c = x3.shape
    bn = _block_n(n, s, c)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, groups=groups, eps=eps),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, s, c), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, s, c), x3.dtype),
        interpret=_interpret(),
    )(x3, gamma.reshape(1, c), beta.reshape(1, c))


def _bwd(x3, dy3, gamma, groups, eps):
    n, s, c = x3.shape
    bn = _block_n(n, s, c)
    dims = _CompilerParams(dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, groups=groups, eps=eps),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((bn, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, s, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, s, c), x3.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),
            pltpu.VMEM((1, c), jnp.float32),
        ],
        compiler_params=dims,
        interpret=_interpret(),
    )(x3, dy3, gamma.reshape(1, c))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _gn(x3, gamma, beta, groups, eps):
    return _fwd(x3, gamma, beta, groups, eps)


def _gn_fwd(x3, gamma, beta, groups, eps):
    return _fwd(x3, gamma, beta, groups, eps), (x3, gamma)


def _gn_bwd(groups, eps, res, dy3):
    x3, gamma = res
    dx, dg, db = _bwd(x3, dy3, gamma, groups, eps)
    return (dx, dg.reshape(gamma.shape).astype(gamma.dtype),
            db.reshape(gamma.shape).astype(gamma.dtype))


_gn.defvjp(_gn_fwd, _gn_bwd)


def group_norm(x, gamma, beta, groups: int, eps: float = 1e-6):
    """Fused GroupNorm: x [..., C] → same shape; gamma/beta [C].

    All leading dims are flattened to [N, S, C] with S the second-to-last
    dim (callers pass [N, H*W, C] or [N*H*W, 1, C]-style layouts; the
    models flatten NHWC spatial dims). ``groups`` must divide C. Stats
    and normalization are f32 (flax ``nn.GroupNorm`` numerics); output in
    x's dtype. Differentiable via a fused backward kernel.
    """
    c = x.shape[-1]
    if c % groups:
        raise ValueError(f"groups {groups} must divide channels {c}")
    orig = x.shape
    if x.ndim == 1:
        x3 = x.reshape(1, 1, c)
    elif x.ndim == 2:
        x3 = x[:, None, :]  # per-sample over channel groups only
    else:
        # normalization is per leading-sample over ALL non-channel dims:
        # [N, prod(middle), C]
        x3 = x.reshape(orig[0], -1, c)
    out = _gn(x3, gamma, beta, groups, eps)
    return out.reshape(orig)
