"""Flash attention as pallas TPU kernels (single-chip hot path).

Fused blockwise attention with streaming softmax: the [T, T] score matrix
is never materialized and VMEM usage is block-sized regardless of sequence
length. Forward stores only the output and row log-sum-exp; backward
recomputes probabilities blockwise (FlashAttention-2 style: dP = dO·Vᵀ,
dS = P∘(dP − δ), δ = rowsum(dO∘O)) in three kernels (fwd, dq, dkv) wired
through ``jax.custom_vjp``.

Kernel structure (the TPU-idiomatic pattern): 3-D grid with the
contraction block dim INNERMOST — TPU grids iterate sequentially over the
last dimension, so VMEM scratch accumulators carry across it; the kernel
initializes scratch on the first inner step and writes the output block on
the last. K/V stream through VMEM one block per step (HBM→VMEM pipelined
by pallas), which is what keeps T=64k+ within the 16 MB VMEM budget.

Layout: [B, T, H, D] public API (matching
fedml_tpu.parallel.ring_attention), flattened to [B*H, T, D]; the
log-sum-exp / delta vectors are stored [B*H, 8, T] (8 identical sublanes)
to satisfy the TPU (8, 128) tiling rule for 1-D-per-row outputs. On
non-TPU backends the kernels run in interpreter mode so the same code path
is testable on the CPU mesh; composes under ring attention as the
per-shard computation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_SUB = 8  # sublane replication for per-row vectors

from fedml_tpu.parallel.compat import pallas_tpu_compiler_params

_CompilerParams = pallas_tpu_compiler_params()

# Grid = (batch·heads, outer block dim, contraction block dim). Only the
# innermost (contraction) dim is sequential — scratch accumulators carry
# across it; telling Mosaic the outer two are parallel frees its scheduler.
_DIMS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _blk(t: int, want: int = 128) -> int:
    return min(want, t)


def _auto_blk(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is ≤ ``want`` and sublane-aligned
    (multiple of 8) — default block sizes must accept every T the old
    fixed-128 defaults accepted (e.g. T=384 → 192, not a ValueError)."""
    if t <= want:
        return t
    for b in range(want, 7, -1):
        if t % b == 0 and b % 8 == 0:
            return b
    return t  # no aligned divisor ≤ want: single block


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Forward: grid (bh, n_q, n_k), scratch carries (acc, m, l) across n_k
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s, *,
                scale, causal, blk_q, blk_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    # Causal: blocks entirely above the diagonal contribute nothing.
    diag_ok = (qi + 1) * blk_q > ki * blk_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        # Dots run in the INPUT dtype (bf16 stays bf16 on the MXU — ~4x the
        # fp32 matmul rate) with f32 accumulation via preferred_element_type;
        # softmax statistics stay f32.
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = _dot(q, k, (((1,), (1,)))) * scale  # [blk_q, blk_k] f32
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, 1), 0)
            k_pos = ki * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev, l_prev = m_s[...], l_s[...]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        c = jnp.exp(m_prev - m_new)
        m_s[...] = m_new
        l_s[...] = l_prev * c + jnp.sum(p, axis=1, keepdims=True)
        acc[...] = acc[...] * c + _dot(p.astype(v.dtype), v, ((1,), (0,)))

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_s[...]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc[...] / l_safe).astype(o_ref.dtype)
        lse = (m_s[...] + jnp.log(l_safe))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (_SUB, blk_q))


def _fwd(q3, k3, v3, scale, causal, blk_q, blk_k):
    bh, t, d = q3.shape
    grid = (bh, t // blk_q, t // blk_k)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, _SUB, blk_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, _SUB, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        compiler_params=_DIMS,
        interpret=_interpret(),
    )(q3, k3, v3)


# ---------------------------------------------------------------------------
# Backward dq: grid (bh, n_q, n_k), dq accumulates across n_k
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc, *,
               scale, causal, blk_q, blk_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    diag_ok = (qi + 1) * blk_q > ki * blk_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        q, do = q_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        k, v = k_ref[0], v_ref[0]
        s = _dot(q, k, ((1,), (1,))) * scale
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, 1), 0)
            k_pos = ki * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        ds = (p * (dp - delta)).astype(q.dtype)
        acc[...] += _dot(ds, k, ((1,), (0,))) * scale

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# Backward dk/dv: grid (bh, n_k, n_q), dk/dv accumulate across n_q
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale, causal, blk_q, blk_k):
    ki, qi = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    diag_ok = (qi + 1) * blk_q > ki * blk_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        k, v = k_ref[0], v_ref[0]
        q, do = q_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = _dot(q, k, ((1,), (1,))) * scale
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, 1), 0)
            k_pos = ki * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [blk_q, blk_k] f32
        dv_acc[...] += _dot(p.astype(do.dtype), do, ((0,), (0,)))
        dp = _dot(do, v, ((1,), (1,)))
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += _dot(ds, q, ((0,), (0,))) * scale

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, lse, do3, scale, causal, blk_q, blk_k):
    bh, t, d = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, _SUB, t))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(bh, t // blk_q, t // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, _SUB, blk_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, _SUB, blk_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        compiler_params=_DIMS,
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(bh, t // blk_k, t // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, _SUB, blk_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, _SUB, blk_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, d), jnp.float32),
            pltpu.VMEM((blk_k, d), jnp.float32),
        ],
        compiler_params=_DIMS,
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q3, k3, v3, causal, blocks):
    blk_q, blk_k = blocks[:2]
    scale = 1.0 / (q3.shape[-1] ** 0.5)
    o, _ = _fwd(q3, k3, v3, scale, causal, blk_q, blk_k)
    return o


def _flash_fwd(q3, k3, v3, causal, blocks):
    blk_q, blk_k = blocks[:2]
    scale = 1.0 / (q3.shape[-1] ** 0.5)
    o, lse = _fwd(q3, k3, v3, scale, causal, blk_q, blk_k)
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(causal, blocks, res, do3):
    q3, k3, v3, o3, lse = res
    bwd_blk_q, bwd_blk_k = blocks[2:]
    scale = 1.0 / (q3.shape[-1] ** 0.5)
    return _bwd(q3, k3, v3, o3, lse, do3, scale, causal,
                bwd_blk_q, bwd_blk_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int | None = None,
                    block_k: int | None = None,
                    bwd_block_q: int | None = None,
                    bwd_block_k: int | None = None):
    """Fused attention: q/k/v [B, T, H, D] → o [B, T, H, D].

    T must be a multiple of the (clamped) block sizes; pad upstream if not.
    Differentiable (custom VJP, FlashAttention-2-style backward).

    Default blocks are (512, 1024) at every T (clamped to divisors of
    T): the r4 re-sweep with floor-calibrated timing
    (scripts/sweep_flash_bwd.py + the fwd confirm sweep, v5e,
    2026-07-31) measures (512, 1024) ahead of the r3-era (256, 512)
    default at EVERY point — fwd +39% @ T=2048, +81% @ 4096; training
    +27% / +42% — the r3 "small blocks win at short T" conclusion was an
    artifact of RTT-polluted timing (each r3 call carried ~0.1 s of
    tunnel dispatch in a ~0.15 s measurement). The three backward
    kernels take their own block sizes (``bwd_block_q/k``, defaulting to
    the forward pair — best-of-sweep for training at T ∈ {4096, 8192});
    pass explicit blocks to override. For the MXU rate, feed bf16
    q/k/v: the kernel dots run in the input dtype (f32 accumulation),
    and bf16 is ~4x the fp32 matmul rate.
    """
    b, t, h, d = q.shape
    if block_q is None:
        block_q = _auto_blk(t, 512)
    if block_k is None:
        block_k = _auto_blk(t, 1024)
    blk_q = _blk(t, block_q)
    blk_k = _blk(t, block_k)
    bwd_q = _blk(t, bwd_block_q) if bwd_block_q else blk_q
    bwd_k = _blk(t, bwd_block_k) if bwd_block_k else blk_k
    for bq, bk in ((blk_q, blk_k), (bwd_q, bwd_k)):
        if t % bq or t % bk:
            raise ValueError(
                f"sequence length {t} must be a multiple of block sizes "
                f"({bq}, {bk}); pad the sequence")

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    o3 = _flash(to3(q), to3(k), to3(v), causal, (blk_q, blk_k, bwd_q, bwd_k))
    return o3.reshape(b, h, t, d).transpose(0, 2, 1, 3)
