// msgnet: length-prefixed TCP message transport for cross-silo federation.
//
// The native runtime layer filling the role the reference reaches through
// gRPC C-core / MPI / TensorPipe (SURVEY.md §2.1, §2.9): each rank runs a
// server socket accepting framed messages into an internal queue
// (mutex+condvar, event-driven — no 0.3 s polling like the reference's MPI
// manager, mpi/com_manager.py:78), and sends through cached client
// connections. Framing: [uint64 LE length][payload bytes].
//
// C API (ctypes-friendly): every function is exported with C linkage and
// plain int/pointer types. Thread-safe. No Python dependency.
//
// Build: g++ -O2 -fPIC -shared -pthread msgnet.cpp -o libmsgnet.so

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Frame {
  std::vector<uint8_t> data;
};

// Read exactly n bytes; false on EOF/error.
bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;  // live connection sockets (for stop())
  std::mutex conn_mu;         // guards conn_threads + conn_fds

  std::mutex mu;
  std::condition_variable cv;
  std::deque<Frame> queue;
  // Bound the queue so a stalled consumer back-pressures instead of
  // OOMing the host (the reference has no bound at all).
  size_t max_queue = 4096;
  // In-flight recv() calls; stop() must not let the object be destroyed
  // while another thread is blocked inside recv (use-after-free).
  int active_recvs = 0;

  ~Server() { stop(); }

  bool start(int port_, int backlog) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(listen_fd);
      listen_fd = -1;
      return false;
    }
    if (port_ == 0) {  // ephemeral: report the bound port
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    }
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, backlog) != 0) {
      ::close(listen_fd);
      listen_fd = -1;
      return false;
    }
    running = true;
    accept_thread = std::thread([this] { accept_loop(); });
    return true;
  }

  void accept_loop() {
    while (running) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (!running) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] { conn_loop(fd); });
    }
  }

  void conn_loop(int fd) {
    while (running) {
      uint64_t len_le = 0;
      if (!read_exact(fd, &len_le, sizeof(len_le))) break;
      uint64_t len = le64toh(len_le);
      // 4 GiB frame cap: a corrupt length must not drive a huge alloc.
      if (len > (uint64_t(1) << 32)) break;
      Frame f;
      f.data.resize(len);
      if (len > 0 && !read_exact(fd, f.data.data(), len)) break;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return queue.size() < max_queue || !running; });
        if (!running) break;
        queue.push_back(std::move(f));
      }
      cv.notify_all();
    }
    // Deregister BEFORE closing: once closed the kernel may recycle this fd
    // number for an unrelated socket, and stop()'s shutdown(fd) would sever
    // that stranger's connection.
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_fds.erase(std::remove(conn_fds.begin(), conn_fds.end(), fd),
                     conn_fds.end());
    }
    ::close(fd);
  }

  // Returns malloc'd buffer (caller frees via mn_free) or nullptr on
  // timeout/stop. timeout_ms < 0 = block forever.
  // Register an in-flight recv. MUST be called under g_mu (before the
  // Server* escapes the handle map) so stop()+delete cannot slip between
  // the map lookup and the increment (TOCTOU use-after-free).
  void acquire_recv() {
    std::lock_guard<std::mutex> lk(mu);
    ++active_recvs;
  }

  // Caller must have called acquire_recv().
  uint8_t* recv(int timeout_ms, uint64_t* out_len) {
    std::unique_lock<std::mutex> lk(mu);
    auto ready = [this] { return !queue.empty() || !running; };
    bool have = true;
    if (timeout_ms < 0) {
      cv.wait(lk, ready);
    } else if (!cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready)) {
      have = false;
    }
    uint8_t* buf = nullptr;
    if (have && !queue.empty()) {
      Frame f = std::move(queue.front());
      queue.pop_front();
      buf = static_cast<uint8_t*>(::malloc(f.data.size() ? f.data.size() : 1));
      if (buf) {
        std::memcpy(buf, f.data.data(), f.data.size());
        *out_len = f.data.size();
      }
    }
    --active_recvs;
    // Notify while still holding mu: once we unlock with active_recvs==0 a
    // waiting stop() may return and the object be deleted — notifying after
    // unlock would touch a freed condition_variable.
    cv.notify_all();  // wake back-pressured producers and a waiting stop()
    lk.unlock();
    return buf;
  }

  void stop() {
    if (!running.exchange(false)) return;
    cv.notify_all();
    ::shutdown(listen_fd, SHUT_RDWR);
    ::close(listen_fd);
    if (accept_thread.joinable()) accept_thread.join();
    // Unblock conn threads stuck in recv() on still-open peer connections,
    // then join them OUTSIDE conn_mu — an exiting conn thread takes conn_mu
    // to deregister its fd, so joining under the lock would deadlock.
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
      conn_fds.clear();
      to_join.swap(conn_threads);
    }
    for (auto& t : to_join)
      if (t.joinable()) t.join();
    // Drain in-flight recv() calls before the destructor can run.
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return active_recvs == 0; });
  }
};

struct Sender {
  std::mutex mu;
  std::map<std::pair<std::string, int>, int> conns;  // (host,port) -> fd

  ~Sender() {
    for (auto& kv : conns) ::close(kv.second);
  }

  int connect_to(const std::string& host, int port) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res) != 0)
      return -1;
    int fd = -1;
    for (auto* rp = res; rp; rp = rp->ai_next) {
      fd = ::socket(rp->ai_family, rp->ai_socktype, rp->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, rp->ai_addr, rp->ai_addrlen) == 0) break;
      ::close(fd);
      fd = -1;
    }
    ::freeaddrinfo(res);
    if (fd >= 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    return fd;
  }

  // 0 on success, -1 on failure (after one reconnect attempt — a cached
  // connection may have been closed by the peer).
  int send(const std::string& host, int port, const uint8_t* data, uint64_t len) {
    std::lock_guard<std::mutex> lk(mu);
    auto key = std::make_pair(host, port);
    for (int attempt = 0; attempt < 2; ++attempt) {
      auto it = conns.find(key);
      int fd;
      if (it == conns.end()) {
        fd = connect_to(host, port);
        if (fd < 0) return -1;
        conns[key] = fd;
      } else {
        fd = it->second;
      }
      uint64_t len_le = htole64(len);
      if (write_exact(fd, &len_le, sizeof(len_le)) &&
          write_exact(fd, data, len)) {
        return 0;
      }
      ::close(fd);
      conns.erase(key);
    }
    return -1;
  }
};

std::mutex g_mu;
std::map<int, Server*> g_servers;
std::map<int, Sender*> g_senders;
int g_next = 1;

}  // namespace

extern "C" {

// Create a server listening on `port` (0 = ephemeral). Returns handle > 0
// or -1.
int mn_server_create(int port, int backlog) {
  auto* s = new Server();
  if (!s->start(port, backlog > 0 ? backlog : 128)) {
    delete s;
    return -1;
  }
  std::lock_guard<std::mutex> lk(g_mu);
  int h = g_next++;
  g_servers[h] = s;
  return h;
}

int mn_server_port(int handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_servers.find(handle);
  return it == g_servers.end() ? -1 : it->second->port;
}

// Blocking receive; returns malloc'd buffer (free with mn_free) or NULL.
uint8_t* mn_server_recv(int handle, int timeout_ms, uint64_t* out_len) {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_servers.find(handle);
    if (it == g_servers.end()) return nullptr;
    s = it->second;
    s->acquire_recv();  // under g_mu: stop() cannot delete s before this
  }
  return s->recv(timeout_ms, out_len);
}

void mn_server_stop(int handle) {
  Server* s = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_servers.find(handle);
    if (it != g_servers.end()) {
      s = it->second;
      g_servers.erase(it);
    }
  }
  if (s) {
    s->stop();
    delete s;
  }
}

int mn_sender_create() {
  std::lock_guard<std::mutex> lk(g_mu);
  int h = g_next++;
  g_senders[h] = new Sender();
  return h;
}

int mn_send(int handle, const char* host, int port, const uint8_t* data,
            uint64_t len) {
  Sender* s;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_senders.find(handle);
    if (it == g_senders.end()) return -1;
    s = it->second;
  }
  return s->send(host, port, data, len);
}

void mn_sender_destroy(int handle) {
  Sender* s = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_senders.find(handle);
    if (it != g_senders.end()) {
      s = it->second;
      g_senders.erase(it);
    }
  }
  delete s;
}

void mn_free(uint8_t* buf) { ::free(buf); }

}  // extern "C"
