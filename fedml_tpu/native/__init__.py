"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes — no pybind11 dependency.

``load_msgnet()`` compiles ``msgnet.cpp`` once (cached as
``_build/libmsgnet.so``, keyed on source mtime) and returns the ctypes
library with argtypes configured.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()
_LIB = None


def _compile(src: str, out: str):
    os.makedirs(_BUILD, exist_ok=True)
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-pthread", "-std=c++17",
        src, "-o", out,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr[-4000:]}"
        )


def build_stress(sanitize: str = "thread") -> str:
    """Build the msgnet stress binary (linked with the transport sources)
    with a sanitizer — the race-detection harness. Returns the binary path."""
    os.makedirs(_BUILD, exist_ok=True)
    out = os.path.join(_BUILD, f"msgnet_stress_{sanitize}")
    srcs = [os.path.join(_HERE, "msgnet.cpp"), os.path.join(_HERE, "msgnet_stress.cpp")]
    newest = max(os.path.getmtime(s) for s in srcs)
    if os.path.isfile(out) and os.path.getmtime(out) >= newest:
        return out
    cmd = ["g++", "-O1", "-g", "-pthread", "-std=c++17",
           f"-fsanitize={sanitize}", *srcs, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"stress build failed: {' '.join(cmd)}\n{proc.stderr[-4000:]}")
    return out


def load_msgnet() -> ctypes.CDLL:
    """Build (if stale) + load the message-transport library."""
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.join(_HERE, "msgnet.cpp")
        out = os.path.join(_BUILD, "libmsgnet.so")
        if not os.path.isfile(out) or os.path.getmtime(out) < os.path.getmtime(src):
            _compile(src, out)
        lib = ctypes.CDLL(out)
        lib.mn_server_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.mn_server_create.restype = ctypes.c_int
        lib.mn_server_port.argtypes = [ctypes.c_int]
        lib.mn_server_port.restype = ctypes.c_int
        lib.mn_server_recv.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.mn_server_recv.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.mn_server_stop.argtypes = [ctypes.c_int]
        lib.mn_sender_create.restype = ctypes.c_int
        # data as c_char_p: a Python bytes object passes zero-copy (the C
        # side takes const uint8* + explicit length; embedded NULs are fine).
        lib.mn_send.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.mn_send.restype = ctypes.c_int
        lib.mn_sender_destroy.argtypes = [ctypes.c_int]
        lib.mn_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _LIB = lib
        return lib
