// msgnet_stress: concurrency stress harness for the msgnet transport.
//
// Race detection the reference never had (SURVEY.md §5 — its concurrency
// is hand-managed threads with no sanitizers). Built with
// -fsanitize=thread by fedml_tpu.native.build_stress() and run in CI: N
// sender threads hammer M servers while receivers drain and the main
// thread tears everything down mid-flight — exercising the accept/conn/
// recv/stop lifecycle under TSAN. Exit 0 = no data races detected.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int mn_server_create(int port, int backlog);
int mn_server_port(int handle);
uint8_t* mn_server_recv(int handle, int timeout_ms, uint64_t* out_len);
void mn_server_stop(int handle);
int mn_sender_create();
int mn_send(int handle, const char* host, int port, const uint8_t* data,
            uint64_t len);
void mn_sender_destroy(int handle);
void mn_free(uint8_t* buf);
}

int main() {
  constexpr int kServers = 3;
  constexpr int kSendersPerServer = 4;
  constexpr int kMsgs = 200;

  int handles[kServers], ports[kServers];
  for (int s = 0; s < kServers; ++s) {
    handles[s] = mn_server_create(0, 64);
    if (handles[s] < 0) return 2;
    ports[s] = mn_server_port(handles[s]);
  }

  std::atomic<long> received{0};
  std::atomic<bool> give_up{false};
  std::vector<std::thread> threads;

  // Deadline watchdog: on message loss the receivers must still exit so the
  // final count check can report exit 3 instead of hanging the harness.
  std::thread watchdog([&] {
    for (int i = 0; i < 600 && !give_up; ++i) {  // 60 s budget
      if (received.load() >= long(kServers) * kSendersPerServer * kMsgs) return;
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    give_up = true;
  });

  // Receivers: several concurrent drainers per server (stresses the
  // recv/stop refcount path).
  for (int s = 0; s < kServers; ++s) {
    for (int r = 0; r < 2; ++r) {
      threads.emplace_back([&, s] {
        uint64_t len;
        while (!give_up) {
          uint8_t* buf = mn_server_recv(handles[s], 50, &len);
          if (buf) {
            received.fetch_add(1);
            mn_free(buf);
          } else if (received.load() >= kServers * kSendersPerServer * kMsgs) {
            return;
          }
        }
      });
    }
  }

  // Senders.
  for (int s = 0; s < kServers; ++s) {
    for (int w = 0; w < kSendersPerServer; ++w) {
      threads.emplace_back([&, s, w] {
        int snd = mn_sender_create();
        std::string payload(128 + 64 * w, 'x');
        for (int i = 0; i < kMsgs; ++i) {
          if (mn_send(snd, "127.0.0.1", ports[s],
                      reinterpret_cast<const uint8_t*>(payload.data()),
                      payload.size()) != 0) {
            std::fprintf(stderr, "send failed\n");
            break;
          }
        }
        mn_sender_destroy(snd);
      });
    }
  }

  for (auto& t : threads) t.join();
  give_up = true;
  watchdog.join();

  // Teardown while a late receiver is still mid-recv: spawn one more
  // blocked receiver, then stop the servers under it.
  std::thread late([&] {
    uint64_t len;
    uint8_t* buf = mn_server_recv(handles[0], 5000, &len);
    if (buf) mn_free(buf);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  for (int s = 0; s < kServers; ++s) mn_server_stop(handles[s]);
  late.join();

  long got = received.load();
  if (got != long(kServers) * kSendersPerServer * kMsgs) {
    std::fprintf(stderr, "lost messages: %ld\n", got);
    return 3;
  }
  std::printf("stress ok: %ld messages\n", got);
  return 0;
}
