"""Local (on-client) training as a jit-compiled ``lax.scan``.

Replaces the reference's per-client Python epoch/batch loop
(fedml_api/distributed/fedavg/MyModelTrainer.py:19-49 — HOT LOOP #3 in
SURVEY.md §3.1). One ``local_train`` call runs ``epochs × steps`` SGD steps
with static shapes; ``vmap`` over the leading client axis turns the
reference's sequential client for-loop
(fedml_api/standalone/fedavg/fedavg_api.py:58-66) into one batched XLA
program whose matmuls keep the MXU busy across clients.

Model state (BatchNorm running stats etc.) travels with the parameters in a
``NetState`` pytree: the reference ships the full ``state_dict`` (params +
BN buffers) over MPI and averages everything (FedAVGAggregator.py:74-82); we
do the same by weighted-averaging the whole ``NetState``.

The reference re-creates the client optimizer every round
(MyModelTrainer.py:26-31) — we mirror that deliberately (``optimizer.init``
inside ``local_train``), so Adam state does NOT persist across rounds, same
as the reference.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from fedml_tpu.core.tree import tree_select


@struct.dataclass
class NetState:
    """Model parameters + non-trainable collections (batch_stats, ...)."""

    params: Any
    model_state: Any  # {} when the model has no mutable collections


class ModelFns(NamedTuple):
    """Functional model interface (the reference's ModelTrainer ABC,
    fedml_core/trainer/model_trainer.py:4-38, reduced to pure functions)."""

    init: Callable  # (rng, sample_x) -> NetState
    apply: Callable  # (net, x, train, rng) -> (logits, new_model_state)


def model_fns(module) -> ModelFns:
    """Wrap a flax.linen module (taking a ``train`` kwarg) into ModelFns."""

    def init(rng, sample_x) -> NetState:
        variables = module.init({"params": rng}, sample_x, train=False)
        params = variables["params"]
        state = {k: v for k, v in variables.items() if k != "params"}
        return NetState(params=params, model_state=state)

    def apply(net: NetState, x, train=False, rng=None):
        variables = {"params": net.params, **net.model_state}
        rngs = {"dropout": rng} if (train and rng is not None) else None
        mutable = list(net.model_state.keys()) if (train and net.model_state) else False
        if mutable:
            logits, new_state = module.apply(
                variables, x, train=train, rngs=rngs, mutable=mutable
            )
            return logits, dict(new_state)
        logits = module.apply(variables, x, train=train, rngs=rngs)
        return logits, net.model_state

    return ModelFns(init=init, apply=apply)


def make_client_optimizer(name: str, lr: float, wd: float = 0.0, grad_clip: float = 0.0):
    """Client optimizers matching the reference's choices
    (MyModelTrainer.py:26-31): plain SGD, or Adam with weight decay +
    amsgrad. ``momentum`` added as a TPU-era convenience. ``grad_clip`` > 0
    prepends global-norm clipping (fed_launch/main.py grad-clipping flag)."""
    if name == "sgd":
        opt = optax.sgd(lr)
    elif name == "momentum":
        opt = optax.sgd(lr, momentum=0.9)
    elif name == "adam":
        # Coupled L2 (decay added to the gradient BEFORE the amsgrad
        # preconditioner) — matches torch.optim.Adam(weight_decay=wd,
        # amsgrad=True) as used by the reference, not AdamW.
        opt = optax.chain(
            optax.add_decayed_weights(wd),
            optax.scale_by_amsgrad(),
            optax.scale(-lr),
        )
    else:
        raise ValueError(f"unknown client optimizer {name!r}")
    if grad_clip and grad_clip > 0:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def softmax_ce(logits, labels):
    """Per-example softmax cross-entropy with integer labels."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def seq_softmax_ce(logits, labels, pad_id: int = 0):
    """Per-example next-token CE for sequence models: ``logits [B, T, V]``,
    ``labels [B, T]``; mean over non-pad positions. Used by the Shakespeare /
    StackOverflow LSTM tasks (the reference masks padding in its
    language_utils)."""
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    tok_mask = (labels != pad_id).astype(per_tok.dtype)
    denom = jnp.maximum(tok_mask.sum(axis=-1), 1.0)
    return (per_tok * tok_mask).sum(axis=-1) / denom


def make_epoch_shuffle(mask, epoch_rng):
    """Per-epoch reshuffle closure over ``[S, B, ...]`` packed arrays
    (DataLoader(shuffle=True) semantics). REAL samples are permuted amongst
    themselves and padding stays at the tail (argsort of random keys offset
    by the mask), so trailing steps remain all-masked no-ops: the per-client
    optimizer-step count stays exactly ``epochs x ceil(n_i/B)`` (FedNova's τ
    depends on this) and at most one batch per epoch mixes real samples
    with padding. Returns ``reshuffle(a)`` applicable to every per-sample
    array of the pack (x, y, mask, teacher logits, ...).

    The per-slot keys are drawn PREFIX-STABLY — slot ``i``'s key depends
    only on ``(epoch_rng, i)``, via fold_in, never on the total slot
    count (a single batched ``uniform(epoch_rng, (S*B,))`` draw would
    change EVERY key when S changes). This is what makes a larger forced
    step bucket an exact training no-op: the real samples draw the same
    keys, so they permute identically, and the extra pad slots (copies of
    the client's first sample, masked) extend only the tail. The windowed
    execution tier (``FedAvgAPI.train_rounds_windowed``) forces a shared
    per-window bucket and leans on exactly this property for its
    bit-equality with the per-round host loop."""
    n_steps, batch = mask.shape[0], mask.shape[1]
    flat_mask = mask.reshape(n_steps * batch)
    keys = jax.vmap(
        lambda i: jax.random.uniform(jax.random.fold_in(epoch_rng, i))
    )(jnp.arange(n_steps * batch))
    # Padded slots get keys > 1 so argsort sends them to the tail.
    perm = jnp.argsort(keys + (1.0 - flat_mask) * 2.0)

    def reshuffle(a):
        flat = a.reshape((n_steps * batch,) + a.shape[2:])
        return jnp.take(flat, perm, axis=0).reshape(a.shape)

    return reshuffle


def _dp_batch_grad(apply_fn, loss_fn, net, xb, yb, mb, rng, noise_rng,
                   clip, noise_multiplier, remat):
    """One DP-SGD gradient: per-example grads (vmap), per-example L2 clip
    to ``clip``, masked sum, Gaussian noise ``N(0, (z*clip)^2)`` per
    parameter on the sum, normalized by the real-sample count. Returns
    (masked mean loss, unchanged model_state, noisy mean grad)."""

    def example_loss(p, xe, ye, key):
        logits, _ = apply_fn(
            NetState(p, net.model_state), xe[None], train=True, rng=key
        )
        return loss_fn(logits, ye[None])[0]

    if remat:  # wrap BEFORE differentiation or no rematerialization happens
        example_loss = jax.checkpoint(example_loss)
    grad_one = jax.value_and_grad(example_loss)
    # Per-example dropout keys: one shared key would correlate the dropout
    # masks of every example in the batch.
    keys = jax.random.split(rng, xb.shape[0])
    losses, per_grads = jax.vmap(grad_one, in_axes=(None, 0, 0, 0))(
        net.params, xb, yb, keys
    )

    # Clip each example's gradient to L2 norm ``clip``; masked examples
    # contribute zero.
    sq = sum(
        jnp.sum(jnp.square(g), axis=tuple(range(1, g.ndim)))
        for g in jax.tree.leaves(per_grads)
    )
    scale = jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(sq), 1e-12)) * mb

    def reduce_leaf(g, key):
        summed = jnp.tensordot(scale, g, axes=(0, 0))
        if noise_multiplier and noise_multiplier > 0:
            summed = summed + noise_multiplier * clip * jax.random.normal(
                key, summed.shape, summed.dtype
            )
        return summed

    leaves, treedef = jax.tree.flatten(per_grads)
    keys = jax.random.split(noise_rng, len(leaves))
    denom = jnp.maximum(jnp.sum(mb), 1.0)
    grads = jax.tree.unflatten(
        treedef, [reduce_leaf(g, k) / denom for g, k in zip(leaves, keys)]
    )
    loss = jnp.sum(losses * mb) / denom
    return loss, net.model_state, grads


def make_corrected_local_train(apply_fn, local_epochs: int, loss_fn,
                               step_update, remat: bool = False,
                               with_step_count: bool = False):
    """Shared corrected-SGD client trainer for algorithms whose per-step
    update needs per-client inputs the generic ``extra_grad_fn`` hook
    cannot carry (SCAFFOLD's control variates, FedDyn's dynamic
    regularizer). ``step_update(params, grads, aux) -> params'`` applies
    the algorithm's correction; ``aux`` is an arbitrary per-client pytree
    the caller vmaps over. Masking / per-epoch reshuffle / gated no-op
    padded steps mirror :func:`make_local_train_fn` exactly.

    Returns ``local_train(net, aux, x, y, mask, rng) -> (net', loss)``,
    plus the true optimizer-step count K when ``with_step_count`` (padded
    trailing batches are no-op steps, so K = epochs x non-empty steps)."""

    def local_train(net: "NetState", aux, x, y, mask, rng):
        def step(carry, inputs):
            net, step_base = carry
            xb, yb, mb, idx = inputs
            # EXACTLY make_local_train_fn's per-step key derivation (the
            # prefix-stable fold_in discipline): the "first round with
            # zero corrections == plain FedAvg" equivalences (SCAFFOLD,
            # FedDyn) hold bit-wise only while the two trainers draw
            # identical streams.
            sub = jax.random.fold_in(jax.random.fold_in(step_base, idx), 0)

            def masked_loss(p):
                logits, new_state = apply_fn(
                    NetState(p, net.model_state), xb, train=True, rng=sub)
                per = loss_fn(logits, yb)
                return (jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0),
                        new_state)

            if remat:
                masked_loss = jax.checkpoint(masked_loss)
            (loss, new_state), grads = jax.value_and_grad(
                masked_loss, has_aux=True)(net.params)
            new_params = step_update(net.params, grads, aux)
            nb = jnp.sum(mb)
            new_net = tree_select(nb > 0, NetState(new_params, new_state),
                                  net)
            return (new_net, step_base), (loss, nb)

        def epoch(carry, epoch_rng):
            # Same fold_in(·, 0)/(·, 1) forks as make_local_train_fn.
            reshuffle = make_epoch_shuffle(
                mask, jax.random.fold_in(epoch_rng, 0))
            ex, ey, em = reshuffle(x), reshuffle(y), reshuffle(mask)
            net, _ = carry
            step_base = jax.random.fold_in(epoch_rng, 1)
            carry, (losses, ns) = jax.lax.scan(
                step, (net, step_base),
                (ex, ey, em, jnp.arange(ex.shape[0])))
            return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

        rng, shuffle_rng = jax.random.split(rng)
        (net, _), epoch_losses = jax.lax.scan(
            epoch, (net, rng), jax.random.split(shuffle_rng, local_epochs))
        if with_step_count:
            k_steps = local_epochs * jnp.sum(
                (jnp.sum(mask, axis=1) > 0).astype(jnp.float32))
            return net, jnp.mean(epoch_losses), jnp.maximum(k_steps, 1.0)
        return net, jnp.mean(epoch_losses)

    return local_train


def make_local_train_fn(
    apply_fn,
    optimizer,
    local_epochs: int,
    loss_fn=softmax_ce,
    extra_grad_fn=None,
    shuffle: bool = True,
    remat: bool = False,
    dp_clip: float = 0.0,
    dp_noise_multiplier: float = 0.0,
):
    """Build ``local_train(net, x, y, mask, rng) -> (net', mean_loss)``.

    ``x: [S, B, ...]``, ``y: [S, B]``, ``mask: [S, B]``. Masked samples
    contribute zero loss; an entirely-masked batch leaves net and optimizer
    state untouched (``tree_select`` gate), so padded steps are exact no-ops
    rather than zero-gradient optimizer ticks.

    ``extra_grad_fn(params, global_params) -> grads`` lets algorithms add
    parameter-space gradient terms (FedProx's μ(w − w_global), fedprox).

    ``remat`` rematerializes the model forward during backprop
    (``jax.checkpoint``): activations are recomputed instead of stored,
    trading ~1.3x FLOPs for peak-HBM that no longer scales with model
    depth — the lever for training big models (or many vmapped clients)
    on one chip.

    ``shuffle`` reshuffles each client's sample-to-batch assignment every
    epoch (the reference's DataLoader(shuffle=True) semantics) via an
    on-device permutation of the flattened ``[S*B]`` sample axis. REAL
    samples are permuted amongst themselves and padding stays at the tail
    (argsort of random keys offset by the mask), so trailing steps remain
    all-masked no-ops: the per-client optimizer-step count stays exactly
    ``epochs x ceil(n_i/B)`` (FedNova's τ depends on this) and at most one
    batch per epoch mixes real samples with padding.

    ``dp_clip`` > 0 switches the gradient computation to example-level
    DP-SGD (Abadi et al. 2016): per-example gradients (``vmap`` of
    ``value_and_grad`` over the batch — one batched XLA program, the
    TPU-native formulation), each clipped to L2 norm ``dp_clip``, summed,
    plus N(0, (dp_noise_multiplier * dp_clip)^2) noise per parameter, then
    normalized by the batch's real-sample count. New capability vs the
    reference, which only adds server-side noise (robust_aggregation.py:
    49-53). DP mode keeps the model state (BN stats) frozen during local
    training — per-example state updates are not well-defined under DP;
    use GroupNorm models (the federated-safe default here anyway).
    Privacy accounting: fedml_tpu.core.privacy.PrivacyAccountant.
    """
    dp = dp_clip and dp_clip > 0

    def local_train(net: NetState, x, y, mask, rng):
        opt_state = optimizer.init(net.params)
        global_params = net.params  # anchor for proximal-style terms
        n_steps, batch = x.shape[0], x.shape[1]

        def step(carry, inputs):
            net, opt_state, step_base = carry
            xb, yb, mb, idx = inputs
            # Per-step keys by fold_in on the STEP INDEX, not a carried
            # split chain: step s draws the same dropout/DP-noise keys
            # whatever the total step count, so the all-masked tail steps
            # a forced bucket appends never shift a later epoch's streams
            # (the prefix-stability the windowed tier's bit-equality
            # rests on — see make_epoch_shuffle).
            per_step = jax.random.fold_in(step_base, idx)
            sub = jax.random.fold_in(per_step, 0)
            noise_rng = jax.random.fold_in(per_step, 1) if dp else None

            def masked_loss(p):
                logits, new_state = apply_fn(
                    NetState(p, net.model_state), xb, train=True, rng=sub
                )
                per = loss_fn(logits, yb)
                loss = jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1.0)
                return loss, new_state

            if remat:
                masked_loss = jax.checkpoint(masked_loss)

            if dp:
                loss, new_state, grads = _dp_batch_grad(
                    apply_fn, loss_fn, net, xb, yb, mb, sub, noise_rng,
                    dp_clip, dp_noise_multiplier, remat,
                )
            else:
                (loss, new_state), grads = jax.value_and_grad(
                    masked_loss, has_aux=True
                )(net.params)
            if extra_grad_fn is not None:
                extra = extra_grad_fn(net.params, global_params)
                grads = jax.tree.map(jnp.add, grads, extra)
            updates, new_opt = optimizer.update(grads, opt_state, net.params)
            new_params = optax.apply_updates(net.params, updates)
            nb = jnp.sum(mb)
            nonempty = nb > 0
            new_net = NetState(new_params, new_state)
            net = tree_select(nonempty, new_net, net)
            opt_state = tree_select(nonempty, new_opt, opt_state)
            return (net, opt_state, step_base), (loss, nb)

        def epoch(carry, epoch_rng):
            if shuffle:
                # fold_in(·, 0): the shuffle keys and the step streams
                # must fork from DISJOINT children of the epoch key.
                reshuffle = make_epoch_shuffle(
                    mask, jax.random.fold_in(epoch_rng, 0))
                ex, ey, em = reshuffle(x), reshuffle(y), reshuffle(mask)
            else:
                ex, ey, em = x, y, mask
            net, opt_state, _ = carry
            step_base = jax.random.fold_in(epoch_rng, 1)
            carry, (losses, ns) = jax.lax.scan(
                step, (net, opt_state, step_base),
                (ex, ey, em, jnp.arange(ex.shape[0])))
            # Sample-weighted epoch loss: padded (all-masked) steps carry
            # weight 0, so small clients are not diluted by padding steps.
            return carry, jnp.sum(losses * ns) / jnp.maximum(jnp.sum(ns), 1.0)

        rng, shuffle_rng = jax.random.split(rng)
        (net, _, _), epoch_losses = jax.lax.scan(
            epoch,
            (net, opt_state, rng),
            jax.random.split(shuffle_rng, local_epochs),
        )
        # Mean over local epochs — the reference logs the average of
        # per-epoch means (MyModelTrainer.py:35-48).
        return net, jnp.mean(epoch_losses)

    return local_train


def make_local_train_fn_from_cfg(apply_fn, optimizer, cfg, loss_fn=softmax_ce,
                                 extra_grad_fn=None, shuffle: bool = True):
    """FedConfig-driven builder. Call sites that accept a config MUST use
    this (not raw ``make_local_train_fn``) so every cfg training field —
    epochs, remat, DP clipping/noise — takes effect everywhere; threading
    the fields by hand is how ``--dp_clip`` silently becomes a no-op on a
    forgotten path."""
    return make_local_train_fn(
        apply_fn, optimizer, cfg.epochs, loss_fn, extra_grad_fn, shuffle,
        remat=cfg.remat,
        dp_clip=getattr(cfg, "dp_clip", 0.0),
        dp_noise_multiplier=getattr(cfg, "dp_noise_multiplier", 0.0),
    )


def make_eval_fn(apply_fn, loss_fn=softmax_ce, pad_id: int = 0):
    """Build ``evaluate(net, x, y, mask) -> {loss, accuracy, num}`` over a
    batched ``[S, B, ...]`` set. On-device replacement for the reference's
    host-side per-client test loop (FedAVGAggregator.py:110-161).

    Sequence tasks ([B, T] labels): accuracy is averaged over non-pad
    positions only, consistent with ``seq_softmax_ce``.
    """

    def evaluate(net: NetState, x, y, mask):
        def step(_, inputs):
            xb, yb, mb = inputs
            logits, _ = apply_fn(net, xb, train=False)
            per = loss_fn(logits, yb)
            correct = (jnp.argmax(logits, -1) == yb).astype(jnp.float32)
            if correct.ndim > 1:  # sequence tasks: mean over non-pad tokens
                tok_mask = (yb != pad_id).astype(jnp.float32)
                tok_mask = tok_mask.reshape(correct.shape[0], -1)
                correct = correct.reshape(correct.shape[0], -1)
                correct = (correct * tok_mask).sum(-1) / jnp.maximum(
                    tok_mask.sum(-1), 1.0
                )
            return None, (jnp.sum(per * mb), jnp.sum(correct * mb), jnp.sum(mb))

        _, (losses, corrects, ns) = jax.lax.scan(step, None, (x, y, mask))
        n = jnp.maximum(jnp.sum(ns), 1.0)
        return {
            "loss": jnp.sum(losses) / n,
            "accuracy": jnp.sum(corrects) / n,
            "num": jnp.sum(ns),
        }

    return evaluate
