from fedml_tpu.trainer.local import (
    ModelFns,
    NetState,
    model_fns,
    make_client_optimizer,
    make_local_train_fn,
    make_eval_fn,
    softmax_ce,
)

__all__ = [
    "ModelFns",
    "NetState",
    "model_fns",
    "make_client_optimizer",
    "make_local_train_fn",
    "make_eval_fn",
    "softmax_ce",
]
