"""ModelTrainer ABC + task trainers.

Parity with the reference's framework-agnostic operator interface
(fedml_core/trainer/model_trainer.py:4-38: get/set_model_params, train,
test, test_on_the_server) and its three standalone task implementations
(fedml_api/standalone/fedavg/my_model_trainer_classification.py, _nwp.py,
_tag_prediction.py).

On TPU the train loop is the jitted ``make_local_train_fn`` machinery; this
class packages it in the reference's object shape so custom trainers can be
passed to the experiment layer the way the reference passes
``custom_model_trainer`` (standalone main_fedavg.py:269).
"""

from __future__ import annotations

import abc
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.trainer.local import (
    NetState,
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn,
    model_fns,
    seq_softmax_ce,
    softmax_ce,
)


def sigmoid_bce(logits, labels):
    """Per-example multi-label BCE (tag prediction: labels are multi-hot
    [B, C]); mean over labels per sample."""
    logp = jax.nn.log_sigmoid(logits)
    lognp = jax.nn.log_sigmoid(-logits)
    per_label = -(labels * logp + (1.0 - labels) * lognp)
    return jnp.mean(per_label, axis=-1)


class ModelTrainer(abc.ABC):
    """The reference ABC, TPU-shaped: params are a pytree (NetState), the
    id is the client index (model_trainer.py:10 set_id)."""

    def __init__(self, model, args=None):
        self.model = model
        self.fns = model_fns(model)
        self.args = args
        self.id = 0
        self.net: Optional[NetState] = None

    def set_id(self, trainer_id: int):
        self.id = trainer_id

    def get_model_params(self):
        return self.net

    def set_model_params(self, net: NetState):
        self.net = net

    def init(self, rng, sample_x):
        self.net = self.fns.init(rng, sample_x)
        return self.net

    @abc.abstractmethod
    def train(self, train_data, device=None, args=None) -> None:
        """Local training over [S, B, ...] packed batches (or a list of
        (x, y) numpy batch pairs from the data loaders)."""

    @abc.abstractmethod
    def test(self, test_data, device=None, args=None) -> Dict[str, float]:
        ...

    def test_on_the_server(self, train_local_dict, test_local_dict,
                           device=None, args=None) -> bool:
        """Reference default: returns False (aggregator falls back to
        per-client eval), model_trainer.py:34-38."""
        return False

    # -- shared plumbing ----------------------------------------------------
    def _pack(self, data):
        """Accept loader batch lists or pre-packed arrays."""
        if isinstance(data, tuple) and len(data) == 3:
            return data  # (x, y, mask) packed
        xs = np.concatenate([np.asarray(b[0]) for b in data])
        ys = np.concatenate([np.asarray(b[1]) for b in data])
        bs = len(np.asarray(data[0][0]))
        from fedml_tpu.data.batching import batch_global

        return batch_global(xs, ys, bs)

    def _build(self, loss_fn, pad_id=0):
        args = self.args
        opt = make_client_optimizer(
            getattr(args, "client_optimizer", "sgd"),
            getattr(args, "lr", 0.03),
            getattr(args, "wd", 0.0),
        )
        epochs = getattr(args, "epochs", 1)
        self._local = jax.jit(
            make_local_train_fn(
                self.fns.apply, opt, epochs, loss_fn,
                remat=getattr(args, "remat", False),
                dp_clip=getattr(args, "dp_clip", 0.0),
                dp_noise_multiplier=getattr(args, "dp_noise_multiplier", 0.0)))
        self._eval = jax.jit(make_eval_fn(self.fns.apply, loss_fn, pad_id=pad_id))
        self._rng = jax.random.PRNGKey(getattr(args, "seed", 0) + self.id)

    def _train_packed(self, data):
        x, y, mask = self._pack(data)
        self._rng, rng = jax.random.split(self._rng)
        self.net, loss = self._local(self.net, x, y, mask, rng)
        return float(loss)

    def _test_packed(self, data):
        x, y, mask = self._pack(data)
        m = self._eval(self.net, x, y, mask)
        return {k: float(v) for k, v in m.items()}


class ClassificationTrainer(ModelTrainer):
    """my_model_trainer_classification.py parity: CE loss, accuracy metric."""

    def __init__(self, model, args=None):
        super().__init__(model, args)
        self._build(softmax_ce)

    def train(self, train_data, device=None, args=None):
        return self._train_packed(train_data)

    def test(self, test_data, device=None, args=None):
        return self._test_packed(test_data)


class NwpTrainer(ModelTrainer):
    """my_model_trainer_nwp.py parity: per-position CE with pad masking."""

    def __init__(self, model, args=None, pad_id: int = 0):
        super().__init__(model, args)
        from functools import partial

        self._build(partial(seq_softmax_ce, pad_id=pad_id), pad_id=pad_id)

    def train(self, train_data, device=None, args=None):
        return self._train_packed(train_data)

    def test(self, test_data, device=None, args=None):
        return self._test_packed(test_data)


class TagPredictionTrainer(ModelTrainer):
    """my_model_trainer_tag_prediction.py parity: multi-label BCE; test
    reports precision/recall over the 0.5 threshold like the reference."""

    def __init__(self, model, args=None):
        super().__init__(model, args)
        self._build(sigmoid_bce)

        apply_fn = self.fns.apply

        def prf(net, x, y, mask):
            def step(acc, inputs):
                bx, by, bm = inputs
                logits, _ = apply_fn(net, bx, train=False)
                pred = (logits > 0).astype(jnp.float32)
                w = bm[:, None]
                tp = jnp.sum(pred * by * w)
                fp = jnp.sum(pred * (1 - by) * w)
                fn = jnp.sum((1 - pred) * by * w)
                t, p_, f_ = acc
                return (t + tp, p_ + fp, f_ + fn), None

            (tp, fp, fn), _ = jax.lax.scan(step, (0.0, 0.0, 0.0), (x, y, mask))
            precision = tp / jnp.maximum(tp + fp, 1.0)
            recall = tp / jnp.maximum(tp + fn, 1.0)
            return precision, recall

        self._prf = jax.jit(prf)

    def train(self, train_data, device=None, args=None):
        return self._train_packed(train_data)

    def test(self, test_data, device=None, args=None):
        x, y, mask = self._pack(test_data)
        precision, recall = self._prf(self.net, x, y, mask)
        return {"precision": float(precision), "recall": float(recall)}
