"""Trace-driven fleet simulation (virtual clock) for the message-passing
federation tiers.

The cross-device story the paper implies — millions of unreliable phones
on diurnal schedules — never runs in a test harness wired to
always-available loopback workers. This package drives the REAL control
plane (``algos/fedavg_distributed.py``'s sync/first-k path,
``algos/fedasync.py``, ``algos/fedbuff.py``, and ``ChaosTransport``)
under a seeded, deterministic fleet trace: device arrival times, diurnal
availability windows, power-law device-speed heterogeneity, and
mid-round churn, all on a VIRTUAL clock so an hour-scale serving
scenario replays in seconds of wall time and two runs with the same seed
are event-for-event identical.

- :mod:`fedml_tpu.sim.clock` — ``VirtualClock`` + ``EventQueue``;
- :mod:`fedml_tpu.sim.trace` — ``FleetSpec`` / ``FleetTrace``;
- :mod:`fedml_tpu.sim.transport` — ``SimNetwork`` / ``SimCommManager``
  (the ``backend="SIM"`` comm fabric);
- :mod:`fedml_tpu.sim.fleet` — ``FleetSimulator`` / ``FleetResult``.

See docs/ROBUSTNESS.md "Serving under churn".
"""

from fedml_tpu.sim.clock import EventQueue, VirtualClock
from fedml_tpu.sim.fleet import FleetResult, FleetSimulator, StoreFleetData
from fedml_tpu.sim.trace import FleetSpec, FleetTrace, make_fleet_trace
from fedml_tpu.sim.transport import SimCommManager, SimNetwork

__all__ = [
    "EventQueue",
    "FleetResult",
    "FleetSimulator",
    "FleetSpec",
    "FleetTrace",
    "SimCommManager",
    "SimNetwork",
    "StoreFleetData",
    "VirtualClock",
    "make_fleet_trace",
]
