"""FleetSimulator: replay a fleet trace against the REAL control plane.

The simulator constructs the actual server/client managers —
``FedAVGServerManager``/``FedAVGClientManager`` (sync, first-k via
``aggregate_k``), ``FedAsyncServerManager``/``FedAsyncClientManager``
(pure async), ``FedBuffServerManager``/``FedBuffClientManager``
(buffered semi-sync) — over the ``backend="SIM"`` fabric and replaces
ONLY the two things wall-clock execution owns:

- **Thread scheduling** → the deterministic event queue. Message
  deliveries, worker beats, and the server watchdog's deadline polls are
  virtual-time events; handler code is the managers' own (deliveries
  dispatch through the registered handler dict, evictions go through the
  server's real ``_post_tick``/``_handle_tick`` self-addressed path, the
  liveness decisions through its real ``HeartbeatMonitor`` running on
  the virtual clock).
- **Wall time** → the trace. A client's jitted local training runs at
  real speed but is CHARGED the trace's per-device virtual compute time
  (power-law speed multiplier x per-task jitter); its upload arrives
  that much later on the virtual clock. Availability windows gate every
  hop: a send from an offline device is lost, a delivery to one too, and
  a window edge inside a training interval kills the upload mid-flight —
  mid-round churn, which the real re-admission/recovery paths then heal.

Training math is therefore exact (time-to-accuracy is real), timing is
simulated (an hour-scale diurnal trace replays in seconds), and a seed
pins the whole interleaving (the determinism tests diff two runs' full
arrival logs). ChaosTransport composes via ``chaos=`` exactly as in
production, its timers rerouted through the event queue.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg_distributed import (
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
    FedAVGAggregator,
    FedAVGClientManager,
    FedAVGServerManager,
    build_federation_setup,
)
from fedml_tpu.algos.fedasync import (
    MSG_ARG_KEY_TASK_SEQ,
    FedAsyncClientManager,
    FedAsyncServerManager,
)
from fedml_tpu.algos.fedbuff import FedBuffClientManager, FedBuffServerManager
from fedml_tpu.comm.resilience import ChaosSpec
from fedml_tpu.sim.clock import EventQueue, VirtualClock
from fedml_tpu.sim.trace import FleetTrace
from fedml_tpu.sim.transport import SimNetwork
from fedml_tpu.trainer.local import softmax_ce

MODES = ("sync", "fedasync", "fedbuff")


def _pct(vals, q):
    return round(float(np.percentile(np.asarray(vals, np.float64), q)), 3)


@dataclasses.dataclass
class FleetResult:
    """One simulated federation run, in virtual time."""

    mode: str
    completed: bool
    virtual_s: float
    updates: int                       # server model versions / rounds
    completion_times: List[float]      # virtual time of each server update
    staleness: List[int]               # per accepted arrival (async/fedbuff)
    arrival_log: List[Tuple[int, int]]  # (worker, base_version) per arrival
    test_history: List[dict]
    health: Dict[str, int]
    net_counts: Dict[str, int]
    churn_killed: int                  # uploads lost to mid-round churn

    @property
    def final_accuracy(self) -> Optional[float]:
        for m in reversed(self.test_history):
            if "accuracy" in m:
                return float(m["accuracy"])
        return None

    @property
    def updates_per_vmin(self) -> float:
        """Server updates per virtual MINUTE — the round-throughput
        figure the serving story is judged on."""
        return 60.0 * self.updates / max(self.virtual_s, 1e-9)

    def summary(self) -> dict:
        from fedml_tpu.utils import rss_mb

        out = {
            "mode": self.mode,
            "completed": self.completed,
            "virtual_s": round(self.virtual_s, 1),
            "updates": self.updates,
            "updates_per_vmin": round(self.updates_per_vmin, 3),
            "final_accuracy": self.final_accuracy,
            "churn_killed_uploads": self.churn_killed,
            # The memory axis of the serving story (ROADMAP item 1) —
            # CURRENT host RSS at summary time, the same single-sourced
            # sample bench.py records per section, so sim drills report
            # it without the bench harness.
            "host_rss_mb": round(rss_mb(), 1),
            "evictions": self.health.get("evictions", 0),
            # Churn recovery: the sync tier counts re-admissions of
            # evicted ranks, the async/buffered tiers count recovery
            # re-assignments to stalled-but-alive workers — report
            # whichever this mode's server tracks.
            "readmissions": self.health.get(
                "readmissions", self.health.get("reassignments", 0)),
        }
        if self.staleness:
            out["staleness_p50"] = _pct(self.staleness, 50)
            out["staleness_p95"] = _pct(self.staleness, 95)
            out["staleness_max"] = int(max(self.staleness))
        if len(self.completion_times) >= 2:
            gaps = np.diff(np.asarray(self.completion_times, np.float64))
            out["update_interval_p50_s"] = _pct(gaps, 50)
            out["update_interval_p95_s"] = _pct(gaps, 95)
            out["update_interval_max_s"] = round(float(gaps.max()), 3)
        return out


class StoreFleetData:
    """A ``FederatedArrays``-shaped LAZY view over a ``FederatedStore``
    (flat or sharded) for the message-passing client managers: ``x[c]``/
    ``y[c]``/``mask[c]`` gather client ``c``'s rows on demand (memmap
    page-ins touch only assigned clients — the composition that lets a
    2^20-client ``ShardedFederatedStore`` + ``ClientDirectory`` back a
    fleet drill whose resident set is O(active devices)), and ``counts``
    is the store's O(clients) count vector. Every client is gathered at
    ONE forced step bucket (the store-wide max) so the jitted local
    trainer sees a single shape. A one-client cache keeps the three
    field reads of one training call to a single gather; the sim event
    loop is single-threaded, so no locking."""

    class _Field:
        def __init__(self, parent: "StoreFleetData", name: str):
            self._parent = parent
            self._name = name

        def __getitem__(self, c: int):
            return getattr(self._parent._gather(int(c)), self._name)[0]

        @property
        def dtype(self):
            return getattr(self._parent._probe, self._name).dtype

        @property
        def shape(self):
            # [C, S, B, ...]: only the feature dims (shape[3:]) and the
            # client count are meaningful to callers (the trainer builds
            # its sample from shape[3:]).
            probe = getattr(self._parent._probe, self._name)
            return (self._parent.store.num_clients,) + tuple(probe.shape[1:])

    def __init__(self, store):
        self.store = store
        self.counts = np.asarray(store.counts)
        # One fixed bucket for every client → one trainer shape.
        self._steps = store._resolve_steps(self.counts, None)
        self._cache_c: Optional[int] = None
        self._cache = None
        self._probe = self._gather(0)
        self.x = self._Field(self, "x")
        self.y = self._Field(self, "y")
        self.mask = self._Field(self, "mask")

    @property
    def batch_size(self) -> int:
        return self.store.batch_size

    def _gather(self, c: int):
        if self._cache_c != c:
            self._cache = self.store.gather_cohort(np.asarray([c]),
                                                   steps=self._steps)
            self._cache_c = c
        return self._cache


class FleetSimulator:
    """Build one federation (server + trace.n_devices clients) in
    ``mode`` ∈ {"sync", "fedasync", "fedbuff"} and replay the trace.

    ``aggregate_k`` is the sync first-k threshold (0 = all); ``alpha`` /
    ``staleness_exp`` the async/buffered staleness weighting (alpha
    defaults to the tier's own default); ``buffer_k`` / ``aggregator``
    the buffered tier's knobs; ``corrupt_ranks`` + ``corruptor`` flag
    Byzantine devices (fedbuff mode). ``chaos`` installs the fleet-wide
    ChaosTransport with virtual-time fault timers.

    Serving-drill composition knobs (the 1M-device drill, ROADMAP item
    1): ``wire_codec`` puts the negotiated codec on every device's
    uploads (top-k/randmask + error feedback need delta payloads —
    fedbuff mode; casts/int8 work everywhere); ``sim_wire`` makes the
    SIM fabric round-trip every message through a real wire format
    (bytes counted per rank — ``health()``'s bytes_tx/rx go live);
    ``directory`` routes the async tiers' client assignment through a
    ``data.directory.ClientDirectory`` (the production cohort sampler —
    cohorts drawn from 2^20-client count metadata, re-sharding
    invariant); ``cfg.ingest_workers`` arms the server's parallel
    ingest pool (comm/ingest.py — decode+fold off the dispatch thread,
    bit-equal for any worker count, so the SAME seeded drill measures
    the ingest-saturation curve); ``agg_shards`` stands up the sharded
    aggregation plane (comm/shardplane.py — M virtual aggregator-shard
    ranks between coordinator and devices, sync mode only; shard beats
    and the shard watchdog run on the virtual clock, so shard-eviction
    drills are as deterministic as device churn)."""

    def __init__(self, model, train_fed, test_global, cfg: FedConfig,
                 trace: FleetTrace, mode: str = "fedbuff", *,
                 loss_fn=softmax_ce, chaos: Optional[ChaosSpec] = None,
                 aggregate_k: int = 0, alpha: Optional[float] = None,
                 staleness_exp: float = 0.5, buffer_k: int = 2,
                 aggregator="mean", corrupt_ranks=(), corruptor=None,
                 wire_codec: str = "none", sim_wire: str = "none",
                 directory=None, agg_shards: int = 0, controller=None):
        if mode not in MODES:
            raise ValueError(f"unknown sim mode {mode!r}; known {MODES}")
        if agg_shards and mode != "sync":
            raise ValueError(
                f"agg_shards={agg_shards} is a synchronous-FedAvg "
                "capability (comm/shardplane.py); the async tiers refuse "
                "it in their server constructors for the same reason — "
                f"mode {mode!r} has no barrier round to partition")
        if getattr(cfg, "secagg", False) and mode != "sync":
            raise ValueError(
                f"secagg is a synchronous-FedAvg capability "
                "(comm/secagg.py); pairwise masks only cancel inside a "
                f"roster-complete cohort sum — mode {mode!r} has none")
        self.mode = mode
        self.agg_shards = int(agg_shards or 0)
        self.trace = trace
        spec = trace.spec
        # The fleet IS the worker set: one rank per traced device. Sim
        # deadlines default from the trace scale when the config leaves
        # them off (the control plane needs them to survive churn).
        cfg = dataclasses.replace(
            cfg, client_num_per_round=spec.n_devices,
            round_timeout_s=(cfg.round_timeout_s if cfg.round_timeout_s > 0
                             else 6.0 * spec.base_round_s),
            heartbeat_interval_s=(cfg.heartbeat_interval_s
                                  if cfg.heartbeat_interval_s > 0
                                  else max(spec.slot_s / 4.0, 1.0)))
        self.cfg = cfg
        self.clock = VirtualClock()
        self.events = EventQueue(self.clock)
        self.network = SimNetwork(spec.n_devices + self.agg_shards + 1,
                                  self.events,
                                  latency_fn=self._latency,
                                  deliver_guard=self._deliver_guard,
                                  wire=sim_wire)
        size, net0, local_train, eval_fn, args = build_federation_setup(
            model, train_fed, test_global, cfg, "SIM", loss_fn, chaos=chaos,
            extra_ranks=self.agg_shards)
        args.network = self.network
        args.chaos_after = self.events.after
        # The jitted local trainer every client shares — exposed so a
        # bench harness can warm the jit cache OUTSIDE its timed window
        # (the serving arms compare wall-clock uploads/s; a first-call
        # compile inside one arm would skew the curve).
        self.local_train = local_train
        self.net0 = net0
        self._ready_at: Dict[Tuple[int, int], float] = {}
        self._ready_rank: Dict[int, float] = {}
        self._task_idx: Dict[int, int] = {
            r: -1 for r in range(self.agg_shards + 1, size)}
        self.churn_killed = 0

        def timed_local_train(rank, fn=local_train):
            def run(*a):
                self._task_idx[rank] += 1
                dt = self.trace.compute_time(self._dev(rank),
                                             self._task_idx[rank])
                # Load spike (FleetSpec.spike_*): rounds starting inside
                # the spike window run spike_factor x slower. The
                # default factor is exactly 1.0, a bit-exact float
                # multiply — spike-free traces are unchanged.
                dt *= self.trace.load_factor(self.clock.now)
                cm = self._client_by_rank.get(rank)
                task = getattr(cm, "_last_task", -1) if cm is not None else -1
                # Charge the compute at TRAINING time as a completion
                # timestamp — keyed by the task the upload answers
                # (async/buffered tiers) or by the rank's latest round
                # (sync, whose strict request/response flow has at most
                # one upload generation in flight). Every wire copy of
                # the upload (ChaosTransport duplicate, cached resend
                # after a drop) then derives its latency from the one
                # recorded completion; a pop-once side channel let a
                # chaos duplicate ship "for free" and outrun the real
                # upload, erasing the device's compute time from the
                # drill.
                if task >= 0:
                    self._ready_at[(rank, task)] = self.clock.now + dt
                else:
                    self._ready_rank[rank] = self.clock.now + dt
                return fn(*a)
            return run

        self.shards = []
        if mode == "sync":
            M = self.agg_shards
            self.aggregator = FedAVGAggregator(net0, size - 1 - M, cfg,
                                               eval_fn, test_global)
            if M > 0:
                from fedml_tpu.comm.shardplane import (
                    AggregatorShardManager, ShardedFedAVGServerManager)

                self.server = ShardedFedAVGServerManager(
                    args, self.aggregator, cfg, size, M, backend="SIM",
                    aggregate_k=aggregate_k, clock=self.clock,
                    directory=directory)
                # beat_interval_s=0 silences the shard's wall-clock
                # HeartbeatSender thread; _schedule_beats replays shard
                # beats as virtual-time events instead.
                self.shards = [
                    AggregatorShardManager(args, r, size, cfg, net0,
                                           backend="SIM",
                                           beat_interval_s=0.0,
                                           clock=self.clock)
                    for r in range(1, M + 1)]
            else:
                self.server = FedAVGServerManager(
                    args, self.aggregator, cfg, size, backend="SIM",
                    aggregate_k=aggregate_k, clock=self.clock)
            self.clients = [
                FedAVGClientManager(args, r, size, train_fed,
                                    timed_local_train(r), cfg, backend="SIM",
                                    wire_codec_spec=wire_codec)
                for r in range(M + 1, size)]
        elif mode == "fedasync":
            self.server = FedAsyncServerManager(
                args, net0, cfg, size, backend="SIM",
                alpha=(0.6 if alpha is None else alpha),
                staleness_exp=staleness_exp, eval_fn=eval_fn,
                test_data=test_global, clock=self.clock,
                directory=directory)
            self.clients = [
                FedAsyncClientManager(args, r, size, train_fed,
                                      timed_local_train(r), cfg,
                                      backend="SIM",
                                      wire_codec_spec=wire_codec)
                for r in range(1, size)]
        else:  # fedbuff
            self.server = FedBuffServerManager(
                args, net0, cfg, size, backend="SIM",
                alpha=(1.0 if alpha is None else alpha),
                staleness_exp=staleness_exp, buffer_k=buffer_k,
                aggregator=aggregator, eval_fn=eval_fn,
                test_data=test_global, clock=self.clock,
                directory=directory)
            corrupt = set(corrupt_ranks)
            self.clients = [
                FedBuffClientManager(args, r, size, train_fed,
                                     timed_local_train(r), cfg,
                                     backend="SIM",
                                     wire_codec_spec=wire_codec,
                                     corruptor=(corruptor if r in corrupt
                                                else None))
                for r in range(1, size)]
        if controller is not None:
            # Adaptive control (fedml_tpu.ctrl): the server is a REAL
            # manager over the SIM backend, so the identical controller
            # object steps from the identical safe-boundary hook it uses
            # in a live run — offline policy development is the point.
            self.server.attach_controller(controller)
        self._client_by_rank = {c.rank: c for c in self.clients}
        self._watch_round = -1
        self._watch_t0 = 0.0
        self._term_t0: Optional[float] = None

    # -- trace-driven policy hooks ------------------------------------------
    def _dev(self, rank: int) -> int:
        """Comm rank → trace device index. Identical when the rank
        layout has no aggregator shards; with M shards the device ranks
        start after them (rank M+d is device d)."""
        return rank - self.agg_shards

    def _latency(self, msg) -> Optional[float]:
        sender = int(msg.get_sender_id())
        receiver = int(msg.get_receiver_id())
        now = self.clock.now
        wire = self.trace.spec.wire_latency_s
        if sender == receiver:
            return 0.0  # the watchdog's self-addressed tick: no network
        if sender <= self.agg_shards:
            # Server or aggregator-shard hop (rank 0, or 1..M when the
            # sharded plane is up): infrastructure is always online and
            # has no trace entry — wire latency only. Receiver liveness
            # is checked at delivery.
            return wire
        # Device-originated. An upload is deliverable once its training
        # completes: ``_ready_at`` for task-tagged async/buffered
        # uploads, ``_ready_rank`` for the sync tier's round-keyed ones
        # — so a duplicate ships no earlier than the original and a
        # cached resend after the completion is wire-only.
        dt = 0.0
        if msg.get_type() == MSG_TYPE_C2S_SEND_MODEL_TO_SERVER:
            task = msg.get(MSG_ARG_KEY_TASK_SEQ)
            ready = (self._ready_at.get((sender, int(task)))
                     if task is not None
                     else self._ready_rank.get(sender))
            if ready is not None:
                dt = max(ready - now, 0.0)
        if not self.trace.online_through(self._dev(sender), now, now + dt):
            # The availability window closed inside the training
            # interval: mid-round churn — the upload (or beat) is lost.
            if dt > 0.0:
                self.churn_killed += 1
            return None
        return dt + wire

    def _deliver_guard(self, msg) -> bool:
        receiver = int(msg.get_receiver_id())
        if receiver <= self.agg_shards:
            return True  # coordinator / aggregator shards: always online
        return self.trace.online_at(self._dev(receiver), self.clock.now)

    # -- scheduled control events -------------------------------------------
    def _schedule_beats(self) -> None:
        hb = self.cfg.heartbeat_interval_s
        horizon = self.trace.spec.horizon_s

        def beat(client):
            if self.server._stopped or self.network.stopped(client.rank):
                return
            if self.trace.online_at(self._dev(client.rank), self.clock.now):
                client._send_beat()
            if self.clock.now + hb <= horizon:
                self.events.after(hb, lambda: beat(client))

        for c in self.clients:
            first = self.trace.next_online(self._dev(c.rank), 0.0)
            if first is not None:
                self.events.at(first + hb, lambda c=c: beat(c))

        # Aggregator shards beat too (their wall-clock HeartbeatSender is
        # disarmed at construction): always online, so a plain cadence —
        # unless a drill killed the shard's rank on the SIM fabric, which
        # is exactly how shard-eviction tests silence one.
        def shard_beat(sh):
            if self.server._stopped or sh._stopped:
                return
            if not self.network.stopped(sh.rank):
                sh._send_beat()
            if self.clock.now + hb <= horizon:
                self.events.after(hb, lambda: shard_beat(sh))

        for sh in self.shards:
            self.events.after(hb, lambda sh=sh: shard_beat(sh))

    def _schedule_watchdog(self) -> None:
        """The event-driven twin of the servers' watchdog threads: same
        deadline decisions (through the real HeartbeatMonitor on the
        virtual clock), same self-addressed ``_post_tick`` delivery —
        only the polling loop is replaced by recurring events.

        CAUTION: the decision logic below mirrors
        ``FedAVGServerManager._watchdog_loop`` and
        ``FedAsyncServerManager._watchdog_loop`` rather than sharing
        code with them (the thread loops interleave sleeping, locking,
        and ``wait_all_or_failed`` blocking in ways an event twin cannot
        reuse directly). A policy change in either server's watchdog —
        eviction predicates, the all-evicted-but-beating hold-open rule,
        terminal handling — must be reflected here, or churn drills will
        validate behavior production no longer has."""
        poll = max(self.cfg.round_timeout_s / 4.0, 1.0)
        horizon = self.trace.spec.horizon_s
        tick = (self._sync_watch if self.mode == "sync"
                else self._async_watch)

        def watch():
            if self.server._stopped:
                return
            tick()
            if not self.server._stopped and self.clock.now + poll <= horizon:
                self.events.after(poll, watch)

        self.events.after(poll, watch)

    def _sync_watch(self) -> None:
        srv = self.server
        now = self.clock.now
        if self.shards:
            # The sharded coordinator's shard watchdog, event-twinned the
            # same way: silent live shards get a self-addressed tick and
            # the eviction executes on the dispatch path
            # (ShardedFedAVGServerManager._shard_watch_loop).
            dead = (set(srv.shard_heartbeat.failed())
                    & set(srv._live_shards_snapshot()))
            if dead:
                srv._post_shard_tick(sorted(dead))
        members = set(srv._members_snapshot())
        r = srv.round_idx
        if r != self._watch_round:
            self._watch_round, self._watch_t0 = r, now
        if not members:
            srv._post_tick(r, [])
            return
        terminal = r >= self.cfg.comm_round
        have = set(srv._done_snapshot() if terminal
                   else srv._arrived_snapshot())
        deadline = srv.done_timeout_s if terminal else srv.round_timeout_s
        if not deadline or deadline <= 0:
            return
        failed = set(srv.heartbeat.failed())
        missing = members - have
        if missing and missing <= failed:
            srv._post_tick(r, sorted(failed & members))
        elif missing and now - self._watch_t0 > deadline:
            srv._post_tick(r, sorted((failed | missing) & members))

    def _async_watch(self) -> None:
        srv = self.server
        now = self.clock.now
        with srv._lock:
            members = set(srv._members)
        terminal = (not members) or srv.version >= self.cfg.comm_round
        if not terminal:
            self._term_t0 = None
            failed = set(srv.heartbeat.failed())
            if members and failed >= members:
                srv._post_tick(sorted(failed & members))
            return
        if self._term_t0 is None:
            self._term_t0 = now
        if not members:
            srv._post_tick([])
            return
        done = set(srv._done_snapshot())
        missing = members - done
        failed = set(srv.heartbeat.failed())
        if missing and missing <= failed:
            srv._post_tick(sorted(failed & members))
        elif missing and now - self._term_t0 > (srv.done_timeout_s or 0):
            srv._post_tick(sorted((failed | missing) & members))

    # -- the run -------------------------------------------------------------
    def _progress(self) -> int:
        return (self.server.round_idx if self.mode == "sync"
                else self.server.version)

    def run(self, max_virtual_s: Optional[float] = None) -> FleetResult:
        horizon = (self.trace.spec.horizon_s if max_virtual_s is None
                   else max_virtual_s)
        for mgr in [self.server] + self.shards + self.clients:
            mgr.register_message_receive_handlers()
        # The server's run() preamble, minus its blocking receive loop.
        M = self.agg_shards
        for r in range(M + 1, M + self.trace.spec.n_devices + 1):
            self.server.heartbeat.beat(r)
        for sh in self.shards:
            self.server.shard_heartbeat.beat(sh.rank)
        self.server.send_init_msg()
        self._schedule_beats()
        self._schedule_watchdog()
        completions: List[float] = []
        last = self._progress()
        while (not self.server._stopped and len(self.events)
               and self.events.next_time() <= horizon):
            self.events.step()
            p = self._progress()
            if p > last:
                completions.extend([self.clock.now] * (p - last))
                last = p
        # "Completed" means the federation actually reached its target
        # (rounds for sync, server versions for async/buffered) — the
        # async managers have no `aborted` flag, and an all-dead fleet
        # finishes their run() with the version short of comm_round, so
        # the progress check is what distinguishes collapse from
        # completion there.
        completed = (self.server._stopped
                     and not getattr(self.server, "aborted", False)
                     and last >= self.cfg.comm_round)
        # Every tier now exposes the same health() surface (PR 11
        # unified it; the async dict used to be hand-assembled here).
        health = self.server.health()
        if self.mode == "sync":
            test_history = self.aggregator.test_history
            staleness: List[int] = []
            arrivals: List[Tuple[int, int]] = []
        else:
            test_history = self.server.test_history
            staleness = list(self.server.staleness_history)
            arrivals = list(self.server.arrival_log)
        return FleetResult(
            mode=self.mode, completed=completed,
            virtual_s=(completions[-1] if completions else self.clock.now),
            updates=last, completion_times=completions,
            staleness=staleness, arrival_log=arrivals,
            test_history=list(test_history), health=health,
            net_counts=dict(self.network.counts),
            churn_killed=self.churn_killed)
