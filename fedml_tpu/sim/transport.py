"""The ``backend="SIM"`` comm fabric: message delivery as virtual-time
events.

``SimNetwork`` replaces LoopbackNetwork's per-rank blocking queues with
the event queue: a send schedules a delivery event at
``now + latency_fn(msg)`` and the delivery dispatches the message to the
receiving manager's registered handlers directly — the same serialized
one-message-at-a-time semantics as the real receive loops (the fake-
clock protocol tests already rely on direct handler invocation being
faithful), but ordered by VIRTUAL time instead of thread scheduling.

The fleet simulator owns the two policy hooks:

- ``latency_fn(msg) -> float | None`` at SEND time — wire latency,
  per-device compute time for uploads, or ``None`` to drop (sender
  offline / churn killed the upload mid-training);
- ``deliver_guard(msg) -> bool`` at DELIVERY time — receiver
  reachability (a message to an offline phone is lost).

A stopped rank (its manager called ``finish()``) drops deliveries like
a dead process. ChaosTransport wraps a ``SimCommManager`` exactly as it
wraps any real backend (``args.chaos``), with its delay/reorder timers
rerouted through the same event queue (``args.chaos_after``), so chaos
drills stay deterministic under simulation.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.sim.clock import EventQueue


def _tracer():
    """The active span tracer (obs.trace), imported lazily so the sim
    fabric stays importable without pulling the obs package (which
    imports jax) until a drill actually runs."""
    from fedml_tpu.obs import trace as obs_trace

    return obs_trace.active()


class SimNetwork:
    """Shared virtual-time router: observers per rank, deliveries as
    events. Single-threaded by construction.

    ``wire`` (default ``"none"``): with a real wire format name
    (``tensor`` | ``json`` | ``pickle``) every inter-rank message is
    serialized at post and deserialized at delivery — the LoopbackNetwork
    round-trip mode's virtual-time twin, so a fleet drill exercises the
    exact frame code the socket backends ship AND counts honest
    bytes-on-wire (per-rank :class:`~fedml_tpu.comm.wire.ByteLedger`\\ s,
    surfaced through each manager's ``bytes_ledger`` → ``health()``).
    Self-addressed messages (the watchdog tick) skip the round-trip —
    they never cross a wire."""

    def __init__(self, size: int, events: EventQueue,
                 latency_fn: Optional[Callable[[Message],
                                               Optional[float]]] = None,
                 deliver_guard: Optional[Callable[[Message], bool]] = None,
                 default_latency_s: float = 0.0, wire: str = "none"):
        from fedml_tpu.comm.wire import WIRE_FORMATS, ByteLedger

        if wire not in ("none",) + WIRE_FORMATS:
            raise ValueError(f"unknown sim wire format {wire!r}")
        self.size = size
        self.events = events
        self.latency_fn = latency_fn
        self.deliver_guard = deliver_guard
        self.default_latency_s = default_latency_s
        self.wire = wire
        self.ledgers: Dict[int, "ByteLedger"] = {
            r: ByteLedger() for r in range(size)}
        self._observers: Dict[int, List[Observer]] = {}
        self._stopped: Set[int] = set()
        self.counts: Dict[str, int] = {
            "sent": 0, "delivered": 0, "dropped_send": 0,
            "dropped_offline": 0, "dropped_stopped": 0,
        }

    def attach(self, rank: int, observer: Observer) -> None:
        self._observers.setdefault(rank, []).append(observer)

    def detach(self, rank: int, observer: Observer) -> None:
        self._observers.get(rank, []).remove(observer)

    def stop(self, rank: int) -> None:
        self._stopped.add(rank)

    def stopped(self, rank: int) -> bool:
        return rank in self._stopped

    def post(self, msg: Message) -> None:
        self.counts["sent"] += 1
        latency = self.default_latency_s
        if self.latency_fn is not None:
            latency = self.latency_fn(msg)
        if latency is None:
            self.counts["dropped_send"] += 1
            tr = _tracer()
            if tr:
                tr.instant("wire.drop", cat="wire", reason="send",
                           sender=int(msg.get_sender_id()),
                           receiver=int(msg.get_receiver_id()))
            return
        # Wire round-trip (after the latency decision, which reads the
        # live message): bytes sit in flight, the sender's ledger counts
        # tx NOW and the receiver's counts rx at delivery.
        blob = None
        sender = int(msg.get_sender_id())
        receiver = int(msg.get_receiver_id())
        if self.wire != "none" and sender != receiver:
            from fedml_tpu.comm.wire import serialize_message

            blob = serialize_message(msg, self.wire)
            self.ledgers[sender].count_tx(receiver, len(blob))
        # The in-flight time becomes one "wire.sim" span at delivery:
        # install a SpanTracer over THIS simulation's VirtualClock
        # (obs.trace.tracing_to(dir, clock=sim.clock)) and the trace's
        # time axis is virtual seconds — compute charge + wire latency
        # drawn exactly as the drill scheduled them.
        t_sent = _tracer().now()
        self.events.after(latency, lambda m=msg, b=blob, t0=t_sent:
                          self._deliver(m, t0, b))

    def _deliver(self, msg: Message, t_sent: float = 0.0,
                 blob=None) -> None:
        receiver = int(msg.get_receiver_id())
        tr = _tracer()
        if receiver in self._stopped:
            self.counts["dropped_stopped"] += 1
            if tr:
                tr.instant("wire.drop", cat="wire", reason="stopped",
                           receiver=receiver)
            return
        if self.deliver_guard is not None and not self.deliver_guard(msg):
            self.counts["dropped_offline"] += 1
            if tr:
                tr.instant("wire.drop", cat="wire", reason="offline",
                           receiver=receiver)
            return
        self.counts["delivered"] += 1
        if blob is not None:
            from fedml_tpu.comm.wire import deserialize_message

            self.ledgers[receiver].count_rx(int(msg.get_sender_id()),
                                            len(blob))
            msg = deserialize_message(blob, self.wire)
        if tr:
            tr.complete("wire.sim", t_sent, cat="wire",
                        sender=int(msg.get_sender_id()), receiver=receiver,
                        msg_type=int(msg.get_type()))
        for obs in list(self._observers.get(receiver, ())):
            obs.receive_message(msg.get_type(), msg)


class SimCommManager(BaseCommunicationManager):
    """Per-rank handle on the SimNetwork, implementing the backend
    surface the managers expect. ``handle_receive_message`` is a no-op:
    under simulation the EVENT LOOP dispatches (the fleet simulator
    never calls the managers' blocking ``run()``)."""

    def __init__(self, network: SimNetwork, rank: int):
        self.network = network
        self.rank = rank

    @property
    def bytes_ledger(self):
        """This rank's tx/rx byte totals (live only when the network
        runs a wire round-trip mode) — the surface ``health()`` reads on
        every backend."""
        return self.network.ledgers[self.rank]

    def send_message(self, msg: Message) -> None:
        if self.network.stopped(self.rank):
            raise ConnectionError(f"sim rank {self.rank} is stopped")
        self.network.post(msg)

    def add_observer(self, observer: Observer) -> None:
        self.network.attach(self.rank, observer)

    def remove_observer(self, observer: Observer) -> None:
        self.network.detach(self.rank, observer)

    def handle_receive_message(self) -> None:
        """No blocking loop: deliveries are event-queue callbacks."""

    def stop_receive_message(self) -> None:
        self.network.stop(self.rank)
