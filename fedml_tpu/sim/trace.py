"""Fleet traces: seeded arrival/availability/speed schedules.

A trace answers three questions about every device, entirely ahead of
time (so a drill is replayable and two runs with one seed are
identical):

- **When does it exist?** Each device ARRIVES once (staggered joins over
  ``arrival_spread_s``) and is offline before that.
- **When is it reachable?** Availability is drawn per ``slot_s`` slot
  from a diurnal-modulated Bernoulli — ``mean_online`` scaled by a
  sinusoid of ``diurnal_period_s`` with per-device phase, the canonical
  cross-device pattern (phones charge at night in their own timezones).
  Consecutive online slots merge into windows; a window edge landing
  inside a device's training interval IS the mid-round churn the
  buffered tier is built for.
- **How fast is it?** Per-device TIME multipliers are power-law
  (Pareto(``speed_alpha``), support [1, inf)): most phones are fine, the
  tail is brutally slow — the straggler distribution first-k and
  buffered aggregation react to. Per-task lognormal jitter
  (``compute_jitter``) models thermal/load variance.

Randomness is keyed per (seed, stream, device, draw-index) through a
stable integer mix — no global RNG order dependence, so adding a stream
never reshuffles another's draws.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

# The PYTHONHASHSEED-proof integer mix ChaosTransport keys its fault
# streams on — shared, not copied, so the two keying schemes cannot
# drift apart.
from fedml_tpu.comm.resilience import _mix

# Stream tags (arbitrary distinct constants).
_S_ARRIVAL = 1
_S_SPEED = 2
_S_AVAIL = 3
_S_PHASE = 4
_S_COMPUTE = 5


def _rng(seed: int, *key: int) -> np.random.RandomState:
    return np.random.RandomState(_mix(seed, *key) % (2 ** 31))


@dataclasses.dataclass(frozen=True)
class FleetSpec:
    """Everything that defines a fleet trace. Frozen: a spec + seed IS
    the trace identity (the determinism tests pin that)."""

    n_devices: int = 8
    seed: int = 0
    horizon_s: float = 3600.0        # virtual length of the trace
    arrival_spread_s: float = 120.0  # device joins uniform in [0, spread)
    slot_s: float = 120.0            # availability decision granularity
    mean_online: float = 0.85        # base per-slot availability
    diurnal_amplitude: float = 0.0   # 0 = flat, 1 = full day/night swing
    diurnal_period_s: float = 86400.0
    base_round_s: float = 30.0       # local round on a speed-1 device
    speed_alpha: float = 2.0         # Pareto shape of the TIME multiplier
    max_speed_mult: float = 20.0     # clamp the Pareto tail
    compute_jitter: float = 0.1      # lognormal sigma per (device, task)
    wire_latency_s: float = 0.5      # one-way control/model hop
    # Load spike: every local round whose training STARTS inside
    # [spike_t0, spike_t1) takes spike_factor x as long — a fleet-wide
    # thermal/contention event, the staleness-cliff stimulus the
    # adaptive controller (fedml_tpu.ctrl) is drilled against. The
    # defaults are exact no-ops (x1.0 is bit-exact in float), so every
    # pre-spike trace digest is unchanged.
    spike_t0: float = -1.0
    spike_t1: float = -1.0
    spike_factor: float = 1.0


class FleetTrace:
    """Materialized trace: per-device online windows + speed multipliers.
    Device ids are the message-passing RANKS 1..n_devices (rank 0 is the
    server, always online)."""

    def __init__(self, spec: FleetSpec):
        self.spec = spec
        self.arrivals: Dict[int, float] = {}
        self.speeds: Dict[int, float] = {}
        self.windows: Dict[int, List[Tuple[float, float]]] = {}
        for r in range(1, spec.n_devices + 1):
            self.arrivals[r] = float(
                _rng(spec.seed, _S_ARRIVAL, r).rand() * spec.arrival_spread_s)
            # Pareto(alpha) on [1, inf): inverse-CDF of a uniform draw.
            u = _rng(spec.seed, _S_SPEED, r).rand()
            self.speeds[r] = float(
                min((1.0 - u) ** (-1.0 / spec.speed_alpha),
                    spec.max_speed_mult))
            self.windows[r] = self._build_windows(r)

    def _build_windows(self, r: int) -> List[Tuple[float, float]]:
        spec = self.spec
        phase = float(_rng(spec.seed, _S_PHASE, r).rand()
                      * spec.diurnal_period_s)
        rng = _rng(spec.seed, _S_AVAIL, r)
        start = self.arrivals[r]
        n_slots = int(np.ceil((spec.horizon_s - start) / spec.slot_s))
        if n_slots <= 0:
            return []
        t = start + np.arange(n_slots) * spec.slot_s
        p = spec.mean_online * (
            1.0 + spec.diurnal_amplitude
            * np.sin(2.0 * np.pi * (t + phase) / spec.diurnal_period_s))
        online = rng.rand(n_slots) < np.clip(p, 0.0, 1.0)
        windows: List[Tuple[float, float]] = []
        for i, flag in enumerate(online):
            s, e = t[i], min(t[i] + spec.slot_s, spec.horizon_s)
            if not flag:
                continue
            if windows and abs(windows[-1][1] - s) < 1e-9:
                windows[-1] = (windows[-1][0], e)
            else:
                windows.append((s, e))
        return windows

    # -- queries -------------------------------------------------------------
    def online_at(self, rank: int, t: float) -> bool:
        if rank == 0:
            return True
        return any(s <= t < e for s, e in self.windows.get(rank, ()))

    def online_through(self, rank: int, t0: float, t1: float) -> bool:
        """True iff the device stays online for the WHOLE interval — a
        window edge inside [t0, t1] is exactly mid-round churn."""
        if rank == 0:
            return True
        return any(s <= t0 and t1 <= e
                   for s, e in self.windows.get(rank, ()))

    def next_online(self, rank: int, t: float) -> Optional[float]:
        if rank == 0:
            return t
        for s, e in self.windows.get(rank, ()):
            if t < e:
                return max(s, t)
        return None

    def compute_time(self, rank: int, task_idx: int) -> float:
        """Virtual seconds of local training for this device's
        ``task_idx``-th assignment: base x power-law device multiplier x
        per-task lognormal jitter. Keyed, so replays are identical."""
        spec = self.spec
        jitter = 1.0
        if spec.compute_jitter > 0:
            jitter = float(np.exp(
                _rng(spec.seed, _S_COMPUTE, rank, task_idx).randn()
                * spec.compute_jitter))
        return spec.base_round_s * self.speeds[rank] * jitter

    def load_factor(self, t: float) -> float:
        """Compute-time multiplier at virtual time ``t`` (the load-spike
        window, 1.0 outside it). Deterministic in (spec, t) — part of
        the trace identity, like every other schedule here."""
        spec = self.spec
        if spec.spike_t0 <= t < spec.spike_t1:
            return spec.spike_factor
        return 1.0

    def online_fraction(self, rank: int) -> float:
        total = sum(e - s for s, e in self.windows.get(rank, ()))
        return total / max(self.spec.horizon_s - self.arrivals[rank], 1e-9)

    def describe(self) -> dict:
        """Summary scalars for bench artifacts."""
        speeds = np.array([self.speeds[r]
                           for r in sorted(self.speeds)], np.float64)
        online = np.array([self.online_fraction(r)
                           for r in sorted(self.windows)], np.float64)
        return {
            "n_devices": self.spec.n_devices,
            "seed": self.spec.seed,
            "horizon_s": self.spec.horizon_s,
            "speed_mult_p50": round(float(np.median(speeds)), 3),
            "speed_mult_max": round(float(speeds.max()), 3),
            "online_fraction_mean": round(float(online.mean()), 3),
            "online_fraction_min": round(float(online.min()), 3),
        }


def make_fleet_trace(spec: FleetSpec) -> FleetTrace:
    return FleetTrace(spec)
