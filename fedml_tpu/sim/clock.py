"""Virtual time: a monotone clock plus a deterministic event queue.

The control-plane managers already take ``clock=`` (HeartbeatMonitor,
FedAVGServerManager, FedAsyncServerManager), so a fleet drill can run on
simulated seconds: the event queue pops callbacks in (time, insertion)
order and advances the clock to each event's timestamp — no sleeping, no
thread races, and the same seed replays the same schedule event for
event. Ties break on insertion order, which is itself deterministic
because the whole simulation is single-threaded.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, List, Tuple


class VirtualClock:
    """Monotone simulated time; pass the instance itself as ``clock=``
    (it is callable, matching ``time.monotonic``'s signature)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now - 1e-9:
            raise ValueError(f"clock cannot run backwards: {t} < {self._now}")
        self._now = max(self._now, float(t))


class EventQueue:
    """Deterministic discrete-event scheduler over a :class:`VirtualClock`.

    ``after(dt, fn)`` / ``at(t, fn)`` enqueue; ``step()`` pops the
    earliest event, advances the clock to it, and runs it (events it
    enqueues land back in the queue). Exceptions propagate — a failing
    handler should fail the drill, not vanish on a daemon thread."""

    def __init__(self, clock: VirtualClock):
        self.clock = clock
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = itertools.count()

    def __len__(self) -> int:
        return len(self._heap)

    def at(self, t: float, fn: Callable[[], None]) -> None:
        heapq.heappush(self._heap,
                       (max(float(t), self.clock.now), next(self._seq), fn))

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self.clock.now + max(float(dt), 0.0), fn)

    def next_time(self) -> float:
        if not self._heap:
            raise IndexError("empty event queue")
        return self._heap[0][0]

    def step(self) -> None:
        t, _, fn = heapq.heappop(self._heap)
        self.clock.advance_to(t)
        fn()
