"""Negotiated wire codecs for model-update payloads.

The compressed-communication layer the reference never had: its transports
ship full-precision pickled state_dicts every round (mpi_send_thread.py:27,
grpc_comm_manager.py:54 — with the gRPC cap raised to 1000 MB to make them
fit), and the communication term dominates federated averaging at scale
(Parallel Restarted SGD, arXiv:1807.06629). This module sits UNDER the
``tensor`` wire frame (fedml_tpu.comm.wire): a codec turns an update
pytree into a compact payload of plain arrays + scalars that any wire
format can carry, and the frame self-describes its codec (``CODEC_KEY``)
so the receiver rebuilds the exact decoder per message.

Codecs are **composable stages**, spelled ``stage[+stage]``:

- ``bf16`` / ``fp16`` — dtype cast of the shipped values (2x, lossy in
  mantissa only; bf16 keeps fp32's exponent range).
- ``int8`` — QSGD-style stochastic-rounded uniform quantization (4x).
  Dense frames carry one scale PER TENSOR (a single global scale would
  flush small-magnitude layers to zero); after a sparsifier, one scale
  covers the surviving values.
- ``topk<ratio>`` — magnitude top-k sparsification; ships fp32 values +
  int32 indices (``k*(4+4)`` bytes instead of ``4n``).
- ``randmask<ratio>`` — seed-expanded random mask: ships the PRNG seed +
  the selected values ONLY (``k*4`` bytes + one int); the receiver
  re-expands the index set from the seed, so the indices never cross the
  wire.

A chain is at most one sparsifier (first) plus at most one value
transform, e.g. ``topk0.01+int8``. Sparsifying codecs carry **per-client
error feedback**: ``encode`` returns the residual ``input − decode(
encode(input))`` (which also folds in any downstream quantization error),
the caller adds it to the next round's update, and the compression error
telescopes instead of accumulating — pinned against a numpy reference in
tests/test_wire_codec.py.

Negotiation rides the init/registration handshake: the server advertises
its supported stage names under ``OFFER_KEY``; :func:`negotiate` resolves
the client's requested spec against the offer and falls back to the
uncompressed tensor wire — LOUDLY logged, never silent — when the peer is
codec-ignorant (no offer key: an older build) or lacks a stage.

Decode is pickle-free and safe to parse, like the tensor frame itself:
pure numpy over arrays the wire already validated, with explicit
:class:`CodecError` refusal of truncated/corrupt/inconsistent frames.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, List, Optional, Tuple

import numpy as np

if TYPE_CHECKING:  # the spec type lives with its on-device twins
    from fedml_tpu.core.compression import TreeSpec

log = logging.getLogger(__name__)


def tree_spec(tree) -> "TreeSpec":
    """Build the receiver's model spec (re-export of
    :func:`fedml_tpu.core.compression.tree_spec`, imported lazily so the
    comm package stays importable without touching jax until a codec is
    actually used)."""
    from fedml_tpu.core.compression import tree_spec as _ts

    return _ts(tree)

#: Message key carrying the frame's codec spec (self-description).
CODEC_KEY = "wire_codec"
#: Handshake key: the peer's advertised stage names (negotiation offer).
OFFER_KEY = "codec_offer"
#: Handshake key: the receiver ACCEPTS delta-framed uploads (sync tier:
#: anchor-based reconstruction against the round's broadcast net; async
#: tiers: the additive staleness-discounted fold). Promoted from a
#: FedBuff-only client-class attribute into a negotiated per-connection
#: capability (PR 15) so sync + async + fedbuff all accept delta frames
#: — and a delta sender REFUSES loudly against a delta-ignorant peer
#: (:func:`require_delta_peer`) instead of letting it mis-fold the delta
#: as a full model.
DELTA_OK_KEY = "delta_frames_ok"
#: Upload message key: True = the payload is a DELTA against the model
#: the sender pulled; False = a full model. Absent = a legacy peer —
#: each tier keeps its historical interpretation (sync/async full,
#: fedbuff delta) for hand-built protocol-test messages.
DELTA_KEY = "payload_is_delta"
#: Handshake key: the server runs the secure-aggregation plane
#: (``comm/secagg.py``) and will fold MASKED int64 fixed-point frames.
#: Advertised on assignments exactly like :data:`DELTA_OK_KEY` — a
#: secagg client facing a server that never advertised it must refuse
#: (:func:`require_secagg_peer`), not upload its update in the clear.
SECAGG_OK_KEY = "secagg_ok"
#: Upload message key: True = the payload is a PAIRWISE-MASKED int64
#: fixed-point contribution (fold with ``PartialAccumulator.add_fixed``,
#: never decode/clip); absent/False = a normal clear-domain payload.
SECAGG_MASKED_KEY = "secagg_masked"

#: Stage names this build implements — the negotiation offer.
SUPPORTED_STAGES = ("bf16", "fp16", "int8", "topk", "randmask")

_SPARSIFIERS = ("topk", "randmask")


class CodecError(ValueError):
    """A wire-codec frame is corrupt, truncated, or inconsistent with the
    negotiated model spec; refuse it rather than aggregate garbage."""


def _bf16_dtype():
    import ml_dtypes  # registered by jax's dependency set

    return np.dtype(ml_dtypes.bfloat16)


# --------------------------------------------------------------------------
# Host-side pytree <-> fp32 vector (numpy; the wire layer is host-side —
# the on-device jitted twins live in fedml_tpu.core.compression)


def tree_to_vector_np(tree) -> np.ndarray:
    """Flatten an update pytree (numpy/jax leaves, any dtype incl.
    bfloat16) into one fp32 numpy vector."""
    import jax

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate(
        [np.ravel(np.asarray(l)).astype(np.float32) for l in leaves])


def vector_to_tree_np(vec: np.ndarray, spec: TreeSpec):
    """Rebuild the pytree from a fp32 vector: per-leaf reshape + cast back
    to the original dtype. Raises :class:`CodecError` on a length
    mismatch (a truncated or wrong-model frame)."""
    import jax

    total = int(sum(spec.sizes))
    if vec.shape != (total,):
        raise CodecError(
            f"decoded vector has {vec.shape[0] if vec.ndim == 1 else vec.shape} "
            f"elements but the model spec declares {total}")
    out, off = [], 0
    for shape, dtype, size in zip(spec.shapes, spec.dtypes, spec.sizes):
        out.append(vec[off:off + size].reshape(shape).astype(np.dtype(dtype)))
        off += size
    return jax.tree.unflatten(spec.treedef, out)


def _stochastic_round(x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Unbiased: round up with probability = fractional part."""
    low = np.floor(x)
    return low + (rng.random(x.shape) < (x - low))


def _expand_mask(seed: int, n: int, k: int) -> np.ndarray:
    """The randmask index set, derived identically on both ends from the
    frame's seed (Philox bit-stream — stable across numpy versions)."""
    rng = np.random.Generator(np.random.Philox(np.uint64(seed & (2**64 - 1))))
    scores = rng.random(n)
    idx = np.argpartition(scores, k - 1)[:k] if k < n else np.arange(n)
    idx.sort()
    return idx.astype(np.int64)


def _require(payload: dict, key: str, codec: str):
    try:
        return payload[key]
    except (KeyError, TypeError):
        raise CodecError(
            f"codec {codec!r} frame missing field {key!r} — truncated or "
            "corrupt") from None


# --------------------------------------------------------------------------
# Value stages (operate on the shipped values array)


class _CastStage:
    def __init__(self, name: str):
        self.name = name
        self._dtype = _bf16_dtype() if name == "bf16" else np.dtype(np.float16)

    def encode(self, vals, seed, segments):
        return {"q": vals.astype(self._dtype)}

    def decode(self, payload, n_vals, segments, codec):
        q = np.asarray(_require(payload, "q", codec))
        if q.dtype != self._dtype:
            raise CodecError(
                f"codec {codec!r}: values dtype {q.dtype} != {self._dtype}")
        if q.shape != (n_vals,):
            raise CodecError(
                f"codec {codec!r}: {q.shape} values for {n_vals} slots")
        return q.astype(np.float32)


class _Int8Stage:
    """Stochastic-rounded symmetric int8, one scale per segment. Dense
    frames segment per tensor (``segments`` = the spec's leaf sizes);
    sparse frames ship the survivors as one segment."""

    name = "int8"
    LEVELS = 127

    def encode(self, vals, seed, segments):
        rng = np.random.Generator(
            np.random.Philox(np.uint64((seed ^ 0xC0DEC) & (2**64 - 1))))
        q = np.empty(vals.shape, np.int8)
        scales = np.empty(len(segments), np.float32)
        off = 0
        for i, size in enumerate(segments):
            seg = vals[off:off + size]
            scale = (float(np.max(np.abs(seg))) / self.LEVELS
                     if size else 0.0) or 1e-12
            scaled = _stochastic_round(seg / scale, rng)
            q[off:off + size] = np.clip(
                scaled, -self.LEVELS, self.LEVELS).astype(np.int8)
            scales[i] = scale
            off += size
        return {"q": q, "scale": scales}

    def decode(self, payload, n_vals, segments, codec):
        q = np.asarray(_require(payload, "q", codec))
        scales = np.asarray(_require(payload, "scale", codec),
                            np.float32).ravel()
        if q.dtype != np.int8 or q.shape != (n_vals,):
            raise CodecError(
                f"codec {codec!r}: bad quantized values "
                f"(dtype {q.dtype}, shape {q.shape} for {n_vals} slots)")
        if len(scales) != len(segments):
            raise CodecError(
                f"codec {codec!r}: {len(scales)} scales for "
                f"{len(segments)} tensor segments")
        out = np.empty(n_vals, np.float32)
        off = 0
        for scale, size in zip(scales, segments):
            out[off:off + size] = q[off:off + size].astype(np.float32) * scale
            off += size
        return out


class _IdentityStage:
    name = "fp32"

    def encode(self, vals, seed, segments):
        return {"q": vals.astype(np.float32)}

    def decode(self, payload, n_vals, segments, codec):
        q = np.asarray(_require(payload, "q", codec))
        if q.shape != (n_vals,):
            raise CodecError(
                f"codec {codec!r}: {q.shape} values for {n_vals} slots")
        return q.astype(np.float32)


# --------------------------------------------------------------------------
# Sparsifier stages (select which vector entries ship at all)


class _TopKStage:
    name = "topk"

    def __init__(self, ratio: float):
        if not 0 < ratio <= 1:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        self.ratio = ratio

    def k_of(self, n: int) -> int:
        return max(1, int(round(self.ratio * n))) if n else 0

    def select(self, vec, seed):
        n = vec.shape[0]
        k = self.k_of(n)
        if k >= n:
            idx = np.arange(n, dtype=np.int64)
        else:
            idx = np.argpartition(np.abs(vec), n - k)[n - k:]
            idx.sort()
        return idx, {"idx": idx.astype(np.int32)}

    def expand(self, payload, n, codec):
        idx = np.asarray(_require(payload, "idx", codec))
        if idx.ndim != 1 or idx.size > n:
            raise CodecError(
                f"codec {codec!r}: {idx.size} indices for an {n}-element "
                "model")
        idx = idx.astype(np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= n):
            raise CodecError(
                f"codec {codec!r}: index out of range for an {n}-element "
                "model — corrupt frame")
        return idx


class _RandMaskStage:
    name = "randmask"

    def __init__(self, ratio: float):
        if not 0 < ratio <= 1:
            raise ValueError(f"randmask ratio must be in (0, 1], got {ratio}")
        self.ratio = ratio

    def k_of(self, n: int) -> int:
        return max(1, int(round(self.ratio * n))) if n else 0

    def select(self, vec, seed):
        n = vec.shape[0]
        idx = _expand_mask(seed, n, self.k_of(n))
        # Only the seed + count cross the wire; the server re-expands.
        return idx, {"seed": int(seed & (2**64 - 1)), "k": int(idx.size)}

    def expand(self, payload, n, codec):
        seed = int(_require(payload, "seed", codec))
        k = int(_require(payload, "k", codec))
        if not 0 < k <= n:
            raise CodecError(
                f"codec {codec!r}: mask count {k} outside (0, {n}]")
        return _expand_mask(seed, n, k)


# --------------------------------------------------------------------------
# The codec chain


class WireCodec:
    """A parsed codec chain. ``encode`` maps an update pytree to a wire
    payload (plain string-keyed dict of arrays/scalars — exactly what the
    ``tensor`` frame encodes without pickling) plus the error-feedback
    residual; ``decode`` maps a payload back to a pytree shaped by the
    receiver's model spec."""

    def __init__(self, name: str, sparsifier, value_stage):
        self.name = name
        self.sparsifier = sparsifier
        self.value_stage = value_stage or _IdentityStage()
        #: Sparsifying chains are biased — the caller must carry the
        #: returned residual into its next update (EF-SGD).
        self.error_feedback = sparsifier is not None

    def stage_names(self) -> List[str]:
        out = [self.sparsifier.name] if self.sparsifier else []
        if not isinstance(self.value_stage, _IdentityStage):
            out.append(self.value_stage.name)
        return out

    # -- encode -------------------------------------------------------------
    def encode(self, update_tree, residual: Optional[np.ndarray] = None,
               seed: int = 0) -> Tuple[dict, Optional[np.ndarray]]:
        """``residual`` is the previous round's error-feedback carry (or
        None); ``seed`` keys the stochastic rounding and the randmask
        expansion, and must therefore be fresh per upload (derive it from
        round/client) but identical for a RESEND of the same upload.
        Returns ``(payload, new_residual)`` — new_residual is None for
        unbiased (non-sparsifying) chains."""
        vec = tree_to_vector_np(update_tree)
        spec_sizes = None
        if self.error_feedback and residual is not None:
            if residual.shape != vec.shape:
                raise ValueError(
                    f"error-feedback residual shape {residual.shape} does "
                    f"not match the update ({vec.shape}) — carries must "
                    "never cross clients or model shapes")
            vec = vec + residual
        payload = {"codec": self.name, "n": int(vec.shape[0]),
                   "seed": int(seed & (2**64 - 1))}
        if self.sparsifier is not None:
            idx, fields = self.sparsifier.select(vec, seed)
            payload.update(fields)
            vals = vec[idx]
            segments = [int(vals.shape[0])]
        else:
            vals = vec
            spec_sizes = self._dense_segments(update_tree)
            segments = spec_sizes
        payload.update(self.value_stage.encode(vals, seed, segments))
        new_residual = None
        if self.error_feedback:
            new_residual = vec - self._decode_vector(payload, vec.shape[0],
                                                     segments)
        return payload, new_residual

    @staticmethod
    def _dense_segments(tree) -> List[int]:
        import jax

        return [int(np.asarray(l).size) for l in jax.tree.leaves(tree)]

    # -- decode -------------------------------------------------------------
    def decode(self, payload, spec: TreeSpec):
        """Payload → pytree of numpy leaves in the spec's dtypes. Raises
        :class:`CodecError` on any inconsistency; never unpickles."""
        if not isinstance(payload, dict):
            raise CodecError(
                f"codec {self.name!r}: payload is "
                f"{type(payload).__name__}, expected a frame dict")
        n = int(_require(payload, "n", self.name))
        total = int(sum(spec.sizes))
        if n != total:
            raise CodecError(
                f"codec {self.name!r}: frame encodes an {n}-element model "
                f"but the receiver's spec has {total}")
        segments = ([None] if self.sparsifier is not None
                    else [int(s) for s in spec.sizes])
        vec = self._decode_vector(payload, n, segments)
        return vector_to_tree_np(vec, spec)

    def _decode_vector(self, payload, n: int, segments) -> np.ndarray:
        if self.sparsifier is not None:
            idx = self.sparsifier.expand(payload, n, self.name)
            vals = self.value_stage.decode(payload, int(idx.size),
                                           [int(idx.size)], self.name)
            vec = np.zeros(n, np.float32)
            vec[idx] = vals
            return vec
        return self.value_stage.decode(payload, n, segments, self.name)


class _NoWireCodec:
    """The uncompressed fallback — uniform object so callers can always
    hold a codec and branch on ``name``."""

    name = "none"
    error_feedback = False

    def encode(self, update_tree, residual=None, seed=0):
        return update_tree, None

    def decode(self, payload, spec: TreeSpec):
        return payload

    def stage_names(self) -> List[str]:
        return []


def _parse_stage(token: str):
    if token in ("bf16", "fp16"):
        return ("value", _CastStage(token))
    if token == "int8":
        return ("value", _Int8Stage())
    if token.startswith("topk"):
        try:
            ratio = float(token[4:])
        except ValueError:
            raise ValueError(
                f"bad wire-codec stage {token!r}: topk needs a ratio, "
                "e.g. topk0.01") from None
        return ("sparse", _TopKStage(ratio))
    if token.startswith("randmask"):
        try:
            ratio = float(token[8:])
        except ValueError:
            raise ValueError(
                f"bad wire-codec stage {token!r}: randmask needs a ratio, "
                "e.g. randmask0.01") from None
        return ("sparse", _RandMaskStage(ratio))
    raise ValueError(
        f"unknown wire-codec stage {token!r}; use bf16 | fp16 | int8 | "
        "topk<ratio> | randmask<ratio>, composable as sparsifier+value "
        "(e.g. topk0.01+int8)")


def make_wire_codec(spec: Optional[str]):
    """Parse a codec spec: ``none``, one stage, or ``sparsifier+value``
    (the sparsifier first — it decides WHAT ships, the value stage HOW).
    Must accept every name a codec generates for itself: frames carry
    ``codec.name`` and the server rebuilds the decoder from it."""
    if spec in (None, "", "none"):
        return _NoWireCodec()
    tokens = [t for t in spec.split("+") if t]
    sparsifier = None
    value_stage = None
    for tok in tokens:
        kind, stage = _parse_stage(tok)
        if kind == "sparse":
            if sparsifier is not None:
                raise ValueError(
                    f"wire codec {spec!r}: more than one sparsifier stage")
            if value_stage is not None:
                raise ValueError(
                    f"wire codec {spec!r}: the sparsifier must come first "
                    "(it decides what ships; the value stage encodes it)")
            sparsifier = stage
        else:
            if value_stage is not None:
                raise ValueError(
                    f"wire codec {spec!r}: more than one value stage")
            value_stage = stage
    return WireCodec("+".join(tokens), sparsifier, value_stage)


class CodecCache:
    """Per-connection decoder cache: frames self-describe their codec
    spec, and rebuilding a ``WireCodec`` per message would re-parse the
    chain on every upload. Shared by the sync and async servers so the
    decode discipline cannot diverge between tiers (each tier keeps its
    own REFUSAL policy — evict vs re-assign — on the raised
    :class:`CodecError`)."""

    def __init__(self):
        self._by_spec = {}

    def decode(self, spec_str: str, payload, spec: "TreeSpec"):
        codec = self._by_spec.get(spec_str)
        if codec is None:
            codec = self._by_spec[spec_str] = make_wire_codec(spec_str)
        # Span the pure-numpy frame decode separately from the servers'
        # enclosing ingest.decode (which also covers the O(model) delta
        # reconstruction) — the flight-recorder trace then attributes
        # codec cost vs tree_add cost per upload. Lazy import keeps the
        # comm package jax-free until a codec is actually used; the
        # tracer is the no-op NULL when tracing is off.
        from fedml_tpu.obs import trace as obs_trace

        with obs_trace.active().span("codec.decode", cat="codec",
                                     codec=spec_str):
            return codec.decode(payload, spec)


def negotiated_codec(requested: Optional[str], offer, *,
                     peer: str = "peer"):
    """Negotiate-once helper for the client managers: resolve the
    requested spec against the peer's handshake offer (loud fallback —
    see :func:`negotiate`) and return the ready codec object."""
    return make_wire_codec(negotiate(requested, offer, peer=peer))


def frame_seed(*vals: int) -> int:
    """Stable 64-bit seed from (run seed, epoch, round, client, ...) —
    PYTHONHASHSEED-proof, identical for a RESEND of the same upload (so a
    retransmitted frame is bit-identical and the server's idempotent
    ingest sees a true duplicate) and fresh across rounds/clients."""
    h = 0x9E3779B97F4A7C15
    for v in vals:
        h = ((h ^ (int(v) & 0xFFFFFFFFFFFFFFFF))
             * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


# --------------------------------------------------------------------------
# Negotiation


def codec_offer() -> List[str]:
    """What a peer advertises in the handshake (``OFFER_KEY``)."""
    return list(SUPPORTED_STAGES)


def require_delta_peer(offer_flag, *, peer: str = "peer") -> None:
    """Loud refusal of delta uploads against a delta-ignorant peer: a
    receiver that never advertised ``DELTA_OK_KEY`` would fold the delta
    frame AS a full model (or buffer a full model as a delta) —
    silently corrupting the global with no error anywhere. Unlike codec
    negotiation there is no safe fallback to degrade to: the sender's
    protocol (FedBuff's delta uploads, an adapter federation) REQUIRES
    delta semantics, so the connection must refuse, not limp."""
    if not offer_flag:
        raise ValueError(
            f"delta uploads required but the {peer} is delta-ignorant "
            f"(no {DELTA_OK_KEY!r} in its handshake): it would mis-fold "
            "a delta frame as a full model — upgrade the peer or run a "
            "full-model tier")


def require_secagg_peer(offer_flag, *, peer: str = "peer") -> None:
    """Loud refusal of masked uploads against a secagg-ignorant server:
    same shape as :func:`require_delta_peer`, stricter stakes. A client
    configured for secure aggregation that "degrades" to clear uploads
    has silently dropped the privacy property the run was configured
    for — and a secagg-ignorant server would decode the masked int64
    frame as model floats and corrupt the global. There is no fallback:
    the connection must refuse."""
    if not offer_flag:
        raise ValueError(
            f"secure aggregation required but the {peer} is "
            f"secagg-ignorant (no {SECAGG_OK_KEY!r} in its handshake): "
            "it would fold the masked int64 frame as a clear model — "
            "and uploading in the clear instead would silently drop the "
            "privacy the run was configured for; upgrade the peer or "
            "run with secagg off")


def stage_names_of(spec: str) -> List[str]:
    """The stage names a spec needs (validates the spec as a side effect)."""
    return make_wire_codec(spec).stage_names()


def negotiate(requested: Optional[str], offer, *, peer: str = "peer") -> str:
    """Resolve the codec to USE for a connection: the requested spec when
    the peer's offer covers every stage, else ``"none"`` — logged loudly,
    so a codec-ignorant peer (no ``OFFER_KEY`` in its handshake: an older
    build, or a hand-rolled client) degrades to the plain tensor wire
    visibly instead of silently shipping frames it cannot decode."""
    if requested in (None, "", "none"):
        return "none"
    needed = set(stage_names_of(requested))
    if offer is None:
        log.warning(
            "wire codec %r requested but the %s is codec-ignorant (no "
            "codec offer in its handshake): falling back to the "
            "uncompressed tensor wire", requested, peer)
        return "none"
    missing = needed - {str(s) for s in offer}
    if missing:
        log.warning(
            "wire codec %r requested but the %s does not support stage(s) "
            "%s (offer: %s): falling back to the uncompressed tensor wire",
            requested, peer, sorted(missing), sorted(offer))
        return "none"
    return requested
