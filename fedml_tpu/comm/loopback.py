"""In-memory loopback backend.

The fake/test backend the reference never had (SURVEY.md §4.6 — its nearest
substitute is running real ``mpirun`` on one machine). A
``LoopbackNetwork`` owns one queue per rank; managers send by enqueueing
directly to the receiver's queue and receive by blocking on their own —
event-driven, unlike the reference's MPI manager which polls its receive
queue every 0.3 s (mpi/com_manager.py:78). Messages are delivered by
reference (no serialization) which also makes this the fastest possible
single-host multi-worker transport; use ``Message.to_json`` round-trip in
tests to exercise the wire format.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.wire import (ByteLedger, WIRE_FORMATS,
                                 deserialize_message, serialize_message)

_STOP = object()


class LoopbackNetwork:
    """Shared router: one inbox per rank. Thread-safe.

    ``wire`` (default ``"none"``): with a real wire format name
    (``tensor`` | ``json`` | ``pickle``) every message is serialized by
    the sender and deserialized by the receiver — BYTES sit in the
    inboxes, each manager's :class:`ByteLedger` counts them, and the
    single-host drill exercises the exact frame code the socket backends
    ship. The default keeps delivery by reference (the fastest possible
    transport, zero serialization)."""

    def __init__(self, size: int, wire: str = "none"):
        if wire not in ("none",) + WIRE_FORMATS:
            raise ValueError(f"unknown loopback wire format {wire!r}")
        self.size = size
        self.wire = wire
        self._inboxes: List[queue.Queue] = [queue.Queue() for _ in range(size)]

    def post(self, receiver_id: int, msg) -> None:
        self._inboxes[receiver_id].put(msg)

    def inbox(self, rank: int) -> queue.Queue:
        return self._inboxes[rank]


class LoopbackCommManager(BaseCommunicationManager):
    def __init__(self, network: LoopbackNetwork, rank: int):
        self.network = network
        self.rank = rank
        self.size = network.size
        self.bytes_ledger = ByteLedger()
        self._observers: List[Observer] = []
        self._running = False
        self._stop_requested = False

    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        if self.network.wire != "none":
            blob = serialize_message(msg, self.network.wire)
            self.bytes_ledger.count_tx(receiver, len(blob))
            self.network.post(receiver, blob)
            return
        self.network.post(receiver, msg)

    def inbox_depth(self) -> int:
        """Messages waiting in this rank's inbox — the ingest-queue-depth
        gauge the server's metrics registry samples per upload
        (docs/OBSERVABILITY.md). Approximate by nature (qsize races the
        receive loop), which is fine for a gauge."""
        return self.network.inbox(self.rank).qsize()

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        # Stop-before-start: the _STOP sentinel is already queued, but the
        # latch also covers it without draining whatever preceded it.
        self._running = not self._stop_requested
        inbox = self.network.inbox(self.rank)
        while self._running:
            msg = inbox.get()
            if msg is _STOP:
                break
            if isinstance(msg, (bytes, bytearray)):  # wire round-trip mode
                nbytes = len(msg)
                msg = deserialize_message(msg, self.network.wire)
                self.bytes_ledger.count_rx(int(msg.get_sender_id()), nbytes)
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._stop_requested = True  # latched: stop-before-start must hold
        self._running = False
        self.network.post(self.rank, _STOP)


def run_workers(worker_fns) -> None:
    """Run one callable per rank on daemon threads and join them all.
    Single-host analogue of ``mpirun -np N`` (run_fedavg_distributed_pytorch
    .sh:21); exceptions in any worker are re-raised in the caller."""
    errors: Dict[int, BaseException] = {}

    def wrap(i, fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                errors[i] = e

        return run

    threads = [
        threading.Thread(target=wrap(i, fn), daemon=True)
        for i, fn in enumerate(worker_fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        rank, err = sorted(errors.items())[0]
        raise RuntimeError(f"worker rank {rank} failed") from err
