"""Parallel server-ingest pool: decode workers + associative-exact folds.

PR 11 measured the wall this module breaks: every upload funnels through
ONE single-threaded dispatch loop doing codec decode + delta
reconstruction + accumulator fold, and ``ingest_occupancy`` on the bench
drill sits at ~0.78 — the dispatch thread IS the serving ceiling, the
software analogue of the server-side ingest bottleneck PAPERS.md
"Performance Improvement of Federated Learning Server using Smart NIC"
(arXiv:2307.06561) names as *the* FL scaling limit. The decode and fold
are pure numpy over model-sized arrays — exactly the work CPython
releases the GIL for — so a bounded pool of threads pulls them off the
dispatch path while the control plane (dedupe, membership, heartbeats,
replies) stays single-threaded and unchanged.

**Why the fold can be parallel at all.** A floating-point running sum is
not associative: per-worker partial accumulators merged at flush would
regroup the additions and drift from the single-threaded fold by a few
ulps per upload — and WHICH worker folded WHICH upload depends on thread
scheduling, so the drift would be nondeterministic. The pool therefore
accumulates in **fixed-point int64** (:data:`SCALE_BITS` fraction bits):
each weighted contribution ``w * x`` is computed in float64 and rounded
ONCE onto the fixed-point grid — a per-upload operation with no order
dependence — and everything after that is integer addition, which IS
associative and commutative. Any partitioning of uploads across any
number of workers, folded in any interleaving, merges to the identical
bits; the permutation-matrix tests in tests/test_ingest_pool.py pin
pooled == serial across arrival orders × worker counts. The cost is a
one-time quantization of each contribution to ``2**-SCALE_BITS``
absolute resolution (~1e-9; far below fp32's own rounding at the
magnitudes model updates live at), paid identically by the 1-worker
"serial" pool — ``ingest_workers=1`` is the reference arm the bit-equal
pins compare against, and ``ingest_workers=0`` keeps the legacy inline
float path untouched.

Failure containment: a task that raises (a corrupt codec frame —
``CodecError``) is recorded with its metadata and surfaced to the
dispatch thread at the next :meth:`IngestPool.drain` barrier; the server
tiers apply their evict-and-release refusal policy there, so a poisoned
frame can never wedge the pool or silently zero into the mean.

Observability: each task runs under an ``ingest.pool`` span (worker id +
the upload's correlation key) in the installed tracer, task latency
lands in the owning server's ``pool_task_ms`` registry histogram, and
:meth:`IngestPool.profile` reports per-worker busy seconds / occupancy +
task counts for ``ingest_profile()`` (docs/OBSERVABILITY.md).

Deliberately jax-free at import time (like the rest of the comm
package); the only jax use is the lazy pytree flatten/unflatten at the
finalize boundary.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

#: Fixed-point fraction bits of the exact accumulator grid. 2**-30 ≈
#: 9.3e-10 absolute resolution per contribution.
SCALE_BITS = 30
_SCALE = float(2 ** SCALE_BITS)
#: Per-contribution saturation bound: |w * x| caps at 2**(50-30) ≈ 1e6,
#: leaving 2**13 uploads of headroom before an int64 partial could
#: overflow (the serving tiers flush every round / every buffer_k — far
#: below that).
_CLIP = float(2 ** 50)


def quantize_contribution(x: np.ndarray, weight: float = 1.0) -> np.ndarray:
    """One contribution → the int64 fixed-point grid: compute
    ``float32(x) * float32(weight * 2^SCALE_BITS)`` (single-precision —
    the inputs are fp32 model updates, so the product carries their own
    precision at half the memory traffic of an f64 pipeline), clamp,
    then TRUNCATE toward zero (the C cast). Truncation instead of
    round-to-nearest keeps the hot fold free of ``np.rint`` at the cost
    of ≤1 grid step of bias per contribution. Non-finite entries map to
    0 deterministically (the buffered tier's nan_guard already
    weight-zeroes non-finite deltas; this keeps an unguarded NaN from
    turning the exact integer sum into platform-defined garbage) and the
    magnitude saturates at ``_CLIP``. The reference semantics of
    :meth:`PartialAccumulator.add` — every step is a deterministic
    elementwise function of ONE contribution, which is what makes the
    integer accumulation order-invariant."""
    q = np.asarray(x, np.float32) * np.float32(weight * _SCALE)
    q = np.nan_to_num(q, nan=0.0, posinf=_CLIP, neginf=-_CLIP)
    return np.clip(q, -_CLIP, _CLIP).astype(np.int64)


def quantize_weight(w: float) -> int:
    w = float(w)
    if not np.isfinite(w):
        return 0
    return int(np.clip(np.rint(w * _SCALE), 0.0, _CLIP))


def finalize_partial_mean(total: "PartialAccumulator", ref_tree, dtype=None):
    """The ONE place a fixed-point partial becomes a float mean: return
    ``(mean_tree, count)`` — the weighted mean ``Σ w·x / Σ w`` as numpy
    leaves shaped/ordered by ``ref_tree``, cast to each reference leaf's
    dtype (or ``dtype`` for every leaf). ``mean_tree`` is ``None`` when
    nothing (or only weight-zero contributions) accumulated.

    Module-level because TWO finalize sites must agree to the bit:
    :meth:`IngestPool.finalize_mean` (the in-process pool) and the shard
    coordinator's wire merge (``comm/shardplane.py``), whose bit-equality
    contract is "same int64 totals → same mean" BY CONSTRUCTION — both
    call here, so there is no second copy of the division to drift."""
    import jax

    count = total.count
    if total.leaves is None or total.wsum <= 0:
        return None, count
    ref_leaves, treedef = jax.tree.flatten(ref_tree)
    if len(ref_leaves) != len(total.leaves):
        raise ValueError(
            f"pooled accumulator holds {len(total.leaves)} leaves but "
            f"the reference model has {len(ref_leaves)}")
    inv = 1.0 / (total.wsum / _SCALE)
    out = []
    for r, acc in zip(ref_leaves, total.leaves):
        mean = (acc / _SCALE) * inv
        d = dtype if dtype is not None else np.asarray(r).dtype
        out.append(mean.reshape(np.shape(r)).astype(d))
    return jax.tree.unflatten(treedef, out), count


class FixedContribution:
    """A contribution ALREADY on the int64 fixed-point grid — the masked
    secure-aggregation frames (``comm/secagg.py``): the client quantized
    ``w·x`` with :func:`quantize_contribution` semantics itself, then
    added pairwise masks that span the FULL int64 range, so the server
    fold must be a raw modular int64 add — re-quantizing, clipping, or
    range-checking a pre-cancellation masked frame would break the exact
    mask cancellation (and leak that a value was large). ``qweight`` is
    the already-quantized weight (:func:`quantize_weight`), ``count``
    the membership delta (0 for a server-side mask correction, which
    adds leaves without representing an upload), ``clipped`` the
    client-counted envelope saturations to roll into ``saturated`` (the
    client runs the same quantization clip the server pool would, and
    ships the count in the clear — it is weight metadata, not update
    content)."""

    __slots__ = ("leaves", "qweight", "count", "clipped")

    def __init__(self, leaves: List[np.ndarray], qweight: int,
                 count: int = 1, clipped: int = 0):
        self.leaves = leaves
        self.qweight = int(qweight)
        self.count = int(count)
        self.clipped = int(clipped)


class PartialAccumulator:
    """One worker's running Σ w_i·x_i (int64 leaves) + Σ w_i (int).
    Single-writer (its owning pool worker); merged under the pool lock at
    the drain barrier.

    Allocation-free on the hot path: per-leaf float64 scratch buffers are
    allocated once (first contribution) and every later fold runs
    in-place (``out=`` / ``copyto``). This is a throughput requirement,
    not a nicety — a model-sized temporary per numpy op crosses glibc's
    mmap threshold, and the resulting page-fault + allocator churn both
    dominates the fold cost and serializes the pool on the allocator's
    GIL-held sections (measured: the naive fold was ~30x slower and flat
    across workers).

    The computed contribution is ``trunc((x [+ base]) * w * 2^SCALE_BITS)``
    evaluated in float32 (:func:`quantize_contribution`) — a per-upload
    value with NO dependence on fold order — then clamped (non-finite →
    0, magnitude → ±2^50) and added in int64, where addition is exact
    and associative. ``base`` lets the sync tier fold
    ``w * (broadcast_anchor + delta)`` without materializing the
    reconstruction."""

    __slots__ = ("leaves", "wsum", "count", "saturated", "_buf", "_ibuf",
                 "_bool")

    def __init__(self):
        self.leaves: Optional[List[np.ndarray]] = None
        self.wsum = 0
        self.count = 0
        #: Contributions whose FINITE values (or weight) exceeded the
        #: ±2^50 grid envelope and were clamped — silent clipping would
        #: mis-weight large-sample silos relative to the inline fold,
        #: so saturation is counted (surfaced via IngestPool.profile()
        #: + a once-per-pool warning) instead of swallowed.
        self.saturated = 0
        self._buf: Optional[List[np.ndarray]] = None
        self._ibuf: Optional[List[np.ndarray]] = None
        self._bool: Optional[List[np.ndarray]] = None

    def _ensure(self, leaves) -> None:
        if self.leaves is None:
            self.leaves = [np.zeros(np.shape(l), np.int64) for l in leaves]
            self._buf = [np.empty(np.shape(l), np.float32) for l in leaves]
            self._ibuf = [np.empty(np.shape(l), np.int64) for l in leaves]
            self._bool = [np.empty(np.shape(l), bool) for l in leaves]
        elif len(leaves) != len(self.leaves):
            raise ValueError(
                f"contribution has {len(leaves)} leaves, accumulator holds "
                f"{len(self.leaves)} — uploads must share one model")

    def add(self, leaves: List[np.ndarray], weight: float,
            base: Optional[List[np.ndarray]] = None) -> None:
        # quantize_contribution(leaf [+ base], w) per element, on
        # preallocated float32 scratch. The truncation to the grid
        # happens PER CONTRIBUTION (the int64 scratch cast) before the
        # exact int64 accumulate — truncating a running float sum
        # instead would make the result depend on fold order.
        w = float(weight)
        ws = np.float32(w * _SCALE)
        # At most ONE saturation count per contribution, whether the
        # weight or any value tripped the envelope.
        clipped = bool(np.isfinite(w) and abs(w) * _SCALE > _CLIP)
        self._ensure(leaves)
        for i, leaf in enumerate(leaves):
            buf, acc = self._buf[i], self.leaves[i]
            if base is not None:
                # The sync tier's w*(anchor + delta), summed at value
                # scale before scaling (best f32 conditioning).
                np.add(np.asarray(leaf), np.asarray(base[i]), out=buf,
                       casting="unsafe")
            else:
                np.copyto(buf, np.asarray(leaf), casting="unsafe")
            np.multiply(buf, ws, out=buf)
            # Deterministic containment: NaN → 0 (rare path — one bool
            # reduction gates it), ±inf/huge → saturate at the clip.
            fin = self._bool[i]
            np.isfinite(buf, out=fin)
            if not fin.all():
                np.nan_to_num(buf, copy=False, nan=0.0, posinf=_CLIP,
                              neginf=-_CLIP)
            elif not clipped and buf.size and \
                    float(np.max(np.abs(buf))) > _CLIP:
                # FINITE values beyond the grid envelope: the clip below
                # distorts this contribution's weight in the mean —
                # count it so the envelope is observable (non-finite
                # containment above is by design and not counted).
                clipped = True
            np.clip(buf, -_CLIP, _CLIP, out=buf)
            # Exact truncation onto the int grid, then exact int64 sum.
            ib = self._ibuf[i]
            np.copyto(ib, buf, casting="unsafe")
            np.add(acc, ib, out=acc)
        if clipped:
            self.saturated += 1
        self.wsum += quantize_weight(w)
        self.count += 1

    def add_fixed(self, fixed: FixedContribution) -> None:
        """Fold a :class:`FixedContribution`: raw MODULAR int64 leaf adds
        (the uint64 bit view — two's-complement wraparound with no numpy
        warning machinery in the loop), no float path, no clip, no
        envelope count. Masked secagg frames sit anywhere in the int64
        range by construction; clamping one would destroy the exact
        pairwise-mask cancellation the whole protocol rests on. The
        envelope becomes checkable only AFTER cancellation — see
        :meth:`envelope_overflow`, run by the finalize sites on the
        merged total."""
        leaves = fixed.leaves
        if leaves is not None:
            self._ensure(leaves)
            for i, leaf in enumerate(leaves):
                acc = self.leaves[i]
                lf = np.asarray(leaf)
                if lf.dtype != np.int64:
                    raise ValueError(
                        f"fixed contribution leaf {i} has dtype {lf.dtype}, "
                        "expected int64 — a masked frame that lost its grid "
                        "dtype on the wire cannot be folded")
                if lf.shape != acc.shape:
                    raise ValueError(
                        f"fixed contribution leaf {i} has shape {lf.shape}, "
                        f"accumulator holds {acc.shape}")
                np.add(acc.view(np.uint64),
                       np.ascontiguousarray(lf).view(np.uint64),
                       out=acc.view(np.uint64))
        self.wsum += fixed.qweight
        self.count += fixed.count
        self.saturated += fixed.clipped

    def envelope_overflow(self) -> int:
        """Post-cancellation envelope headroom check for the masked
        fold: once every pairwise mask has cancelled (or been corrected
        away), the merged total must satisfy ``|leaf| <= count * 2^50``
        — each of ``count`` contributions was clamped to ±2^50 at
        quantization, so a residual beyond that bound means uncancelled
        mask mass (a protocol bug, a forged frame) or genuine int64
        wraparound of the sum. COUNTED into ``saturated`` (one bump per
        check that found any overflow, mirroring the per-contribution
        convention of :meth:`add`), never clamped: the finalize sites
        report it through the same ``saturated`` rollup the shardplane
        wire frame already carries. Returns the number of offending
        elements."""
        if self.leaves is None or self.count <= 0:
            return 0
        bound = int(self.count) * int(_CLIP)
        over = 0
        for acc in self.leaves:
            over += int(np.count_nonzero(acc > bound)
                        + np.count_nonzero(acc < -bound))
        if over:
            self.saturated += 1
        return over

    def merge_into(self, other: "PartialAccumulator") -> None:
        """Exact merge: int64 leaf adds + scalar sums. The scalar tallies
        — ``wsum``, ``count`` AND ``saturated`` — propagate even when
        this partial never folded a leaf (an accumulator fresh off
        ``reset()`` still carries its monotone saturation count; dropping
        it at merge boundaries is how fleet-wide saturation used to
        vanish from pooled health reports)."""
        other.wsum += self.wsum
        other.count += self.count
        other.saturated += self.saturated
        if self.leaves is None:
            return
        if other.leaves is None:
            other.leaves = [l.copy() for l in self.leaves]
        else:
            for a, b in zip(other.leaves, self.leaves):
                a += b

    def reset(self) -> None:
        # Keep the allocated leaves/scratch (zeroed in place) — reset
        # runs at every flush, and reallocating model-sized buffers per
        # round would reintroduce the allocator churn documented above.
        # ``saturated`` survives resets: it is monotone telemetry, not
        # window state.
        if self.leaves is not None:
            for a in self.leaves:
                a.fill(0)
        self.wsum = 0
        self.count = 0


class IngestPool:
    """Bounded pool of decode+fold workers for the message-passing
    servers (``cfg.ingest_workers``).

    The dispatch thread stays the only control-plane writer: it
    ``submit``\\ s one task per accepted upload (the task closure does
    the codec decode / delta reconstruction and returns ``(leaves,
    weight)``), and at every round/buffer flush it calls :meth:`drain`
    (barrier) then :meth:`finalize_mean` (exact merge of the per-worker
    partials, the ONE division, cast back to the reference dtypes).
    Worker→upload assignment is whichever thread pops the queue first —
    irrelevant to the result, because the partial folds are
    associative-exact (module docstring).

    ``run`` is the synchronous escape hatch for tiers whose fold cannot
    be deferred (pure async mixes every arrival into the global
    immediately): the callable executes on a pool worker, the caller
    blocks for its result, and exceptions re-raise in the caller — the
    tier's existing inline refusal policy applies unchanged.
    """

    _STOP = object()

    def __init__(self, workers: int, registry=None, queue_cap: int = 0):
        workers = int(workers)
        if workers < 1:
            raise ValueError(f"ingest pool needs >= 1 worker, got {workers}")
        self.workers = workers
        self._q: "queue.Queue" = queue.Queue(
            maxsize=(queue_cap or workers * 8))
        self.partials = [PartialAccumulator() for _ in range(workers)]
        self._busy_s = [0.0] * workers
        self._tasks = [0] * workers
        self._t0: Optional[float] = None
        self._t1: Optional[float] = None
        self._failures: List[Tuple[Dict, BaseException]] = []
        self._pending = 0
        self._cv = threading.Condition()
        self._lock = threading.Lock()  # stats + failures + merge
        self._h_task = (registry.histogram("pool_task_ms")
                        if registry is not None else None)
        self._warned_saturation = False
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, args=(i,), daemon=True,
                             name=f"ingest-pool-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    # -- worker side ---------------------------------------------------------
    def _worker(self, i: int) -> None:
        from fedml_tpu.obs import trace as obs_trace

        # Under the lock: resize() appends to self.partials concurrently
        # (worker i's own slot always exists before its thread starts).
        with self._lock:
            partial = self.partials[i]
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            fn, meta, sink = item
            t0 = time.perf_counter()
            with self._lock:
                if self._t0 is None:
                    self._t0 = t0
            try:
                with obs_trace.active().span("ingest.pool", cat="ingest",
                                             worker=i, **meta):
                    out = fn()
                    if sink is None:
                        if isinstance(out, FixedContribution):
                            # Already on the int64 grid (masked secagg
                            # frames / mask corrections): modular add,
                            # no float path, no clip.
                            partial.add_fixed(out)
                        else:
                            # (leaves, weight) or (leaves, weight, base)
                            # — base folds w*(base+leaf) without
                            # materializing the reconstruction (the sync
                            # tier's deltas).
                            if len(out) == 3:
                                leaves, w, base = out
                            else:
                                (leaves, w), base = out, None
                            partial.add(leaves, w, base=base)
            except BaseException as e:  # noqa: BLE001 — surfaced at drain
                if sink is not None:
                    sink["err"] = e
                else:
                    with self._lock:
                        self._failures.append((meta, e))
            else:
                if sink is not None:
                    sink["out"] = out
            finally:
                t1 = time.perf_counter()
                with self._lock:
                    self._busy_s[i] += t1 - t0
                    self._tasks[i] += 1
                    self._t1 = t1
                    if self._h_task is not None:
                        self._h_task.record((t1 - t0) * 1e3)
                if sink is not None:
                    sink["done"].set()
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()

    # -- dispatch side -------------------------------------------------------
    def submit(self, fn: Callable[[], Tuple[List[np.ndarray], float]],
               **meta) -> None:
        """Enqueue one upload's decode+fold. ``fn`` runs on a pool worker
        and returns ``(numpy leaves, weight)``; a raise is recorded with
        ``meta`` and surfaced at the next :meth:`drain`. Blocks when the
        bounded queue is full — natural backpressure on the dispatch
        thread."""
        if self._closed:
            raise RuntimeError("ingest pool is closed")
        with self._cv:
            self._pending += 1
        self._q.put((fn, meta, None))

    def run(self, fn: Callable, **meta):
        """Execute ``fn`` on a pool worker and block for its result
        (exceptions re-raise here). No fold — the synchronous decode
        path for the pure-async tier."""
        if self._closed:
            return fn()
        sink = {"done": threading.Event()}
        with self._cv:
            self._pending += 1
        self._q.put((fn, meta, sink))
        sink["done"].wait()
        if "err" in sink:
            raise sink["err"]
        return sink["out"]

    def drain(self) -> List[Tuple[Dict, BaseException]]:
        """Barrier: wait until every submitted task has completed, then
        return (and clear) the failure list — the flush-time hook where
        the server tiers apply their refusal policy."""
        with self._cv:
            while self._pending > 0:
                self._cv.wait()
        with self._lock:
            failures, self._failures = self._failures, []
        if not self._warned_saturation and any(
                p.saturated for p in self.partials):
            self.profile()  # emits the once-per-pool saturation warning
        return failures

    def queue_depth(self) -> int:
        return self._q.qsize()

    def resize(self, workers: int) -> None:
        """Grow the pool to ``workers`` (the autoscaling actuation).

        Growing is exact and safe mid-stream: a new worker gets its own
        ``PartialAccumulator`` + stats slots and starts pulling from the
        shared queue, and since the partial folds are associative-exact
        the merged mean is bit-identical for any worker count. SHRINK is
        refused — retiring a worker would strand its accumulated partial
        (or force a mid-round merge off the dispatch thread), so the
        actuation seam surfaces it as a named refusal instead."""
        workers = int(workers)
        if self._closed:
            raise RuntimeError("ingest pool is closed")
        if workers < self.workers:
            raise ValueError(
                f"ingest pool shrink unsupported ({self.workers} -> {workers}): "
                "a retiring worker would strand its partial accumulator")
        with self._lock:
            start = self.workers
            for i in range(start, workers):
                self.partials.append(PartialAccumulator())
                self._busy_s.append(0.0)
                self._tasks.append(0)
            self.workers = workers
        for i in range(start, workers):
            t = threading.Thread(target=self._worker, args=(i,), daemon=True,
                                 name=f"ingest-pool-{i}")
            self._threads.append(t)
            t.start()

    def reset(self) -> None:
        """Drop all accumulated partials (callers drain first)."""
        for p in self.partials:
            p.reset()

    def merge_partials(self) -> PartialAccumulator:
        """Exact merge of the per-worker partials into ONE fresh
        accumulator, resetting the workers — the flush-time export for
        the sharded aggregation plane (``comm/shardplane.py``): the
        shard ships the merged int64 partial over the wire and the
        COORDINATOR finalizes, so the division happens exactly once per
        round no matter how many processes folded. Because the
        per-worker ``saturated`` tallies are monotone across resets, the
        returned total's ``saturated`` is the pool's LIFETIME saturation
        count at this flush (a gauge, not a delta). Callers must
        :meth:`drain` first."""
        total = PartialAccumulator()
        with self._lock:
            for p in self.partials:
                p.merge_into(total)
            self.reset()
        return total

    def finalize_mean(self, ref_tree, dtype=None):
        """Merge the per-worker partials exactly and return
        ``(mean_tree, count)``: the weighted mean ``Σ w·x / Σ w`` as
        numpy leaves shaped/ordered by ``ref_tree``, cast to each
        reference leaf's dtype (or ``dtype`` for every leaf — the
        buffered tier keeps its delta in float32). ``mean_tree`` is
        ``None`` when nothing (or only weight-zero contributions)
        accumulated — the caller keeps its previous net, the
        all-excluded contract. Resets the partials either way. Callers
        must :meth:`drain` first."""
        return finalize_partial_mean(self.merge_partials(), ref_tree,
                                     dtype=dtype)

    # -- observability -------------------------------------------------------
    def profile(self) -> Dict[str, object]:
        """Per-worker occupancy for ``ingest_profile()``: busy seconds ÷
        (first-task-start → last-task-end span), plus task counts."""
        with self._lock:
            span = ((self._t1 - self._t0)
                    if self._t0 is not None and self._t1 is not None
                    else 0.0)
            busy = list(self._busy_s)
            tasks = list(self._tasks)
        saturated = int(sum(p.saturated for p in self.partials))
        if saturated and not self._warned_saturation:
            self._warned_saturation = True
            log.warning(
                "ingest pool: %d contribution(s) had finite values or "
                "weights beyond the ±2^%d fixed-point envelope and were "
                "CLAMPED — their weight in the mean is distorted relative "
                "to the inline fold (huge sample counts or diverged "
                "updates; consider ingest_workers=0 or rescaling weights)",
                saturated, 50)
        return {
            "workers": self.workers,
            "tasks": int(sum(tasks)),
            "tasks_per_worker": tasks,
            "busy_s_per_worker": [round(b, 4) for b in busy],
            "occupancy_per_worker": ([round(b / span, 4) for b in busy]
                                     if span > 0 else None),
            "span_s": round(span, 4),
            "saturated_contributions": saturated,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._q.put(self._STOP)
        for t in self._threads:
            t.join(timeout=5.0)
