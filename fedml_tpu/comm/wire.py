"""Message ↔ bytes wire serialization shared by the socket-level backends
(tcp, grpc_backend, trpc).

Three formats, selected per manager:

- ``pickle`` — pickled ``Message`` param dict, the same wire content the
  reference's MPI backend ships (mpi_send_thread.py:27). Fast; assumes
  TRUSTED silo peers.
- ``json`` — ``Message.to_json`` (message.py:5-74 parity), safe against
  malicious payloads; the format for untrusted/mobile edges (is_mobile
  nested-list encoding included).
- ``tensor`` — TENSOR-AWARE framing, the TensorPipe role (the reference's
  TRPC backend exists to move tensors without pickling them): a JSON
  header describing the nested structure + the arrays' raw buffers
  appended verbatim. Arrays (numpy/jax, any dtype incl. bfloat16) are
  never pickled — decode is ``np.frombuffer`` per buffer — and the
  format is safe to parse (no code execution). NetState payloads are
  first-class.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Dict

import numpy as np

from fedml_tpu.comm.message import Message, _np_dtype

WIRE_FORMATS = ("pickle", "json", "tensor")


class ByteLedger:
    """Per-peer bytes-on-wire counters — the ONE shared hook every
    backend taps where it calls ``serialize_message`` /
    ``deserialize_message`` (tcp / grpc / trpc / mqtt, plus the loopback
    wire round-trip mode). No bytes-on-wire observability existed before;
    the wire-codec A/B and the server's per-round ``health()`` metrics
    read these. Thread-safe: send paths and receive loops run on
    different threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tx: Dict[int, int] = {}  # peer rank -> bytes sent to it
        self.rx: Dict[int, int] = {}  # peer rank -> bytes received from it

    def count_tx(self, peer: int, nbytes: int) -> None:
        with self._lock:
            self.tx[peer] = self.tx.get(peer, 0) + int(nbytes)

    def count_rx(self, peer: int, nbytes: int) -> None:
        with self._lock:
            self.rx[peer] = self.rx.get(peer, 0) + int(nbytes)

    @property
    def total_tx(self) -> int:
        with self._lock:
            return sum(self.tx.values())

    @property
    def total_rx(self) -> int:
        with self._lock:
            return sum(self.rx.values())

    def totals(self) -> Dict[str, int]:
        return {"bytes_tx": self.total_tx, "bytes_rx": self.total_rx}


def _encode_obj(obj, bufs):
    from fedml_tpu.trainer.local import NetState

    if isinstance(obj, NetState):
        return {"t": "net", "p": _encode_obj(obj.params, bufs),
                "s": _encode_obj(obj.model_state, bufs)}
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                # json would silently stringify int keys (3 → "3"),
                # diverging from the pickle wire; fail loudly instead.
                raise TypeError(
                    f"tensor wire requires string dict keys, got "
                    f"{type(k).__name__} key {k!r}")
        return {"t": "d", "v": {k: _encode_obj(v, bufs)
                                for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"t": "l" if isinstance(obj, list) else "tu",
                "v": [_encode_obj(v, bufs) for v in obj]}
    if obj is None or isinstance(obj, (bool, str, int, float)):
        return {"t": "s", "v": obj}
    if hasattr(obj, "__array__"):  # numpy / jax arrays, numpy scalars
        arr = np.asarray(obj)
        if arr.dtype.byteorder == ">":
            # dtype.name drops byte order; normalize to native so the
            # decoder's frombuffer reads the values it was sent.
            arr = arr.astype(arr.dtype.newbyteorder("="))
        bufs.append(arr.tobytes())
        return {"t": "a", "dtype": arr.dtype.name, "shape": list(arr.shape)}
    raise TypeError(
        f"tensor wire cannot encode {type(obj).__name__} (arrays, "
        "dicts/lists/tuples, scalars and NetState only — no pickling)")


def _decode_obj(node, bufs, pos):
    """Returns (value, next_buffer_index)."""
    t = node["t"]
    if t == "net":
        from fedml_tpu.trainer.local import NetState

        p, pos = _decode_obj(node["p"], bufs, pos)
        s, pos = _decode_obj(node["s"], bufs, pos)
        return NetState(p, s), pos
    if t == "d":
        out = {}
        for k, v in node["v"].items():
            out[k], pos = _decode_obj(v, bufs, pos)
        return out, pos
    if t in ("l", "tu"):
        items = []
        for v in node["v"]:
            item, pos = _decode_obj(v, bufs, pos)
            items.append(item)
        return (items if t == "l" else tuple(items)), pos
    if t == "s":
        return node["v"], pos
    if t == "a":
        # .copy(): frombuffer over a bytes slice is read-only, and the
        # pickle/json wire formats hand receivers writable arrays — a
        # receiver mutating params in place must behave identically on
        # every wire. One memcpy per tensor.
        arr = np.frombuffer(bufs[pos], dtype=_np_dtype(node["dtype"]))
        return arr.reshape(node["shape"]).copy(), pos + 1
    raise ValueError(f"tensor wire: unknown node type {t!r}")


def _tensor_encode(params: dict) -> bytes:
    bufs: list = []
    meta = _encode_obj(params, bufs)
    header = json.dumps({"meta": meta,
                         "lens": [len(b) for b in bufs]}).encode()
    return struct.pack("<I", len(header)) + header + b"".join(bufs)


def _tensor_decode(payload: bytes) -> dict:
    (hlen,) = struct.unpack_from("<I", payload)
    header = json.loads(payload[4:4 + hlen].decode())
    # memoryview slices are zero-copy, so the .copy() in _decode_obj's
    # array branch is the only memcpy per tensor.
    view = memoryview(payload)
    bufs, off = [], 4 + hlen
    for n in header["lens"]:
        bufs.append(view[off:off + n])
        off += n
    out, used = _decode_obj(header["meta"], bufs, 0)
    if used != len(bufs):  # not assert: must survive python -O
        raise ValueError(
            f"tensor wire: header declares {len(bufs)} buffers but the "
            f"structure consumed {used} — corrupted or truncated frame")
    return out


def serialize_message(msg: Message, wire: str) -> bytes:
    if wire == "pickle":
        import pickle

        return pickle.dumps(msg.get_params(), protocol=pickle.HIGHEST_PROTOCOL)
    if wire == "json":
        return msg.to_json().encode()
    if wire == "tensor":
        return _tensor_encode(msg.get_params())
    raise ValueError(f"unknown wire format {wire!r}")


def deserialize_message(payload: bytes, wire: str) -> Message:
    if wire == "pickle":
        import pickle

        msg = Message()
        msg.init(pickle.loads(payload))
        return msg
    if wire == "json":
        return Message.from_json(payload.decode())
    if wire == "tensor":
        msg = Message()
        msg.init(_tensor_decode(payload))
        return msg
    raise ValueError(f"unknown wire format {wire!r}")
