"""Message ↔ bytes wire serialization shared by the socket-level backends
(tcp, grpc_backend).

Two formats, selected per manager and auto-detectable per frame:

- ``pickle`` — pickled ``Message`` param dict, the same wire content the
  reference's MPI backend ships (mpi_send_thread.py:27). Fast; assumes
  TRUSTED silo peers.
- ``json`` — ``Message.to_json`` (message.py:5-74 parity), safe against
  malicious payloads; the format for untrusted/mobile edges (is_mobile
  nested-list encoding included).
"""

from __future__ import annotations

from fedml_tpu.comm.message import Message

WIRE_FORMATS = ("pickle", "json")


def serialize_message(msg: Message, wire: str) -> bytes:
    if wire == "pickle":
        import pickle

        return pickle.dumps(msg.get_params(), protocol=pickle.HIGHEST_PROTOCOL)
    if wire == "json":
        return msg.to_json().encode()
    raise ValueError(f"unknown wire format {wire!r}")


def deserialize_message(payload: bytes, wire: str) -> Message:
    if wire == "pickle":
        import pickle

        msg = Message()
        msg.init(pickle.loads(payload))
        return msg
    if wire == "json":
        return Message.from_json(payload.decode())
    raise ValueError(f"unknown wire format {wire!r}")
