"""Typed key-value message envelope.

Capability parity with the reference's ``Message``
(fedml_core/distributed/communication/message.py:5-74): named constants for
the routing keys, arbitrary payload params, and a JSON wire format for
text-based backends. Array payloads are converted to nested lists on
``to_json`` — the reference's ``is_mobile`` wire format
(fedml_api/distributed/fedavg/utils.py:7-16) — and restored as numpy arrays
on decode; binary backends (loopback, tcp) ship payloads natively.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np


class Message:
    MSG_ARG_KEY_OPERATION = "operation"
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_TRAIN_CORRECT = "train_correct"
    MSG_ARG_KEY_TRAIN_ERROR = "train_error"
    MSG_ARG_KEY_TRAIN_NUM = "train_num_sample"
    MSG_ARG_KEY_TEST_CORRECT = "test_correct"
    MSG_ARG_KEY_TEST_ERROR = "test_error"
    MSG_ARG_KEY_TEST_NUM = "test_num_sample"

    MSG_OPERATION_SEND = "send"
    MSG_OPERATION_RECEIVE = "receive"
    MSG_OPERATION_BROADCAST = "broadcast"
    MSG_OPERATION_REDUCE = "reduce"

    def __init__(self, type: Any = 0, sender_id: int = 0, receiver_id: int = 0):
        self.type = type
        self.sender_id = sender_id
        self.receiver_id = receiver_id
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    def init(self, msg_params: Dict[str, Any]) -> None:
        self.msg_params = dict(msg_params)
        self.type = self.msg_params.get(Message.MSG_ARG_KEY_TYPE)
        self.sender_id = self.msg_params.get(Message.MSG_ARG_KEY_SENDER, 0)
        self.receiver_id = self.msg_params.get(Message.MSG_ARG_KEY_RECEIVER, 0)

    def init_from_json_string(self, json_string: str) -> None:
        self.init(json.loads(json_string))

    def get_sender_id(self) -> int:
        return self.sender_id

    def get_receiver_id(self) -> int:
        return self.receiver_id

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def add(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def get_type(self) -> Any:
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def to_string(self) -> Dict[str, Any]:
        return self.msg_params

    def to_json(self) -> str:
        """JSON wire format; arrays/pytrees become nested lists (the
        reference's mobile transform, fedavg/utils.py:7-16)."""
        return json.dumps(_jsonify(self.msg_params))

    @classmethod
    def from_json(cls, json_string: str) -> "Message":
        msg = cls()
        msg.init(_unjsonify(json.loads(json_string)))
        return msg

    def __repr__(self) -> str:
        return (
            f"Message(type={self.type!r}, sender={self.sender_id}, "
            f"receiver={self.receiver_id}, keys={sorted(self.msg_params)})"
        )


def _jsonify(obj):
    """Arrays → {'__nd__': shape, 'data': flat list}; pytrees recursed."""
    if isinstance(obj, np.ndarray):
        return {"__nd__": list(obj.shape), "dtype": str(obj.dtype),
                "data": obj.ravel().tolist()}
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):  # jax arrays
        return _jsonify(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _np_dtype(name: str):
    """np.dtype with the ml_dtypes fallback (bfloat16 etc., registered
    by jax's dependency set): the wire-codec bf16 frames put bfloat16
    arrays into Message payloads, and a bare ``np.dtype('bfloat16')``
    raises in a process that never imported ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _unjsonify(obj):
    if isinstance(obj, dict):
        if "__nd__" in obj:
            return np.asarray(
                obj["data"], dtype=_np_dtype(obj["dtype"])
            ).reshape(obj["__nd__"])
        return {k: _unjsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unjsonify(v) for v in obj]
    return obj
