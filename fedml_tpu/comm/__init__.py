"""Host-side communication layer (the reference's ``fedml_core/distributed``).

The TPU framework aggregates *simulated* clients with on-device collectives
(fedml_tpu.parallel); this package exists for true cross-silo / cross-device
federation, where clients are separate OS processes or hosts. It mirrors the
reference's architecture — a ``Message`` envelope, a pluggable
``BaseCommunicationManager``, observer dispatch, and ``ClientManager`` /
``ServerManager`` process bases (fedml_core/distributed/communication/
base_com_manager.py:7, client/client_manager.py:14) — with five backends:

- ``loopback`` — in-memory threaded router for tests and single-host
  multi-worker simulation (the fake backend the reference lacks, SURVEY §4.6)
- ``tcp`` — native C++ length-prefixed socket transport over DCN, the
  cross-silo role the reference fills with gRPC (grpc_comm_manager.py:23)
- ``grpc_backend`` — grpcio C-core transport speaking the
  ``proto/comm.proto`` wire format (direct gRPC parity, one fixed ip table
  for both listen and send sides)
- ``trpc`` — TRPC-role RPC transport: acknowledged sends (rpc_sync
  semantics, epoch+seq idempotent delivery) with the pickle-free
  ``tensor`` wire format (the TensorPipe role, trpc_comm_manager.py:25)
- ``mqtt`` — broker pub/sub for device/mobile edges (requires paho-mqtt)

Cross-cutting resilience (fedml_tpu.comm.resilience): one ``RetryPolicy``
shared by every backend's ``send_message``, and ``ChaosTransport`` — a
seeded deterministic fault injector (drop/delay/duplicate/reorder/
partition) over any backend, enabled fleet-wide via ``args.chaos``.
"""

from fedml_tpu.comm.message import Message
from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.codec import (
    CodecError,
    WireCodec,
    codec_offer,
    make_wire_codec,
    negotiate,
)
from fedml_tpu.comm.loopback import LoopbackNetwork, LoopbackCommManager
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.resilience import (
    ChaosSpec,
    ChaosTransport,
    HeartbeatSender,
    RetryGiveUp,
    RetryPolicy,
)
from fedml_tpu.comm.wire import ByteLedger

__all__ = [
    "Message",
    "BaseCommunicationManager",
    "Observer",
    "ByteLedger",
    "CodecError",
    "WireCodec",
    "codec_offer",
    "make_wire_codec",
    "negotiate",
    "LoopbackNetwork",
    "LoopbackCommManager",
    "ClientManager",
    "ServerManager",
    "ChaosSpec",
    "ChaosTransport",
    "HeartbeatSender",
    "RetryGiveUp",
    "RetryPolicy",
]
