"""Control-plane resilience primitives for the message-passing federation.

The reference has no failure story at all: its server blocks on every
sampled worker (``check_whether_all_receive``) and its transports each
grew a slightly different hand-rolled retry loop. This module centralizes
the three concerns every backend was solving ad hoc:

- :class:`RetryPolicy` — ONE retry discipline (exponential backoff with
  seeded jitter, per-attempt and total deadlines, a retriable-error
  predicate, visible counters) shared by the TCP, gRPC, and TRPC
  ``send_message`` paths. Backends keep their *parameters* (first-contact
  sends tolerate peers that haven't bound yet; established peers fail
  fast) but no longer their own loops.
- :class:`ChaosTransport` / :class:`ChaosSpec` — a fault-injecting
  wrapper implementing the full ``BaseCommunicationManager`` surface over
  any real backend: seeded, DETERMINISTIC message drop, delay,
  duplication, reordering, and one-way partitions. Fault decisions are
  keyed on message identity (type, sender, receiver, round tag,
  occurrence), not on wall-clock or thread interleaving, so a drill
  replays identically under the same seed. Because the wrapper sits
  *above* the real transport, every drill exercises the same serialize/
  send/receive code paths production uses.
- :class:`HeartbeatSender` — the client-side beat loop: a daemon thread
  that sends a lightweight liveness message every ``interval_s`` while
  local training keeps the worker silent, plus an optional idle timeout
  that bounds a worker's lifetime when the server disappears (crash-stop
  servers must not leave workers blocked on a receive loop forever).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Optional, Set, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message


class RetryGiveUp(ConnectionError):
    """Raised when a RetryPolicy exhausts its attempts or deadline; the
    last underlying error is chained as ``__cause__``."""


class RetryPolicy:
    """Unified retry discipline: exponential backoff with deterministic
    seeded jitter, bounded by ``max_attempts`` AND ``total_deadline_s``.

    ``run(fn, retriable=...)`` calls ``fn()`` until it returns; an
    exception for which ``retriable(err)`` is falsy propagates
    immediately, a retriable one sleeps ``backoff_s * multiplier**k``
    (capped at ``max_backoff_s``, jittered by ±``jitter`` fraction) and
    tries again. ``attempt_timeout_s`` is advisory per-attempt budget for
    transports that support one (gRPC call timeout, TRPC connect
    timeout) — the policy carries it so it stops being a magic constant
    buried in each backend.

    Counters (``retries``, ``giveups``) are cumulative over the policy's
    lifetime; the comm managers surface them per federation round.
    """

    def __init__(self, max_attempts: int = 3, backoff_s: float = 0.25,
                 multiplier: float = 2.0, max_backoff_s: float = 2.0,
                 jitter: float = 0.1, total_deadline_s: Optional[float] = None,
                 attempt_timeout_s: Optional[float] = None, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.total_deadline_s = total_deadline_s
        self.attempt_timeout_s = attempt_timeout_s
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        self.retries = 0
        self.giveups = 0

    @classmethod
    def first_contact(cls, **kw) -> "RetryPolicy":
        """Cross-silo processes start in any order, so the first sends to
        a peer may race its bind — retry generously (the reference's MPI
        launcher sidesteps this with mpirun's barrier start)."""
        kw.setdefault("max_attempts", 21)
        kw.setdefault("backoff_s", 0.25)
        kw.setdefault("multiplier", 1.6)
        kw.setdefault("max_backoff_s", 2.0)
        kw.setdefault("total_deadline_s", 30.0)
        return cls(**kw)

    @classmethod
    def established(cls, **kw) -> "RetryPolicy":
        """Once a peer has been reached, a failure is real: one quick
        reconnect attempt, then surface — a crashed silo must be visible
        in ~0 s, not after a multi-second retry window per message."""
        kw.setdefault("max_attempts", 2)
        kw.setdefault("backoff_s", 0.0)
        return cls(**kw)

    def backoff_for(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based), jittered."""
        base = min(self.backoff_s * (self.multiplier ** (attempt - 1)),
                   self.max_backoff_s)
        if base <= 0.0 or self.jitter <= 0.0:
            return max(base, 0.0)
        return base * (1.0 + self.jitter * (2.0 * self._rng.random() - 1.0))

    def run(self, fn: Callable[[], object],
            retriable: Callable[[BaseException], bool] = lambda e: True,
            describe: str = "operation"):
        start = self._clock()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except BaseException as err:  # noqa: BLE001 — predicate decides
                last = err
                if not retriable(err):
                    raise
                if attempt >= self.max_attempts:
                    break
                pause = self.backoff_for(attempt)
                if (self.total_deadline_s is not None
                        and self._clock() - start + pause > self.total_deadline_s):
                    break
                self.retries += 1
                if pause > 0.0:
                    self._sleep(pause)
        self.giveups += 1
        raise RetryGiveUp(
            f"{describe} failed after {min(attempt, self.max_attempts)} "
            f"attempt(s)") from last


def _mix(*vals: int) -> int:
    """Stable integer hash of a tuple of ints (PYTHONHASHSEED-proof)."""
    h = 0x9E3779B97F4A7C15
    for v in vals:
        h = ((h ^ (v & 0xFFFFFFFFFFFFFFFF)) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


@dataclasses.dataclass
class ChaosSpec:
    """Shared fault configuration + counters for a ChaosTransport fleet.

    One spec instance is shared by every rank's wrapper, so runtime
    partition flips (``partition`` / ``heal``) are visible federation-wide
    and the counters aggregate the whole drill. Probabilities are
    evaluated per message from a stream keyed on (seed, message identity,
    occurrence index) — deterministic under thread interleaving.
    """

    seed: int = 0
    drop_p: float = 0.0
    dup_p: float = 0.0
    delay_p: float = 0.0
    max_delay_s: float = 0.05
    reorder_p: float = 0.0
    partitions: Set[Tuple[int, int]] = dataclasses.field(default_factory=set)

    def __post_init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {
            "sent": 0, "dropped": 0, "duplicated": 0, "delayed": 0,
            "reordered": 0, "partitioned": 0,
        }

    def partition(self, src: int, dst: int) -> None:
        """Install a ONE-WAY partition: src's messages to dst are dropped
        (dst→src still flows; add the mirror pair for a full cut)."""
        with self._lock:
            self.partitions.add((src, dst))

    def heal(self, src: Optional[int] = None, dst: Optional[int] = None) -> None:
        """Remove matching partitions (None = wildcard)."""
        with self._lock:
            self.partitions = {
                (s, d) for (s, d) in self.partitions
                if not ((src is None or s == src) and (dst is None or d == dst))
            }

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] += n


class ChaosTransport(BaseCommunicationManager):
    """Fault-injecting wrapper over any real comm backend.

    Send-side faults only (a dropped *send* and a dropped *receive* are
    indistinguishable to the protocol): drop, duplicate, delay, reorder,
    one-way partitions, per :class:`ChaosSpec`. Self-addressed messages
    (receiver == own rank — the server manager's watchdog ticks) never
    cross the network and bypass injection, as does everything when the
    spec is all-zeros. Receive side, observers, and shutdown delegate to
    the wrapped manager, so a drill runs the production code paths.
    """

    def __init__(self, inner: BaseCommunicationManager, spec: ChaosSpec,
                 rank: int, after: Optional[Callable] = None):
        self.inner = inner
        self.spec = spec
        self.rank = rank
        # Deferred-delivery scheduler override: ``after(delay_s, fn)``.
        # Default is a real threading.Timer; the virtual-clock fleet
        # simulator (fedml_tpu.sim) injects its event queue here so the
        # delay/reorder faults fire in deterministic virtual-time order
        # instead of racing wall-clock timers.
        self._after_fn = after
        self._occurrence: Dict[Tuple, int] = {}
        # receiver -> (reordered msg, copies): duplication drawn for a
        # held message applies when it is finally shipped, so the
        # 'duplicated' counter never overstates what the wire saw.
        self._held: Dict[int, Tuple[Message, int]] = {}
        # receiver -> hold generation: each safety-flush timer captures
        # the generation it was armed for, so a stale timer (its hold
        # already shipped via the normal swap path) cannot flush a LATER
        # held message early and undo that reorder.
        self._held_gen: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._timers: list = []
        self._closed = False

    # Expose the wrapped backend's resolved port / retry / byte counters.
    @property
    def port(self) -> int:
        return self.inner.port

    @property
    def retry_count(self) -> int:
        return getattr(self.inner, "retry_count", 0)

    @property
    def bytes_ledger(self):
        """The wrapped backend's ByteLedger (None on backends without
        wire serialization): a chaos drill's byte accounting must read
        what actually crossed the wire — dropped sends never serialize,
        duplicates serialize twice."""
        return getattr(self.inner, "bytes_ledger", None)

    def inbox_depth(self):
        """Delegate the ingest-queue-depth gauge to the wrapped backend
        (None where it has no observable inbox)."""
        inner = getattr(self.inner, "inbox_depth", None)
        return inner() if inner is not None else None

    def _key(self, msg: Message) -> Tuple[int, int, int, int]:
        tag = msg.get("round")
        if tag is None:
            tag = msg.get("model_version", -1)
        try:
            t = int(msg.get_type())
        except (TypeError, ValueError):
            t = 0
        return (t, int(msg.get_sender_id()), int(msg.get_receiver_id()),
                int(tag) if tag is not None else -1)

    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        if receiver == self.rank:
            self.inner.send_message(msg)  # local control tick: no network
            return
        spec = self.spec
        if not (spec.drop_p or spec.dup_p or spec.delay_p
                or spec.reorder_p or spec.partitions):
            # All-zeros spec: true pass-through — no occurrence
            # bookkeeping (which grows one entry per round/peer/type for
            # the life of the federation), no lock, no RNG construction.
            # A hold armed before the spec was zeroed must still release
            # behind this send, or it waits out its safety timer.
            self.inner.send_message(msg)
            if self._held:
                with self._lock:
                    held = self._held.pop(receiver, None)
                if held is not None:
                    self._ship(*held)
            return
        key = self._key(msg)
        with self._lock:
            partitioned = (self.rank, receiver) in self.spec.partitions
            if not partitioned:
                occ = self._occurrence.get(key, 0)
                self._occurrence[key] = occ + 1
        if partitioned:
            self.spec.count("partitioned")
            self.spec.count("dropped")
            return
        rng = random.Random(_mix(self.spec.seed, *key, occ))
        self.spec.count("sent")
        if rng.random() < self.spec.drop_p:
            self.spec.count("dropped")
            return
        copies = 1
        if rng.random() < self.spec.dup_p:
            copies = 2
            self.spec.count("duplicated")
        if rng.random() < self.spec.reorder_p:
            # Hold this message; it ships right AFTER the next message to
            # the same receiver (a pairwise swap — the minimal reordering).
            # A duplicate drawn above rides along when it ships.
            self.spec.count("reordered")
            with self._lock:
                prev = self._held.get(receiver)
                self._held[receiver] = (msg, copies)
                gen = self._held_gen.get(receiver, 0) + 1
                self._held_gen[receiver] = gen
            if prev is not None:
                self._ship(*prev)
            # Safety flush: if no later message ever flows to this
            # receiver, deliver after max_delay_s rather than never.
            self._after(self.spec.max_delay_s,
                        lambda r=receiver, g=gen: self._flush_held(r, g))
            return
        held = None
        with self._lock:
            held = self._held.pop(receiver, None)
        for _ in range(copies):
            if rng.random() < self.spec.delay_p:
                self.spec.count("delayed")
                self._after(rng.random() * self.spec.max_delay_s,
                            lambda m=msg: self._late_send(m))
            else:
                self.inner.send_message(msg)
        if held is not None:
            self._ship(*held)

    def _ship(self, msg: Message, copies: int) -> None:
        for _ in range(copies):
            self.inner.send_message(msg)

    def _flush_held(self, receiver: int, gen: Optional[int] = None) -> None:
        with self._lock:
            if gen is not None and self._held_gen.get(receiver) != gen:
                return  # stale safety timer: that hold was already shipped
            held = self._held.pop(receiver, None)
        if held is not None:
            msg, copies = held
            for _ in range(copies):
                self._late_send(msg)

    def _late_send(self, msg: Message) -> None:
        if self._closed:
            return
        try:
            self.inner.send_message(msg)
        except (ConnectionError, OSError):
            pass  # late delivery to a dead peer: genuine loss

    def _after(self, delay_s: float, fn) -> None:
        if self._after_fn is not None:
            self._after_fn(max(delay_s, 1e-4), fn)
            return
        t = threading.Timer(max(delay_s, 1e-4), fn)
        t.daemon = True
        with self._lock:
            self._timers = [x for x in self._timers if x.is_alive()]
            self._timers.append(t)
        t.start()

    # -- delegation ---------------------------------------------------------
    def add_observer(self, observer: Observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer: Observer) -> None:
        self.inner.remove_observer(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()

    def close(self) -> None:
        self._closed = True
        with self._lock:
            timers, self._timers = self._timers, []
        for t in timers:
            t.cancel()
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


class HeartbeatSender:
    """Client-side liveness loop: calls ``send_beat()`` every
    ``interval_s`` on a daemon thread so a worker stays visibly alive to
    the server's HeartbeatMonitor while a long local round keeps it
    silent on the upload path. ``touch()`` records server contact; with
    ``idle_timeout_s > 0``, ``on_idle()`` fires (once) when the server
    has been silent that long — bounding the worker's lifetime when the
    server crashed or the done message was lost."""

    def __init__(self, send_beat: Callable[[], None], interval_s: float,
                 idle_timeout_s: float = 0.0,
                 on_idle: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._send_beat = send_beat
        self.interval_s = interval_s
        self.idle_timeout_s = idle_timeout_s
        self._on_idle = on_idle
        self._clock = clock
        self._last_contact = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def touch(self) -> None:
        self._last_contact = self._clock()

    def start(self) -> None:
        if self._thread is not None or (
                self.interval_s <= 0 and self.idle_timeout_s <= 0):
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        period = self.interval_s if self.interval_s > 0 else max(
            self.idle_timeout_s / 4, 0.05)
        while not self._stop.wait(period):
            if (self.idle_timeout_s > 0
                    and self._clock() - self._last_contact > self.idle_timeout_s):
                self._stop.set()
                if self._on_idle is not None:
                    self._on_idle()
                return
            if self.interval_s > 0:
                try:
                    self._send_beat()
                except (ConnectionError, OSError):
                    pass  # server mid-restart: the beat is best-effort
