"""Client/Server process managers.

Parity with ``fedml_core/distributed/client/client_manager.py:14-79`` and
``server/server_manager.py:15-74``: a manager owns a comm backend, registers
itself as observer, dispatches incoming messages through a handler dict
keyed by message type, and runs a blocking receive loop until ``finish()``.

Backend selection is a string, as in the reference (client_manager.py:20-36):
``LOOPBACK`` (in-memory; needs a shared ``LoopbackNetwork`` in
``args.network``), ``TCP`` (native C++ socket transport; ``args.host_table``
maps rank → (host, port)), ``GRPC`` (grpcio C-core transport, same
``args.host_table`` shape — proto/comm.proto wire format), or ``MQTT``
(external broker via ``args.mqtt_host``/``args.mqtt_port`` — the flags
fedml_tpu.exp.args provides; requires paho-mqtt).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.loopback import LoopbackCommManager
from fedml_tpu.comm.message import Message


def _build_backend(args, rank: int, size: int, backend: str) -> BaseCommunicationManager:
    if backend == "LOOPBACK":
        mgr: BaseCommunicationManager = LoopbackCommManager(args.network, rank)
    elif backend == "TCP":
        from fedml_tpu.comm.tcp import TcpCommManager

        mgr = TcpCommManager(args.host_table, rank)
    elif backend == "GRPC":
        from fedml_tpu.comm.grpc_backend import GrpcCommManager

        mgr = GrpcCommManager(args.host_table, rank)
    elif backend == "MQTT":
        from fedml_tpu.comm.mqtt import MqttCommManager

        mgr = MqttCommManager(args.mqtt_host, args.mqtt_port, rank, size)
    elif backend == "TRPC":
        from fedml_tpu.comm.trpc import TRPCCommManager

        mgr = TRPCCommManager(args.host_table, rank)
    elif backend == "SIM":
        # Virtual-clock fleet simulation (fedml_tpu.sim): the event-queue
        # fabric dispatches deliveries in deterministic virtual-time
        # order; ``args.network`` is a sim.transport.SimNetwork.
        from fedml_tpu.sim.transport import SimCommManager

        mgr = SimCommManager(args.network, rank)
    else:
        raise ValueError(f"unknown comm backend {backend!r}")
    # Fault drills: ``args.chaos`` (a resilience.ChaosSpec, shared by the
    # whole fleet) wraps the real backend in a ChaosTransport, so drills
    # exercise the exact transport code paths production uses.
    # ``args.chaos_after`` (set by the fleet simulator) reroutes the
    # wrapper's delay/reorder timers through the virtual-clock event
    # queue so chaos drills stay deterministic under simulation.
    spec = getattr(args, "chaos", None)
    if spec is not None:
        from fedml_tpu.comm.resilience import ChaosTransport

        mgr = ChaosTransport(mgr, spec, rank,
                             after=getattr(args, "chaos_after", None))
    return mgr


class _Manager(Observer):
    def __init__(self, args, rank: int = 0, size: int = 0, backend: str = "LOOPBACK"):
        self.args = args
        self.rank = rank
        self.size = size
        self.backend = backend
        self.com_manager = _build_backend(args, rank, size, backend)
        self.com_manager.add_observer(self)
        self.message_handler_dict: Dict[object, Callable[[Message], None]] = {}

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self) -> None:
        """Subclasses register via :meth:`register_message_receive_handler`."""

    def register_message_receive_handler(self, msg_type, handler) -> None:
        self.message_handler_dict[msg_type] = handler

    def receive_message(self, msg_type, msg: Message) -> None:
        self.message_handler_dict[msg_type](msg)

    def send_message(self, message: Message) -> None:
        self.com_manager.send_message(message)

    def finish(self) -> None:
        """Stop the receive loop. The reference calls MPI Abort here
        (client_manager.py:72-75); loopback/tcp shut down cleanly — tcp also
        releases its native sockets."""
        self.com_manager.stop_receive_message()
        close = getattr(self.com_manager, "close", None)
        if close is not None:
            close()


class ClientManager(_Manager):
    pass


class ServerManager(_Manager):
    """Server managers additionally clock their dispatch thread: every
    upload funnels through this single-threaded handler loop — the
    server-ingest wall (arXiv:2307.06561) — and ``busy seconds ÷
    (first→last message span)`` is the ``ingest_occupancy`` figure the
    bench's ``ingest_profile`` section reports and a parallel-ingest PR
    must beat. Attribute defaults via ``getattr`` so subclasses need no
    constructor coordination; the fake-clock protocol tests that invoke
    handlers directly simply record no occupancy."""

    def receive_message(self, msg_type, msg: Message) -> None:
        t0 = time.perf_counter()
        if getattr(self, "_dispatch_t0", None) is None:
            self._dispatch_t0 = t0
        try:
            super().receive_message(msg_type, msg)
        finally:
            t1 = time.perf_counter()
            self._busy_s = getattr(self, "_busy_s", 0.0) + (t1 - t0)
            self._dispatch_t1 = t1

    def ingest_profile(self) -> Dict[str, object]:
        """Where an upload's server-side time goes: dispatch-thread
        occupancy plus the ingest registry's decode/fold/bytes/staleness
        histograms (when the subclass keeps a ``self.registry``).
        ``None`` occupancy means fewer than two dispatched messages."""
        from fedml_tpu.obs.registry import hist_fields

        busy = getattr(self, "_busy_s", 0.0)
        t0: Optional[float] = getattr(self, "_dispatch_t0", None)
        t1: Optional[float] = getattr(self, "_dispatch_t1", None)
        span = max(t1 - t0, 0.0) if (t0 is not None and t1 is not None) else 0.0
        out: Dict[str, object] = {
            "uploads": 0,
            "ingest_occupancy": round(busy / span, 4) if span > 0 else None,
            "dispatch_busy_s": round(busy, 4),
            "dispatch_span_s": round(span, 4),
        }
        reg = getattr(self, "registry", None)
        if reg is not None:
            for name in ("decode_ms", "fold_ms", "bytes_per_upload",
                         "staleness"):
                out.update(hist_fields(reg.histogram(name), name))
            out["uploads"] = reg.histogram("fold_ms").count
        # Parallel ingest pool (comm/ingest.py): per-worker occupancy +
        # task latency ride the same profile so the before/after of the
        # pooled fold is visible in one ruler (docs/OBSERVABILITY.md).
        pool = getattr(self, "_pool", None)
        if pool is not None:
            out["ingest_pool"] = pool.profile()
            if reg is not None:
                out.update(hist_fields(reg.histogram("pool_task_ms"),
                                       "pool_task_ms"))
                out["uploads"] = max(out["uploads"],
                                     reg.histogram("pool_task_ms").count)
        return out

    # -- adaptive control (fedml_tpu.ctrl) -----------------------------------
    def attach_controller(self, controller) -> None:
        """Bind a ``FederationController`` to this manager's actuation
        seam (``self.ctrl``, built by the subclass constructor). The
        manager then invokes the controller from ``_ctrl_boundary()`` at
        its safe boundaries; ``None`` detaches. The same controller
        object may later be attached to a different manager — ``bind()``
        resets policy state and the actuation log."""
        if controller is not None:
            if getattr(self, "ctrl", None) is None:
                raise ValueError(
                    f"{type(self).__name__} exposes no actuation seam; "
                    "cannot attach a controller")
            controller.bind()
        self._controller = controller
        self._ctrl_errors = 0

    def _ctrl_boundary(self) -> None:
        """Safe-boundary hook the subclass calls between rounds / after
        buffer commits (on the dispatch thread, never mid-flush). Drains
        externally queued actuations, then steps the attached controller.

        Failure containment: a policy exception must not take down the
        federation it is supposed to protect. Each exception is counted
        (``actuation_policy_errors``) and flight-recorded; after three
        consecutive failing steps the controller is detached
        (``controller_detached`` flight event) and the managers run on
        with their last-applied knob values — static behavior, not an
        outage."""
        seam = getattr(self, "ctrl", None)
        if seam is not None:
            seam.apply_pending()
        controller = getattr(self, "_controller", None)
        if controller is None:
            return
        try:
            controller.step(self)
        except Exception as e:  # noqa: BLE001 — containment boundary
            self._ctrl_errors = getattr(self, "_ctrl_errors", 0) + 1
            reg = getattr(self, "registry", None)
            if reg is not None:
                reg.counter("actuation_policy_errors").inc()
            flight = getattr(self, "flight", None)
            if flight is not None:
                flight.record("policy_error", error=type(e).__name__,
                              detail=str(e)[:200],
                              consecutive=self._ctrl_errors)
                flight.dump()
            if self._ctrl_errors >= 3:
                self._controller = None
                if flight is not None:
                    flight.record("controller_detached",
                                  after_errors=self._ctrl_errors)
                    flight.dump()
        else:
            self._ctrl_errors = 0
