"""Native TCP comm backend (cross-silo / DCN role).

The reference fills this role with gRPC C-core (grpc_comm_manager.py:23):
each rank runs a server and sends JSON messages to ``ip_config[receiver]``.
Here the transport is the in-repo C++ ``msgnet`` library (length-prefixed
frames over cached TCP connections, event-driven condvar queue — see
fedml_tpu/native/msgnet.cpp) and the payload is the pickled ``Message``
param dict, the same wire content the reference's MPI backend ships
(mpi_send_thread.py:27 pickles whole dicts).

Unlike the reference's gRPC manager — which listens on 50000+rank but sends
to 8888+rank (grpc_comm_manager.py:59-63, a latent port mismatch; SURVEY.md
§2.1) — the ip table here is the single source of truth for both sides.

``read_ip_config`` parses the reference's ``grpc_ipconfig.csv`` format
(receiver_id,ip[,port]).
"""

from __future__ import annotations

import ctypes
import csv
from typing import Dict, List, Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import RetryPolicy
from fedml_tpu.comm.wire import (ByteLedger, WIRE_FORMATS,
                                 deserialize_message, serialize_message)

DEFAULT_BASE_PORT = 50000


def read_ip_config(path: str, base_port: int = DEFAULT_BASE_PORT) -> Dict[int, Tuple[str, int]]:
    """csv ``receiver_id,ip[,port]`` → {rank: (host, port)}; port defaults
    to base_port+rank (utils/ip_config_utils.py:4 reads id→ip only)."""
    out: Dict[int, Tuple[str, int]] = {}
    with open(path) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().startswith("#"):
                continue
            if row[0].strip().lower() in ("receiver_id", "rank"):
                continue  # header
            rank = int(row[0])
            host = row[1].strip()
            port = int(row[2]) if len(row) > 2 else base_port + rank
            out[rank] = (host, port)
    return out


class TcpCommManager(BaseCommunicationManager):
    """One instance per rank.

    ``ip_config``: {rank: (host, port)}. The server binds ``port`` for this
    rank (0 = ephemeral, then ``port`` property reports it — handy in
    tests).
    """

    def __init__(self, ip_config: Dict[int, Tuple[str, int]], rank: int,
                 backlog: int = 128, serializer: str = "pickle",
                 retry_first: Optional[RetryPolicy] = None,
                 retry: Optional[RetryPolicy] = None):
        """``serializer``: 'pickle' or 'json' — see
        :mod:`fedml_tpu.comm.wire` for the trust trade-off.

        ``retry_first`` / ``retry``: the shared RetryPolicy pair — used
        until a peer is first reached / afterwards (comm.resilience)."""
        from fedml_tpu.native import load_msgnet

        if serializer not in WIRE_FORMATS:
            raise ValueError(f"unknown serializer {serializer!r}")
        self._serializer = serializer
        self._retry_first = retry_first or RetryPolicy.first_contact(seed=rank)
        self._retry = retry or RetryPolicy.established(seed=rank)
        self._lib = load_msgnet()
        self.rank = rank
        # Shared BY REFERENCE: with ephemeral ports (port 0) each rank
        # writes its resolved port back so peers constructed from the same
        # table see it (single-host setups construct all managers
        # sequentially before any send).
        self.ip_config = ip_config
        port = self.ip_config[rank][1]
        self._server = self._lib.mn_server_create(port, backlog)
        if self._server < 0:
            raise OSError(f"msgnet: cannot bind port {port} for rank {rank}")
        real_port = self._lib.mn_server_port(self._server)
        self.ip_config[rank] = (self.ip_config[rank][0], real_port)
        self._sender = self._lib.mn_sender_create()
        self.bytes_ledger = ByteLedger()
        self._observers: List[Observer] = []
        self._running = False
        self._stop_requested = False
        self._contacted: set = set()  # peers reached at least once

    @property
    def port(self) -> int:
        return self.ip_config[self.rank][1]

    @property
    def retry_count(self) -> int:
        return self._retry_first.retries + self._retry.retries

    def _send_once(self, receiver: int, host: str, port: int,
                   blob: bytes) -> None:
        """One transport attempt — the unit the RetryPolicy wraps (also
        the no-policy side of bench.py's ``chaos_clean_overhead`` A/B).
        bytes → const uint8* zero-copy (argtype c_char_p)."""
        rc = self._lib.mn_send(self._sender, host.encode(), port, blob,
                               len(blob))
        if rc != 0:
            raise ConnectionError(
                f"msgnet: send from rank {self.rank} to {receiver} "
                f"({host}:{port}) failed (rc={rc})")
        self._contacted.add(receiver)

    # -- BaseCommunicationManager ------------------------------------------
    def send_message(self, msg: Message) -> None:
        """Send under the shared RetryPolicy: generous first-contact
        retries (cross-silo processes start in any order, so the first
        sends may race the receiver's bind — the reference's MPI launcher
        sidesteps this because mpirun barrier-starts all ranks); once a
        peer has been contacted, one quick re-attempt (the C layer
        reconnects), then raise — a crashed silo must surface in ~0 s,
        not after a retry window per message."""
        receiver = int(msg.get_receiver_id())
        blob = serialize_message(msg, self._serializer)
        policy = (self._retry if receiver in self._contacted
                  else self._retry_first)
        # ip_config is re-read per attempt: a restarted peer may have
        # rebound an ephemeral port into the shared table mid-retry.
        policy.run(
            lambda: self._send_once(receiver, *self.ip_config[receiver],
                                    blob),
            retriable=lambda e: isinstance(e, (ConnectionError, OSError)),
            describe=f"msgnet send rank {self.rank} -> {receiver}")
        self.bytes_ledger.count_tx(receiver, len(blob))

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking receive loop; returns after ``stop_receive_message`` —
        including a stop that ran BEFORE this loop started (a server
        restored at the terminal round can finish inside send_init_msg;
        re-arming unconditionally here would then spin forever on the
        already-stopped native server)."""
        self._running = not self._stop_requested
        out_len = ctypes.c_uint64()
        while self._running:
            ptr = self._lib.mn_server_recv(self._server, 200, ctypes.byref(out_len))
            if not ptr:
                continue  # timeout tick: re-check _running
            try:
                blob = ctypes.string_at(ptr, out_len.value)
            finally:
                self._lib.mn_free(ptr)
            msg = deserialize_message(blob, self._serializer)
            self.bytes_ledger.count_rx(int(msg.get_sender_id()), len(blob))
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._stop_requested = True  # latched: stop-before-start must hold
        self._running = False

    def close(self) -> None:
        self.stop_receive_message()
        self._lib.mn_server_stop(self._server)
        self._lib.mn_sender_destroy(self._sender)
