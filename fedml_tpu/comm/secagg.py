"""Dropout-robust secure aggregation in the quantized integer domain.

The server learns only the SUM (ROADMAP item 1): every client adds
pairwise cancelling masks to its **fixed-point int64 contribution** —
the exact representation ``PartialAccumulator`` folds — so the pooled
fold over any ``ingest_workers`` count, any arrival order, and any
shard count M cancels the masks EXACTLY (integer adds mod 2^64 are
associative and commutative; cancellation survives the shardplane's
coordinator-side wire merge of per-shard partials unchanged, which is
why this module adds *no* new aggregation path — masked frames ride
``PartialAccumulator.add_fixed`` through the existing pool/shard
plumbing).

Protocol (one epoch = one server incarnation):

1. **Key agreement** — each client draws a DH secret ``sk`` and
   publishes ``pk = g^sk mod p`` (``core/mpc.pk_gen``); the server
   relays the roster of pks. Pair key ``k_ij = key_agreement(sk_i,
   pk_j) = key_agreement(sk_j, pk_i)`` — symmetric, never on the wire.
2. **Share distribution** — each client Shamir-shares its ``sk``
   t-of-n over the fixed worker UNIVERSE (``core/mpc.bgw_encode``,
   evaluation point of worker slot s is s+1) and ships the share for
   peer j encrypted under a one-time pad derived from ``k_ij``. The
   server stores the ciphertext matrix; it cannot decrypt any entry.
3. **Masked upload** — for round r the client quantizes its weighted
   contribution onto the int64 grid (the same
   ``quantize_contribution`` arithmetic the server pool runs), then
   adds ``sign(i, j) * expand(frame_seed(k_ij, epoch, r))`` for every
   roster peer j — the ``randmask`` PRNG-expansion pattern from
   ``comm/codec.py``, widened to full-range uint64 draws. The
   ``frame_seed`` discipline means a cached RESEND of the upload is
   bit-identical and a chaos duplicate is a true duplicate (the
   server's round-dedupe drops it before any fold).
4. **Dropout recovery** — a heartbeat eviction leaves the victim's
   masks orphaned inside the survivors' uploads. The server asks ≥t
   survivors for their (decrypted) shares of the victim's ``sk``,
   reconstructs it (``bgw_decode``), re-derives the victim's pair
   keys from the roster pks, expands the orphaned masks and SUBTRACTS
   them from the merged total; the round then commits over survivors,
   bit-equal to a federation that never had the victim. Reveals are
   epoch-fenced (a share from a previous incarnation is dropped) and
   flight-recorded; a revealed rank is released for the rest of the
   epoch — the server now knows its mask stream, so re-admitting it
   would silently void its privacy.

Threat model (docs/ROBUSTNESS.md "Secure aggregation"): honest-but-
curious server, up to n−t dropouts per round. Everything here is
host-side numpy/python — a protocol between trust domains, not a TPU
kernel — and deliberately jax-free at import time like the rest of
the comm package.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.comm.codec import frame_seed
from fedml_tpu.core.mpc import (DEFAULT_PRIME, bgw_decode, bgw_encode,
                                key_agreement, pk_gen)

_M64 = 0xFFFFFFFFFFFFFFFF
#: Domain-separation constants folded into every frame_seed derivation,
#: so the mask stream, the share pads, and the Shamir coefficient rng
#: can never collide even under equal (key, epoch, round) tuples.
_DOM_MASK = 0x5EC0AD
_DOM_PAD = 0x5EC04A
_DOM_SHAMIR = 0x5EC05A


def _gen_sk(p: int = DEFAULT_PRIME) -> int:
    """A DH secret from OS entropy, in [1, p-2]. Tests inject ``sk``
    directly for reproducibility; the bit-equality of the POOLED MEAN
    never depends on the draw (masks cancel exactly for any keys)."""
    return int.from_bytes(os.urandom(8), "big") % (p - 2) + 1


def expand_masks(seed: int, shapes: Sequence[Tuple[int, ...]]
                 ) -> List[np.ndarray]:
    """One pair mask: full-range uint64 leaves expanded from ``seed``
    (Philox bit-stream — stable across numpy versions, same generator
    discipline as the codec's ``randmask`` stage). Both ends — client
    masking and the server's dropout correction — call HERE with the
    same seed and the model's leaf shapes, so the expansion can never
    drift between them."""
    rng = np.random.Generator(np.random.Philox(np.uint64(seed & _M64)))
    total = int(sum(int(np.prod(s, dtype=np.int64)) for s in shapes))
    flat = rng.integers(0, 2 ** 64, size=total, dtype=np.uint64)
    out, off = [], 0
    for s in shapes:
        n = int(np.prod(s, dtype=np.int64))
        out.append(flat[off:off + n].reshape(s))
        off += n
    return out


def mask_seed(pair_key: int, epoch: int, round_idx: int) -> int:
    """The per-(pair, epoch, round) mask seed — ``frame_seed`` keyed so
    a resend of the same round's upload regenerates the identical mask
    and every new round gets a fresh stream."""
    return frame_seed(_DOM_MASK, pair_key, epoch, round_idx)


def _share_pad(pair_key: int, epoch: int, owner: int, holder: int,
               p: int) -> int:
    """One-time pad digit in Z_p for the (owner → holder) share cipher,
    derived from the pair key the server never sees."""
    rng = np.random.Generator(np.random.Philox(np.uint64(
        frame_seed(_DOM_PAD, pair_key, epoch, owner, holder) & _M64)))
    return int(rng.integers(0, p))


def resolve_threshold(n: int, requested: int = 0) -> int:
    """The Shamir threshold t for an n-member roster: ``requested`` when
    given, else majority (n//2 + 1). Must satisfy 1 <= t <= n-1 (the
    reveal path reconstructs a DEAD rank's seed from survivors only, so
    t == n could never fire) — except the degenerate n == 1 roster,
    which has no pairs and no shares and takes t = 1."""
    if n <= 1:
        if requested > 1:
            raise ValueError(
                f"secagg_t={requested} impossible for a 1-member roster")
        return 1
    t = int(requested) if requested else n // 2 + 1
    if not 1 <= t <= n - 1:
        raise ValueError(
            f"secagg_t={t} outside [1, {n - 1}] for an {n}-member roster: "
            "the seed-reveal path needs t shares from SURVIVORS of a "
            "1-rank dropout")
    return t


def _as_uint_view(leaves: Iterable[np.ndarray]) -> List[np.ndarray]:
    return [np.ascontiguousarray(l, np.int64).view(np.uint64)
            for l in leaves]


def apply_pair_masks(leaves: List[np.ndarray], rank: int,
                     pair_keys: Dict[int, int], roster: Sequence[int],
                     epoch: int, round_idx: int) -> List[np.ndarray]:
    """Mask a client's int64 contribution IN the integer domain:
    ``u_i = c_i + Σ_j sign(i, j) · m_ij (mod 2^64)`` with ``sign(i, j)
    = +1`` for the lower rank of the pair. Returns int64 leaves (the
    inputs are modified in place through a uint64 bit view — modular,
    warning-free)."""
    views = _as_uint_view(leaves)
    shapes = [v.shape for v in views]
    for j in sorted(roster):
        if j == rank:
            continue
        m = expand_masks(mask_seed(pair_keys[j], epoch, round_idx), shapes)
        for v, mm in zip(views, m):
            if rank < j:
                np.add(v, mm, out=v)
            else:
                np.subtract(v, mm, out=v)
    return [v.view(np.int64) for v in views]


class SecAggClient:
    """One worker's half of the protocol. Created when the client
    adopts an epoch under ``cfg.secagg``; holds the DH secret, the pair
    keys once the roster lands, and the cached encrypted share row (so
    a duplicate ROSTER gets a bit-identical SHARES reply)."""

    def __init__(self, rank: int, epoch: int, *, p: int = DEFAULT_PRIME,
                 sk: Optional[int] = None):
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.p = int(p)
        self.sk = int(sk) if sk is not None else _gen_sk(p)
        self.pk = pk_gen(self.sk, p)
        self.pair_keys: Optional[Dict[int, int]] = None
        self.roster: Optional[Tuple[int, ...]] = None
        self.t: Optional[int] = None
        self._universe: Optional[Tuple[int, ...]] = None
        self._row: Optional[Dict[int, int]] = None

    def build_shares(self, pks: Dict[int, int], t: int,
                     universe: Sequence[int]) -> Dict[int, int]:
        """Handle the server's ROSTER: derive every pair key, Shamir-
        share ``sk`` degree t−1 over the fixed universe, and return the
        encrypted share row ``{holder: cipher}`` for the roster peers.
        Deterministic in (sk, epoch, roster) — the idempotence the
        chaos-duplicate drills rely on."""
        universe = tuple(sorted(int(u) for u in universe))
        roster = tuple(sorted(int(j) for j in pks))
        if self._row is not None and roster == self.roster \
                and universe == self._universe:
            return dict(self._row)
        self.roster, self.t, self._universe = roster, int(t), universe
        self.pair_keys = {
            int(j): key_agreement(self.sk, int(pk), self.p)
            for j, pk in pks.items() if int(j) != self.rank}
        # Shamir coefficients from a stream keyed by the SECRET — secret
        # randomness, deterministic resends.
        rng = np.random.RandomState(
            frame_seed(_DOM_SHAMIR, self.sk, self.epoch) % (2 ** 32))
        shares = bgw_encode(np.asarray([[self.sk]], np.int64),
                            N=len(universe), T=int(t) - 1, p=self.p,
                            rng=rng)
        slot = {r: s for s, r in enumerate(universe)}
        row = {}
        for j in roster:
            if j == self.rank:
                continue
            s_j = int(shares[slot[j], 0, 0])
            pad = _share_pad(self.pair_keys[j], self.epoch, self.rank, j,
                             self.p)
            row[j] = (s_j + pad) % self.p
        self._row = dict(row)
        return row

    def mask(self, leaves: List[np.ndarray], round_idx: int,
             roster: Sequence[int]) -> List[np.ndarray]:
        """Mask this round's int64 contribution over ``roster`` (the
        server-stamped per-round member set — every member of the round
        masks against the same peer set, or nothing cancels)."""
        if self.pair_keys is None:
            raise ValueError(
                f"rank {self.rank}: masking before the roster handshake "
                "completed — the assignment arrived without pair keys")
        missing = [j for j in roster
                   if j != self.rank and j not in self.pair_keys]
        if missing:
            raise ValueError(
                f"rank {self.rank}: round roster names peers {missing} "
                "with no agreed pair key (roster drifted across epochs?)")
        return apply_pair_masks(leaves, self.rank, self.pair_keys,
                                roster, self.epoch, round_idx)

    def reveal_share(self, target: int, cipher: int) -> int:
        """Decrypt this client's stored share of ``target``'s sk for
        the server's dropout-recovery round."""
        if self.pair_keys is None or target not in self.pair_keys:
            raise ValueError(
                f"rank {self.rank}: no pair key for reveal target "
                f"{target}")
        pad = _share_pad(self.pair_keys[target], self.epoch, int(target),
                         self.rank, self.p)
        return (int(cipher) - pad) % self.p


class SecAggServer:
    """The coordinator's half: pk roster + encrypted share matrix +
    per-round rosters + the reveal bookkeeping. Holds NOTHING that lets
    it unmask a live client — pair keys and share plaintexts exist only
    on clients until a reveal round reconstructs a DEAD rank's sk."""

    def __init__(self, universe: Sequence[int], *, t: int = 0,
                 p: int = DEFAULT_PRIME):
        self.universe = tuple(sorted(int(u) for u in universe))
        self.p = int(p)
        self.t_requested = int(t)
        self.t: Optional[int] = None
        self.pks: Dict[int, int] = {}
        self.rows: Dict[int, Dict[int, int]] = {}
        #: The pair-key MESH, frozen the moment every live member's pk is
        #: in: only these ranks ever hold a round slot this epoch. A rank
        #: that missed the handshake window cannot be grafted into a live
        #: mesh (nobody holds a pair key with it) — it is released for
        #: the epoch rather than silently admitted unmasked.
        self.setup_roster: Optional[Tuple[int, ...]] = None
        #: Per-round roster snapshot — stamped into every assignment
        #: (including resends) so a re-admitted client masks against the
        #: same peer set as everyone else in the round.
        self.round_roster: Dict[int, Tuple[int, ...]] = {}
        #: rank → reconstructed sk. Presence means the rank's mask
        #: stream is known to the server: never re-admit it this epoch.
        self.revealed: Dict[int, int] = {}
        self._shares: Dict[int, Dict[int, int]] = {}

    # -- setup ---------------------------------------------------------------
    def add_pk(self, rank: int, pk: int) -> None:
        self.pks.setdefault(int(rank), int(pk))

    def add_row(self, owner: int, row: Dict[int, int]) -> None:
        self.rows.setdefault(int(owner), {int(h): int(c)
                                          for h, c in row.items()})

    def pks_missing(self, members: Iterable[int]) -> List[int]:
        return sorted(m for m in members if m not in self.pks)

    def rows_missing(self, members: Iterable[int]) -> List[int]:
        return sorted(m for m in members if m not in self.rows)

    def setup_complete(self, members: Iterable[int]) -> bool:
        members = list(members)
        return bool(members) and not self.pks_missing(members) \
            and not self.rows_missing(members)

    def roster_payload(self, members: Iterable[int]) -> Dict[str, object]:
        """The ROSTER broadcast body: the member pks, the resolved
        threshold, and the share universe (slot order = Shamir
        evaluation points, fixed for the epoch regardless of churn)."""
        if self.setup_roster is None:
            ranks = sorted(int(m) for m in members)
            missing = self.pks_missing(ranks)
            if missing:
                raise ValueError(
                    f"roster broadcast before pks arrived from {missing}")
            self.t = resolve_threshold(len(ranks), self.t_requested)
            self.setup_roster = tuple(ranks)
        pks = {r: self.pks[r] for r in self.setup_roster}
        return {"pks": pks, "t": int(self.t),
                "universe": list(self.universe)}

    # -- per-round rosters ---------------------------------------------------
    def stamp_roster(self, round_idx: int,
                     members: Iterable[int]) -> Tuple[int, ...]:
        """Snapshot the roster for ``round_idx`` ONCE (first call wins);
        resent assignments re-stamp the stored snapshot."""
        r = int(round_idx)
        if r not in self.round_roster:
            self.round_roster[r] = tuple(sorted(
                m for m in members if self.can_participate(m)))
        return self.round_roster[r]

    def roster_for(self, round_idx: int) -> Tuple[int, ...]:
        return self.round_roster.get(int(round_idx), ())

    def compromised(self, rank: int) -> bool:
        """True once a reveal round for ``rank`` has started or landed —
        from the first SEED_REVEAL ask, the rank's privacy this epoch is
        forfeit, so both states gate re-admission identically."""
        r = int(rank)
        return r in self.revealed or r in self._shares

    def can_participate(self, rank: int) -> bool:
        """A rank may hold a round slot only while it sits in the frozen
        pair-key mesh (``setup_roster``) and its sk is uncompromised —
        after a reveal the server can derive its every future mask (the
        privacy-over-availability rule)."""
        r = int(rank)
        return (self.setup_roster is not None and r in self.setup_roster
                and not self.compromised(r))

    # -- dropout recovery ----------------------------------------------------
    def orphans(self, round_idx: int, arrived: Iterable[int]) -> List[int]:
        """Roster members whose masked upload never folded: their masks
        sit uncancelled in the merged total and need correction."""
        arrived = set(arrived)
        return [d for d in self.roster_for(round_idx) if d not in arrived]

    def unreconstructed(self, round_idx: int,
                        arrived: Iterable[int]) -> List[int]:
        return [d for d in self.orphans(round_idx, arrived)
                if d not in self.revealed]

    def reveal_request(self, target: int, holder: int) -> Optional[int]:
        """The ciphertext of ``holder``'s share of ``target``'s sk (the
        body of a SEED_REVEAL ask), or None when ``target`` never
        shipped a row for that holder."""
        return self.rows.get(int(target), {}).get(int(holder))

    def add_reveal_share(self, target: int, holder: int,
                         share: int) -> bool:
        """Record one survivor's decrypted share; returns True when this
        share newly completes the threshold and reconstructs ``sk``.
        Duplicates (chaos resends) are idempotent by (target, holder)."""
        target, holder = int(target), int(holder)
        if target in self.revealed:
            return False
        got = self._shares.setdefault(target, {})
        got.setdefault(holder, int(share))
        if self.t is None or len(got) < self.t:
            return False
        holders = sorted(got)[:max(self.t, 1)]
        slot = {r: s for s, r in enumerate(self.universe)}
        shares = np.asarray([[[got[h]]] for h in holders], np.int64)
        sk = int(bgw_decode(shares, [slot[h] for h in holders], p=self.p,
                            T=self.t - 1)[0, 0])
        self.revealed[target] = sk
        return True

    def shares_held(self, target: int) -> int:
        return len(self._shares.get(int(target), {}))

    def has_share(self, target: int, holder: int) -> bool:
        return int(holder) in self._shares.get(int(target), {})

    def correction(self, target: int, round_idx: int, epoch: int,
                   peers: Iterable[int],
                   shapes: Sequence[Tuple[int, ...]]) -> List[np.ndarray]:
        """The int64 leaves that cancel ``target``'s orphaned masks out
        of a merged total containing exactly the uploads of ``peers``:
        each arrived peer j folded ``sign(j, target) · m_j,target``, so
        the correction adds ``sign(target, j) · m_j,target``. Pairs
        between two orphans appear in NO folded upload and need no
        correction — hence the sum runs over arrived peers only."""
        sk = self.revealed[int(target)]
        views = [np.zeros(s, np.uint64) for s in shapes]
        for j in sorted(set(int(x) for x in peers)):
            if j == int(target):
                continue
            k = key_agreement(sk, self.pks[j], self.p)
            m = expand_masks(mask_seed(k, epoch, round_idx), shapes)
            for v, mm in zip(views, m):
                if int(target) < j:
                    np.add(v, mm, out=v)
                else:
                    np.subtract(v, mm, out=v)
        return [v.view(np.int64) for v in views]
