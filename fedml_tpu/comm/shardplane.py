"""Sharded aggregation plane: M-way server scale-out with wire-merged
fixed-point partials.

PAPERS.md 2307.06561 names server ingest as *the* FL bottleneck; its
SmartNIC offload is a hardware answer. The software answer stacks the
repo's own primitives one level up:

- PR 12's ``IngestPool`` proved the associativity story INSIDE one
  process: per-worker int64 fixed-point :class:`PartialAccumulator`
  partials merge bit-equal to any serial fold, for any worker count and
  arrival interleaving.
- This module lifts that proof OVER THE WIRE. M ``AggregatorShardManager``
  processes (loopback-threaded twins in tests/bench) each own a client
  partition, run the full codec negotiation + ``IngestPool`` fold over
  their own uploads, and at flush ship ONE serialized int64 partial
  (+ participation mass + ``saturated`` gauge + ByteLedger totals). The
  rank-0 :class:`ShardedFedAVGServerManager` coordinator merges the M
  partials with the same exact ``merge_into`` adds and finalizes through
  the SAME division site (``finalize_partial_mean``) the single-process
  pool uses — bit-equality for any shard count by construction, not by
  test luck.

Rank layout: rank 0 coordinator, ranks ``1..M`` aggregator shards, ranks
``M+1..size-1`` workers. Worker→shard routing rides the existing
init/assignment handshake: each assignment stamps
``MSG_ARG_KEY_SHARD_RANK`` (directory-aware — ``ClientDirectory.
agg_shard_of`` folds data-shard locality onto the M aggregator shards),
and the client uploads to that rank while control traffic (heartbeats)
stays on rank 0.

The partial-merge wire format (see docs/ARCHITECTURE.md) rides the
existing tensor frame: a PARTIAL message whose payload dict holds the
accumulator's int64 leaves (``np.int64`` arrays — the tensor frame
round-trips them exactly) plus ``wsum``/``count``/``saturated`` as JSON
integers (arbitrary precision, so a 2^23-client round cannot overflow a
wire int). No floats cross the wire until the coordinator's single
finalize division.

Failure model — shard death is an eviction the PR 5 control plane
already understands:

- The coordinator runs a second :class:`HeartbeatMonitor` over the shard
  ranks; a silent shard is evicted via a self-addressed tick (state
  changes execute on the dispatch thread, like worker evictions).
- Eviction pulls the dead shard's un-shipped arrivals back out of the
  round and re-routes its workers with resend-flagged assignments — the
  clients' cached uploads re-target the surviving shard — so the round
  completes over surviving shards' partials.
- Mid-flush, the dead shard is simply dropped from the pending set (its
  already-collected partial, if any, is kept: those folds are safe at
  the coordinator).
- A re-admitted shard (its beats resume) catches up via a resync ANCHOR:
  it discards any uncollected partial and rejoins at the current round;
  per-channel FIFO ordering guarantees stale in-flight uploads drain
  before the resync and are deduped by the shard's round high-water
  marks.

Everything here is sync-FedAvg + mean-aggregation only: FedAsync's
sequential server mix and FedBuff's global-arrival-order buffer have no
associative partition to exploit — their managers refuse
``cfg.agg_shards`` loudly (algos/fedasync.py).

Deliberately NOT imported from ``comm/__init__``: this module imports
``algos.fedavg_distributed`` (jax, the model stack), and the comm
package stays importable without it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import jax
import numpy as np

from fedml_tpu.algos.fedavg_distributed import (
    MSG_ARG_KEY_MODEL_PARAMS,
    MSG_ARG_KEY_NUM_SAMPLES,
    MSG_ARG_KEY_SHARD_RANK,
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
    FedAVGServerManager,
)
from fedml_tpu.comm import codec as wire_codec
from fedml_tpu.comm.ingest import (
    FixedContribution,
    IngestPool,
    PartialAccumulator,
    finalize_partial_mean,
    quantize_weight,
)
from fedml_tpu.comm.managers import ServerManager
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import HeartbeatSender
from fedml_tpu.core.compression import make_compressor, tree_spec
from fedml_tpu.core.faults import HeartbeatMonitor
from fedml_tpu.obs import trace as obs_trace
from fedml_tpu.obs.registry import MetricsRegistry, payload_nbytes

log = logging.getLogger(__name__)

# Shard-plane message types, disjoint from the worker protocol (1..5 in
# fedavg_distributed, async additions in fedasync/fedbuff).
MSG_TYPE_COORD2SHARD_ANCHOR = 20  # round/epoch/broadcast net (+done/resync)
MSG_TYPE_COORD2SHARD_FLUSH = 21   # ship your partial for round r
MSG_TYPE_SHARD2COORD_PARTIAL = 22  # the int64 partial frame
MSG_TYPE_SHARD2COORD_NOTICE = 23  # per-upload accept/stale/dup/refused
MSG_TYPE_SHARD2COORD_BEAT = 24    # shard liveness
MSG_TYPE_COORD_SHARD_TICK = 25    # coordinator self-addressed deadline

PARTIAL_KEY = "shard_partial"


def encode_partial(total: PartialAccumulator) -> dict:
    """The partial frame: int64 leaves + exact scalar tallies, riding the
    tensor wire (comm/wire.py serializes int64 arrays bit-exactly and
    JSON integers with arbitrary precision). ``leaves`` is ``None`` for a
    shard that folded nothing this round — the merge treats it as the
    additive identity, exactly like a fresh in-process accumulator."""
    return {
        "leaves": (None if total.leaves is None
                   else [np.ascontiguousarray(l, dtype=np.int64)
                         for l in total.leaves]),
        "wsum": int(total.wsum),
        "count": int(total.count),
        "saturated": int(total.saturated),
    }


def decode_partial(payload: dict) -> PartialAccumulator:
    out = PartialAccumulator()
    leaves = payload.get("leaves")
    if leaves is not None:
        out.leaves = [np.asarray(l, dtype=np.int64) for l in leaves]
    out.wsum = int(payload["wsum"])
    out.count = int(payload["count"])
    out.saturated = int(payload["saturated"])
    return out


class AggregatorShardManager(ServerManager):
    """One aggregator shard (rank ``1..M``): ingests its partition's
    uploads — codec decode, delta reconstruction against the coordinator-
    anchored broadcast net, exact fixed-point fold on its own
    :class:`IngestPool` — and ships the merged int64 partial to the
    coordinator on FLUSH. Per-upload outcomes travel as small NOTICE
    messages so all round bookkeeping (arrival counts, straggler /
    duplicate / refusal policy) stays on the coordinator's dispatch
    thread, exactly where the single-server path keeps it.

    Per-channel FIFO is the correctness backbone: the coordinator sends
    ANCHOR(r) before any round-r assignment, so the anchor is always
    installed before the first round-r upload arrives; ACCEPT notices
    are sent before the PARTIAL that contains their folds, so the
    coordinator can never finalize a flush missing an accepted fold."""

    def __init__(self, args, rank: int, size: int, cfg, net_ref,
                 backend: str = "LOOPBACK", *,
                 ingest_workers: Optional[int] = None,
                 beat_interval_s: Optional[float] = None,
                 clock=time.monotonic):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.cfg = cfg
        self.round_idx = 0
        self.epoch = 0
        # High-water of the round whose partial already shipped: later
        # same-round uploads would be orphaned folds — refused as "late".
        self.flushed_round = -1
        self._anchor = None  # this round's broadcast net (delta base)
        self._spec = tree_spec(net_ref)
        self._decoders = {}  # legacy compressor name → compressor
        # Guards the decoder cache only: pool workers get-or-create
        # concurrently, and twin compressors would split error-feedback
        # state across them.
        self._lock = threading.Lock()
        self._wire_decoders = wire_codec.CodecCache()
        self.registry = MetricsRegistry()
        self._h_bytes = self.registry.histogram("bytes_per_upload", lo=1.0)
        self._g_queue = self.registry.gauge("ingest_queue_depth")
        self._g_pool_queue = self.registry.gauge("ingest_pool_queue_depth")
        workers = (int(getattr(cfg, "ingest_workers", 0) or 0)
                   if ingest_workers is None else int(ingest_workers))
        # A shard ALWAYS pools (min 1 worker): the pool's partial is the
        # unit of exchange, and its fold path is the bit-equality anchor.
        self._pool = IngestPool(max(1, workers), registry=self.registry)
        self._last_upload_round: Dict[int, int] = {}
        self.accepted = 0
        self.refused = 0
        self._stopped = False
        self._beats = HeartbeatSender(
            self._send_beat,
            interval_s=(cfg.heartbeat_interval_s if beat_interval_s is None
                        else beat_interval_s),
            clock=clock)

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        self._beats.start()
        super().run()

    def finish(self) -> None:
        self._stopped = True
        self._beats.stop()
        self._pool.close()
        super().finish()

    def _send_beat(self) -> None:
        msg = Message(MSG_TYPE_SHARD2COORD_BEAT, self.rank, 0)
        # fedlint: disable=P1(epoch is a monotonically-adopted small int; a beat stamped with the pre-adoption epoch is indistinguishable from one sent just before adoption and the coordinator accepts both)
        msg.add("epoch", self.epoch)
        self.send_message(msg)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._handle_upload)
        self.register_message_receive_handler(
            MSG_TYPE_COORD2SHARD_ANCHOR, self._handle_anchor)
        self.register_message_receive_handler(
            MSG_TYPE_COORD2SHARD_FLUSH, self._handle_flush)

    # -- coordinator control ------------------------------------------------
    def _handle_anchor(self, msg: Message) -> None:
        if self._stopped:
            return  # killed mid-dispatch: the pool is closed; eviction owns us
        ep = msg.get("epoch")
        if ep is not None:
            ep = int(ep)
            if ep < self.epoch:
                return  # straggler from a pre-crash coordinator epoch
            if ep > self.epoch:
                # Coordinator restart: adopt the epoch; the dedupe marks
                # die with the old epoch (the restored run replays rounds).
                # fedlint: disable=P1(single-writer adoption on the dispatch thread; the beat thread only stamps the value and tolerates the previous epoch)
                self.epoch = ep
                self._last_upload_round.clear()
        if msg.get("done"):
            self.finish()
            return
        r = int(msg.get("round", 0))
        if bool(msg.get("resync")) or r != self.round_idx:
            # New round, or re-admission catch-up: any folds still in the
            # pool belong to a flush that will never be asked for (the
            # coordinator completed that round without us) — discard so
            # they cannot leak into the NEXT round's partial.
            self._pool.drain()
            self._pool.reset()
        self.round_idx = r
        self._anchor = msg.get(MSG_ARG_KEY_MODEL_PARAMS)

    def _handle_flush(self, msg: Message) -> None:
        if self._stopped:
            return  # a FLUSH racing finish(): drain would park on dead workers
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return
        r = int(msg.get("round", self.round_idx))
        if r != self.round_idx or r <= self.flushed_round:
            return  # duplicate FLUSH of an already-shipped round
        # Barrier on the pool; surface per-frame refusals FIRST so FIFO
        # delivers them before the PARTIAL (the coordinator's arrival set
        # must shed refused workers before it checks the fold count).
        for meta, err in self._pool.drain():
            self.refused += 1
            self._notify("refused", int(meta.get("sender", -1)), r,
                         error=err)
        with obs_trace.active().span(
                "shard.flush", cat="shard",
                corr=obs_trace.corr(epoch=self.epoch, round=r,
                                    sender=self.rank)):
            total = self._pool.merge_partials()
        self.flushed_round = r
        out = Message(MSG_TYPE_SHARD2COORD_PARTIAL, self.rank, 0)
        out.add(PARTIAL_KEY, encode_partial(total))
        out.add("round", r)
        out.add("epoch", self.epoch)
        # Satellite rollups ride every partial: the shard's ByteLedger
        # totals and pool occupancy (both monotone/latest-wins gauges).
        ledger = getattr(self.com_manager, "bytes_ledger", None)
        out.add("bytes_rx", int(ledger.total_rx) if ledger is not None else 0)
        out.add("bytes_tx", int(ledger.total_tx) if ledger is not None else 0)
        prof = self.ingest_profile()
        out.add("occupancy", prof.get("ingest_occupancy"))
        out.add("queue_depth", int(self._pool.queue_depth()))
        self.send_message(out)

    # -- the partition's uploads --------------------------------------------
    def _handle_upload(self, msg: Message) -> None:
        if self._stopped:
            # fedlint: disable=P2(dead shard: finish() already ran, the heartbeat lapse evicts this rank and the coordinator re-routes the partition — no sender is waiting on a reply from a corpse, and a NOTICE here would race the closing com manager)
            return
        sender = msg.get_sender_id()
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            self._notify("epoch", sender, int(msg.get("round", -1)))
            return
        tag = msg.get("round")
        t = int(tag) if tag is not None else self.round_idx
        if t <= self._last_upload_round.get(sender, -1):
            # Duplicate delivery (chaos duplication / resend race): the
            # first copy was folded or refused — never fold twice.
            self._notify("duplicate", sender, t)
            return
        self._last_upload_round[sender] = t
        if t != self.round_idx or self.round_idx <= self.flushed_round:
            # An older round's straggler, or this round's partial already
            # shipped (a "late" arrival racing the flush): folding would
            # orphan the contribution. The coordinator owns catch-up.
            self._notify("stale", sender, t)
            return
        if not self._submit_upload(sender, t, msg):
            return  # finish() closed the pool under us — see _submit_upload
        self.accepted += 1
        self._notify("accept", sender, t)

    def _submit_upload(self, sender: int, t: int, msg: Message) -> bool:
        """Decode + fold on the shard's pool — the same task shape as the
        single server's ``_submit_ingest`` (closure snapshots the round's
        anchor so a late task cannot reconstruct against the next one).

        Returns False when the shard was killed while this upload was in
        flight: ``finish()`` (another thread — the coordinator's kill or a
        drill's killer) closes the pool between the handler's ``_stopped``
        check and the submit. The upload is dropped, not an error — the
        coordinator's heartbeat eviction re-routes the partition."""
        payload = msg.get(MSG_ARG_KEY_MODEL_PARAMS)
        codec = msg.get("compression")
        wcodec = msg.get(wire_codec.CODEC_KEY)
        is_delta = bool(msg.get(wire_codec.DELTA_KEY))
        masked = bool(msg.get(wire_codec.SECAGG_MASKED_KEY))
        clipped = int(msg.get("secagg_clipped") or 0)
        secagg_on = bool(getattr(self.cfg, "secagg", False))
        weight = float(msg.get(MSG_ARG_KEY_NUM_SAMPLES))
        ck = obs_trace.corr(epoch=self.epoch, round=t, sender=sender)
        self._h_bytes.record(payload_nbytes(payload))
        depth = getattr(self.com_manager, "inbox_depth", None)
        if depth is not None:
            depth = depth()
            if depth is not None:
                self._g_queue.set(depth)
        self._g_pool_queue.set(self._pool.queue_depth())
        anchor = self._anchor
        spec = self._spec

        # fedlint: twin-of(fedml_tpu/algos/fedavg_distributed.py)
        def task():
            if masked:
                # A masked upload is ALREADY in the pool's fixed-point
                # int64 domain: fold it verbatim (any rescale would break
                # the exact pairwise cancellation at the coordinator's
                # wire merge). A masked frame on a non-secagg shard is a
                # refusal — surfaced by the flush drain as a NOTICE, the
                # coordinator's codec-refusal policy evicts+releases.
                if not secagg_on:
                    raise ValueError(
                        "masked upload on a shard without --secagg")
                return FixedContribution(
                    [np.ascontiguousarray(l, np.int64) for l in payload],
                    quantize_weight(weight), 1, int(clipped))
            if codec:
                delta = self._decoder_for(codec).decode(payload, spec)
            elif wcodec:
                delta = self._wire_decoders.decode(wcodec, payload, spec)
            elif is_delta:
                delta = payload
            else:
                delta = None
            if delta is None:
                return ([np.asarray(l) for l in jax.tree.leaves(payload)],
                        weight)
            return ([np.asarray(d) for d in jax.tree.leaves(delta)],
                    weight,
                    [np.asarray(a) for a in jax.tree.leaves(anchor)])

        try:
            self._pool.submit(task, **ck)
        except RuntimeError:
            if self._stopped:
                return False
            raise
        return True

    def _decoder_for(self, codec: str):
        """Get-or-create the per-codec decoder under the lock. The
        shard's pool always runs >=1 worker, so two tasks can miss the
        cache for the same codec at once and construct twin compressors
        — harmless for stateless codecs, state-splitting for
        error-feedback ones."""
        with self._lock:
            dec = self._decoders.get(codec)
            if dec is None:
                dec = self._decoders[codec] = make_compressor(codec)
        return dec

    def _notify(self, kind: str, worker: int, round_idx: int,
                error=None) -> None:
        out = Message(MSG_TYPE_SHARD2COORD_NOTICE, self.rank, 0)
        out.add("kind", kind)
        out.add("worker", int(worker))
        out.add("round", int(round_idx))
        out.add("epoch", self.epoch)
        if error is not None:
            out.add("error", str(error)[:200])
        self.send_message(out)


class ShardedFedAVGServerManager(FedAVGServerManager):
    """Rank-0 coordinator of the sharded aggregation plane. Inherits the
    entire PR 5 control plane — membership, heartbeats, straggler-
    tolerant first-k rounds, epoch fencing, checkpoint resume — and
    replaces only the INGEST: uploads land on the M shard ranks, arrival
    bookkeeping rides NOTICE messages, and the round commit wire-merges
    the shards' int64 partials through the same ``finalize_partial_mean``
    division site as the in-process pool (bit-equality by construction).

    The round-commit handshake: the k-th ACCEPT starts a flush (FLUSH to
    every live shard); each PARTIAL is collected; when the pending set
    empties, ``_finish_flush`` merges in sorted-rank order, finalizes,
    anchors round r+1 on the shards, THEN assigns the workers — FIFO
    per channel makes anchor-before-upload exact.

    Secure aggregation composes: masked uploads are int64 frames the
    shards fold verbatim, pairwise masks cancel in the coordinator's
    wire merge exactly as in the single pool (integer adds are
    associative), and ``_finish_flush`` holds the commit until every
    orphaned roster rank's seeds are revealed and its correction folded
    into the merged total."""

    # The coordinator folds on the shards, not a local pool — tells the
    # base constructor's secagg guard that ingest_workers=0 is fine here.
    _secagg_sharded = True

    def __init__(self, args, aggregator, cfg, size: int, agg_shards: int,
                 backend: str = "LOOPBACK", aggregate_k: int = 0, *,
                 directory=None, round_timeout_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 done_timeout_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None, metrics=None,
                 clock=time.monotonic, flight_dir: Optional[str] = None):
        M = int(agg_shards)
        if M < 1:
            raise ValueError(f"agg_shards={agg_shards} needs at least 1 "
                             "aggregator shard")
        num_workers = size - 1 - M
        if num_workers < 1:
            raise ValueError(
                f"size={size} leaves no worker ranks after 1 coordinator "
                f"+ {M} shards")
        if not aggregator.aggregator.is_mean:
            raise ValueError(
                f"agg_shards={M} needs the mean aggregator: "
                f"{aggregator.aggregator.name!r} keeps the serialized "
                "stack-then-reduce cohort buffer — the wire partials are "
                "mean-only fixed-point sums (comm/shardplane.py)")
        if aggregate_k and not 1 <= aggregate_k <= num_workers:
            raise ValueError(
                f"aggregate_k={aggregate_k} outside [1, {num_workers}]")
        # The shards own the ingest pools; the coordinator folds nothing.
        cfg0 = dataclasses.replace(cfg, ingest_workers=0)
        super().__init__(args, aggregator, cfg0, size, backend=backend,
                         aggregate_k=0, round_timeout_s=round_timeout_s,
                         heartbeat_timeout_s=heartbeat_timeout_s,
                         done_timeout_s=done_timeout_s,
                         checkpoint_dir=checkpoint_dir, metrics=metrics,
                         clock=clock, flight_dir=flight_dir)
        self.agg_shards = M
        self.aggregate_k = aggregate_k or num_workers
        # Re-base membership + worker liveness onto ranks M+1..size-1;
        # ranks 1..M get their own monitor (same timeout policy).
        self._members = set(range(M + 1, size))
        self.heartbeat = HeartbeatMonitor(
            range(M + 1, size), timeout_s=self.heartbeat.timeout_s,
            clock=clock)
        self.shard_heartbeat = HeartbeatMonitor(
            range(1, M + 1), timeout_s=self.heartbeat.timeout_s,
            clock=clock)
        self._live_shards: Set[int] = set(range(1, M + 1))
        self.shard_evictions = 0
        self.shard_readmissions = 0
        self._directory = directory
        self._assigned_shard: Dict[int, int] = {}  # worker → routed shard
        self._arrived_via: Dict[int, int] = {}     # worker → accepting shard
        self._shard_partials: Dict[int, PartialAccumulator] = {}
        self._flush_pending: Set[int] = set()
        self._flushing_round: Optional[int] = None
        # Workers to catch up once the in-flight flush commits: "late"
        # stragglers whose current-round re-assignment the client-side
        # dedupe would drop, and workers pulled back by a mid-flush shard
        # eviction.
        self._catchup_after_flush: Set[int] = set()
        # Latest-wins per-shard gauges (satellites: fleet-wide saturation
        # + ByteLedger rollup in health()).
        self._shard_saturated: Dict[int, int] = {}
        self._shard_bytes: Dict[int, Tuple[int, int]] = {}
        if getattr(cfg, "secagg", False):
            if aggregate_k:
                raise ValueError(
                    f"secagg with aggregate_k={aggregate_k}: a first-k "
                    "commit orphans every straggler's masks, so each "
                    "round would reveal the stragglers' seeds and "
                    "permanently release them (comm/secagg.py is "
                    "all-or-reveal)")
            # The base constructor keyed the secagg coordinator to the
            # pre-rebase membership (ranks 1..size-1 — which includes
            # the M shard ranks); re-key it to the true worker ranks.
            self._secagg_init()

    # -- rank plumbing ------------------------------------------------------
    def _worker_slot(self, worker: int) -> int:
        return worker - self.agg_shards - 1

    def _shard_ranks(self) -> List[int]:
        return list(range(1, self.agg_shards + 1))

    def _live_shards_snapshot(self) -> List[int]:
        with self._lock:
            return sorted(self._live_shards)

    def _route_shard(self, client_index: int) -> int:
        """The shard rank this client's upload belongs to: the client
        directory's data-shard locality when available (``agg_shard_of``)
        else a plain modulo partition, remapped onto the live set when
        the preferred shard is evicted."""
        c = int(client_index)
        if self._directory is not None:
            pref = int(self._directory.agg_shard_of(c, self.agg_shards))
        else:
            pref = c % self.agg_shards
        live = self._live_shards_snapshot()
        if not live:
            return pref + 1  # all dead: the abort path is already running
        rank = pref + 1
        return rank if rank in live else live[pref % len(live)]

    def _stamp_routing(self, out: Message, client_index: int) -> None:
        shard = self._route_shard(client_index)
        out.add(MSG_ARG_KEY_SHARD_RANK, shard)
        with self._lock:
            self._assigned_shard[int(out.get_receiver_id())] = shard

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> None:
        for s in self._shard_ranks():
            self.shard_heartbeat.beat(s)
        if ((self.round_timeout_s and self.round_timeout_s > 0)
                or (self.done_timeout_s and self.done_timeout_s > 0)):
            threading.Thread(target=self._shard_watch_loop,
                             daemon=True).start()
        super().run()

    def finish(self) -> None:
        if not self._stopped:
            # Release EVERY shard rank (evicted-but-alive ones included):
            # a shard stranded in its receive loop would hang the
            # run_workers join forever.
            for s in self._shard_ranks():
                self._send_anchor(s, done=True)
        super().finish()

    def send_init_msg(self) -> None:
        # Anchor before assignment: per-channel FIFO guarantees every
        # shard holds round 0's broadcast net (the delta base) before the
        # first upload can reach it.
        if self.round_idx >= self.cfg.comm_round:
            for s in self._shard_ranks():
                self._send_anchor(s, done=True)
        else:
            for s in self._live_shards_snapshot():
                self._send_anchor(s)
        super().send_init_msg()

    def register_message_receive_handlers(self) -> None:
        super().register_message_receive_handlers()
        self.register_message_receive_handler(
            MSG_TYPE_SHARD2COORD_NOTICE, self._handle_shard_notice)
        self.register_message_receive_handler(
            MSG_TYPE_SHARD2COORD_PARTIAL, self._handle_shard_partial)
        self.register_message_receive_handler(
            MSG_TYPE_SHARD2COORD_BEAT, self._handle_shard_beat)
        self.register_message_receive_handler(
            MSG_TYPE_COORD_SHARD_TICK, self._handle_shard_tick)

    # -- shard control plane ------------------------------------------------
    def _send_anchor(self, shard: int, *, resync: bool = False,
                     done: bool = False) -> None:
        out = Message(MSG_TYPE_COORD2SHARD_ANCHOR, 0, shard)
        out.add(MSG_ARG_KEY_MODEL_PARAMS, None if done else self._broadcast_net)
        out.add("round", self.round_idx)
        out.add("epoch", self.epoch)
        if resync:
            out.add("resync", True)
        if done:
            out.add("done", True)
            try:
                self.send_message(out)
            except (ConnectionError, OSError):
                pass  # release is best-effort: a dead shard needs none
            return
        try:
            self.send_message(out)
        except (ConnectionError, OSError) as err:
            log.warning("anchor to shard %d failed (%s): evicting",
                        shard, err)
            self._evict_shards([shard])

    def _handle_shard_beat(self, msg: Message) -> None:
        s = msg.get_sender_id()
        self.shard_heartbeat.beat(s)
        if self.round_idx >= self.cfg.comm_round or self._stopped:
            self._send_anchor(s, done=True)
            return
        with self._lock:
            live = s in self._live_shards
        if not live:
            with self._lock:
                self._live_shards.add(s)
                self.shard_readmissions += 1
            log.info("re-admitting aggregator shard %d on beat", s)
            self.flight.record("shard_readmission", shard=s,
                               round=self.round_idx)
            # Resync: the shard discards any uncollected partial and
            # rejoins at the current round with the current anchor. Its
            # in-flight stale uploads drain first (FIFO) and are deduped
            # by its per-worker round high-water marks.
            self._send_anchor(s, resync=True)

    def _shard_watch_loop(self) -> None:
        poll = max(0.005, min(
            0.05, (self.round_timeout_s or self.done_timeout_s) / 10))
        while not self._stopped:
            dead = (set(self.shard_heartbeat.failed())
                    & set(self._live_shards_snapshot()))
            if dead:
                self._post_shard_tick(sorted(dead))
            time.sleep(poll)

    def _post_shard_tick(self, dead) -> None:
        """Self-addressed, like the worker watchdog's TICK: the eviction
        executes on the dispatch thread, serialized with every handler."""
        msg = Message(MSG_TYPE_COORD_SHARD_TICK, 0, 0)
        msg.add("shards", [int(s) for s in dead])
        msg.add("epoch", self.epoch)
        try:
            self.send_message(msg)
        except (ConnectionError, OSError):
            pass  # next watchdog pass re-ticks

    def _handle_shard_tick(self, msg: Message) -> None:
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return
        dead = set(msg.get("shards") or [])
        # Re-check at dispatch time: a beat may have landed while the
        # tick sat in the inbox.
        dead &= set(self.shard_heartbeat.failed())
        with self._lock:
            dead &= self._live_shards
        if dead:
            log.warning("shard deadline: evicting silent shard(s) %s",
                        sorted(dead))
            self._evict_shards(sorted(dead))

    def _evict_shards(self, ranks) -> None:
        evicted = []
        with self._lock:
            for s in ranks:
                if s in self._live_shards:
                    self._live_shards.discard(s)
                    self.shard_evictions += 1
                    evicted.append(s)
        if not evicted:
            return
        self.flight.record("shard_eviction", shards=evicted,
                           round=self.round_idx)
        self.flight.dump()
        # Folds held by the dead shards are lost UNLESS their partial was
        # already collected this flush. Pull the lost arrivals back out
        # and re-route those workers to surviving shards; their cached
        # uploads resend (re-targeted by the stamped shard rank).
        with self._lock:
            flushing = self._flushing_round is not None
            reroute = set()
            for w, via in list(self._arrived_via.items()):
                if via in evicted and via not in self._shard_partials:
                    self._arrived.discard(w)
                    del self._arrived_via[w]
                    reroute.add(w)
            for w, s in list(self._assigned_shard.items()):
                if s in evicted and w in self._members:
                    reroute.add(w)
            self._flush_pending -= set(evicted)
            flush_done = flushing and not self._flush_pending
            none_live = not self._live_shards
        if none_live:
            log.error("all aggregator shards dead at round %d: "
                      "abandoning the run", self.round_idx)
            self.aborted = True
            self.flight.record("abort", round=self.round_idx)
            self.flight.dump()
            for w in self._members_snapshot():
                self._send_done(w)
            if not self._stopped:
                self.finish()
            return
        if flushing:
            # Mid-flush: the round completes over the surviving shards'
            # partials; the pulled-back workers rejoin at the commit.
            with self._lock:
                self._catchup_after_flush |= reroute
            if flush_done:
                self._finish_flush()
        else:
            for w in sorted(reroute):
                self._send_assignment(w, resend=True)

    # -- per-upload notices -------------------------------------------------
    def _handle_shard_notice(self, msg: Message) -> None:
        shard = msg.get_sender_id()
        self.shard_heartbeat.beat(shard)
        with self._lock:
            live = shard in self._live_shards
        if not live:
            # A presumed-dead shard's stale bookkeeping: its accepted
            # folds were already pulled back and re-routed — only its
            # BEAT (a resync) can bring it back.
            return
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            return
        kind = msg.get("kind")
        worker = int(msg.get("worker"))
        r = int(msg.get("round", -1))
        if kind == "accept":
            self._on_accept(shard, worker, r)
        elif kind == "stale":
            self.straggler_drops += 1
            self.flight.record("straggler_drop", sender=worker, round=r)
            self.heartbeat.beat(worker)
            if self.round_idx >= self.cfg.comm_round:
                self._send_done(worker)
            elif (self.secagg is not None
                    and self.secagg.compromised(worker)):
                # Revealed seeds: released for the epoch, never re-fed.
                self._send_done(worker)
            elif r == self.round_idx:
                # A late same-round upload racing the flush: a fresh
                # assignment for THIS round would be deduped client-side
                # — catch the worker up when the flush commits.
                with self._lock:
                    self._catchup_after_flush.add(worker)
            else:
                self._send_assignment(worker)
        elif kind == "duplicate":
            self.duplicate_drops += 1
            self.flight.record("duplicate_drop", sender=worker, round=r)
        elif kind == "epoch":
            self.epoch_drops += 1
            self.flight.record("epoch_drop", sender=worker)
        elif kind == "refused":
            self._on_refused(worker, r, msg.get("error"))
        else:
            log.warning("shard %d sent unknown notice kind %r", shard, kind)

    def _on_accept(self, shard: int, worker: int, r: int) -> None:
        self.heartbeat.beat(worker)  # an upload is liveness
        # A compromised rank (seeds revealed/mid-reveal) is never
        # re-admitted — but its CURRENT upload already folded on the
        # shard, so the arrival must still count (correction ⟺ not
        # arrived; the commit tail releases it).
        with self._lock:
            readmit = worker not in self._members and not (
                self.secagg is not None
                and self.secagg.compromised(worker))
            if readmit:
                self._members.add(worker)
                self.readmissions += 1
            self.flight.record("readmission", sender=worker, round=r,
                               via="upload")
        if r != self.round_idx:
            # Defensive: FIFO (ACCEPT before the shard's own PARTIAL)
            # makes a post-commit ACCEPT for r unreachable.
            log.warning("shard %d accepted worker %d for round %d but the "
                        "coordinator is at %d — ignoring", shard, worker,
                        r, self.round_idx)
            return
        with self._lock:
            self._arrived.add(worker)
            self._arrived_via[worker] = shard
            ready = len(self._arrived) >= self._k_effective()
            flushing = self._flushing_round is not None
        if ready and not flushing:
            self._complete_round()

    def _on_refused(self, worker: int, r: int, error) -> None:
        """The pooled refusal policy (``_settle_pool``), delivered by
        notice: evict AND release — a mismatched encoder can never upload
        a usable model."""
        self.codec_refusals += 1
        log.error("rank %d: shard ingest refused (%s) — evicting and "
                  "releasing the worker", worker, error)
        self.flight.record("codec_refusal", sender=worker, round=r,
                           error=(str(error)[:200]
                                  if error is not None else None))
        with self._lock:
            self._arrived.discard(worker)
            self._arrived_via.pop(worker, None)
        self._evict([worker])
        self.flight.dump()
        with self._lock:
            empty = not self._members
        if empty:
            log.error("all workers refused/evicted at round %d: "
                      "abandoning the run", self.round_idx)
            self.aborted = True
        self._send_done(worker)

    # -- the flush ----------------------------------------------------------
    def _complete_round(self) -> None:
        """k-th accept: start the flush. The commit happens in
        ``_finish_flush`` once every live shard's partial is in."""
        # Mask-completeness gate BEFORE the flush barrier: an evicted
        # roster rank's masks sit orphaned inside the shards' partials;
        # hold the flush until its seeds are revealed (each reveal
        # re-enters via _secagg_recheck). Orphans appearing mid-flush
        # (a shard eviction pulling arrivals back) are caught by the
        # same gate at the top of _finish_flush.
        if self.secagg is not None and not self._secagg_reveals_ready():
            return
        with self._lock:
            if self._flushing_round is not None:
                return
            live = sorted(self._live_shards)
            self._flushing_round = self.round_idx
            self._flush_pending = set(live)
            self._shard_partials = {}
        for s in live:
            out = Message(MSG_TYPE_COORD2SHARD_FLUSH, 0, s)
            out.add("round", self.round_idx)
            out.add("epoch", self.epoch)
            try:
                self.send_message(out)
            except (ConnectionError, OSError) as err:
                log.warning("flush to shard %d failed (%s): evicting",
                            s, err)
                self._evict_shards([s])

    def _handle_shard_partial(self, msg: Message) -> None:
        shard = msg.get_sender_id()
        self.shard_heartbeat.beat(shard)
        ep = msg.get("epoch")
        if ep is not None and int(ep) != self.epoch:
            # fedlint: disable=P2(stale-epoch partial; the resync ANCHOR already re-seated this shard at the live epoch, so it is not blocked waiting on a reply)
            return
        with self._lock:
            live = shard in self._live_shards
        if not live:
            # fedlint: disable=P2(evicted mid-flush; its workers were re-routed with resend assignments and the flush barrier no longer counts this shard)
            return
        # The satellite rollups ride every partial (latest-wins gauges:
        # the shard's saturated count is a lifetime monotone, the ledger
        # totals are cumulative).
        frame = msg.get(PARTIAL_KEY) or {}
        self._shard_saturated[shard] = int(frame.get("saturated", 0))
        self._shard_bytes[shard] = (int(msg.get("bytes_rx", 0)),
                                    int(msg.get("bytes_tx", 0)))
        occ = msg.get("occupancy")
        if occ is not None:
            self.registry.gauge(f"shard{shard}_occupancy").set(float(occ))
        self.registry.gauge(f"shard{shard}_queue_depth").set(
            float(msg.get("queue_depth", 0)))
        r = int(msg.get("round", -1))
        with self._lock:
            if self._flushing_round != r or shard not in self._flush_pending:
                return  # straggling partial from a superseded flush
            self._shard_partials[shard] = decode_partial(
                msg.get(PARTIAL_KEY))
            self._flush_pending.discard(shard)
            done = not self._flush_pending
        if done:
            self._finish_flush()

    def _finish_flush(self) -> None:
        """All live shards' partials are in: merge in sorted-rank order
        (int64 adds — order-insensitive, sorted for determinism of the
        merge span), finalize through the ONE division site the
        in-process pool uses, then run the base round-commit tail."""
        if self.secagg is not None:
            with self._lock:
                r0 = self._flushing_round
                arrived0 = sorted(self._arrived)
            if r0 is None:
                return
            pending = self.secagg.unreconstructed(r0, arrived0)
            if pending:
                # A mid-flush shard eviction pulled arrivals back out of
                # the round: those roster ranks are orphans now, and
                # their un-cancelled masks are already folded into the
                # collected partials. Hold the commit for the reveals
                # (_secagg_recheck re-enters) — and drop them from the
                # catch-up list: a revealed rank is released, not re-fed.
                with self._lock:
                    self._catchup_after_flush -= set(pending)
                self._secagg_request_reveals(pending)
                return
        with self._lock:
            r = self._flushing_round
            if r is None:
                return
            partials = [self._shard_partials[s]
                        for s in sorted(self._shard_partials)]
            arrived = sorted(self._arrived)
            self._arrived = set()
            self._arrived_via = {}
            self._flushing_round = None
            self._flush_pending = set()
            self._shard_partials = {}
            catchup = sorted(self._catchup_after_flush)
            self._catchup_after_flush = set()
        total = PartialAccumulator()
        with obs_trace.active().span(
                "shard.merge", cat="shard",
                corr=obs_trace.corr(epoch=self.epoch, round=r),
                shards=len(partials), arrived=len(arrived)):
            for p in partials:
                p.merge_into(total)
            if self.secagg is not None:
                # Orphaned roster ranks (reveals completed above): fold
                # each reconstructed-seed correction as a weight-0
                # count-0 contribution — the same exact int64 adds the
                # single-pool precommit path uses — then audit the
                # post-cancellation envelope.
                orphans = self.secagg.orphans(r, arrived)
                if orphans:
                    shapes = [np.shape(np.asarray(l))
                              for l in jax.tree.leaves(self.aggregator.net)]
                    for d in orphans:
                        corr = self.secagg.correction(
                            d, r, self.epoch, arrived, shapes)
                        total.add_fixed(FixedContribution(corr, 0, 0))
                    self.flight.record(
                        "secagg_correction", round=r,
                        targets=[int(d) for d in orphans])
                self._secagg_envelope_check(total)
            mean, count = finalize_partial_mean(total, self.aggregator.net)
        if count != len(arrived):
            raise ValueError(
                f"sharded flush merged {count} folded uploads but the "
                f"round arrived {len(arrived)}: a lost fold cannot be "
                "subtracted after the fact — this is a shard-plane "
                "protocol bug (comm/shardplane.py)")
        if arrived and mean is not None:
            self.aggregator.net = mean
        self.flight.record("round_commit", round=r, arrived=len(arrived),
                           shards=len(partials))
        self._broadcast_net = self.aggregator.net
        if (r % self.cfg.frequency_of_the_test == 0
                or r == self.cfg.comm_round - 1):
            self.aggregator.test_on_server(r)
        # Commit under the lock: the inherited watchdog thread reads the
        # round counter through the base class's locked snapshot.
        with self._lock:
            self.round_idx = r + 1
        self._log_round_health(r, arrived)
        if self._ckpt is not None and self.cfg.checkpoint_every and (
                self.round_idx % self.cfg.checkpoint_every == 0):
            self._save_checkpoint(wait=False)
        # Secagg membership repair (waitroom admits, compromised purge,
        # reveal bookkeeping reset) — same tail as the single-pool path.
        extra = (self._secagg_commit_tail(arrived)
                 if self.secagg is not None else [])
        if self.round_idx >= self.cfg.comm_round:
            for s in self._shard_ranks():
                self._send_anchor(s, done=True)
            for worker in list(arrived) + extra:
                self._send_done(worker)
            for worker in catchup:
                if worker not in arrived:
                    self._send_done(worker)
            return
        # Anchor BEFORE assigning: FIFO per channel means every shard
        # holds round r+1's delta base before its first r+1 upload.
        for s in self._live_shards_snapshot():
            self._send_anchor(s)
        client_indexes = self.aggregator.client_sampling(self.round_idx)
        compromised = (self.secagg.compromised
                       if self.secagg is not None else (lambda w: False))
        for worker in list(arrived) + extra:
            if compromised(worker):
                # Its fold counted this round, but its seeds are public
                # now — release for the epoch instead of re-assigning.
                self._send_done(worker)
            else:
                self._send_assignment(worker, client_indexes)
        for worker in catchup:
            if worker not in arrived:
                if compromised(worker):
                    self._send_done(worker)
                else:
                    self._send_assignment(worker, client_indexes)

    def _secagg_recheck(self) -> None:
        """A seed reveal just completed. If the flush barrier already
        emptied (we returned early from ``_finish_flush`` to wait for
        this reveal), re-enter the commit; if no flush is in flight the
        base recheck re-drives ``_complete_round`` through its own gate.
        A flush with partials still pending needs nothing — the gate
        re-runs when the last partial lands."""
        if self.round_idx >= self.cfg.comm_round:
            return
        with self._lock:
            flushing = self._flushing_round is not None
            drained = flushing and not self._flush_pending
        if drained:
            self._finish_flush()
        elif not flushing:
            super()._secagg_recheck()

    # -- observability ------------------------------------------------------
    def health(self) -> Dict[str, int]:
        out = super().health()
        with self._lock:
            live = len(self._live_shards)
            saturated = sum(self._shard_saturated.values())
            bytes_rx = sum(rx for rx, _ in self._shard_bytes.values())
            bytes_tx = sum(tx for _, tx in self._shard_bytes.values())
        out["shards"] = live
        out["shard_evictions"] = self.shard_evictions
        out["shard_readmissions"] = self.shard_readmissions
        # Satellite fixes: fleet-wide saturation (each shard reports its
        # pool's lifetime gauge; the sum IS the fleet total because the
        # shards' client partitions are disjoint) and the per-shard
        # ByteLedger totals folded into the coordinator's own.
        out["ingest_saturated"] = out.get("ingest_saturated", 0) + saturated
        out["bytes_rx"] = out.get("bytes_rx", 0) + bytes_rx
        out["bytes_tx"] = out.get("bytes_tx", 0) + bytes_tx
        return out
