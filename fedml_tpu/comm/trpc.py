"""TRPC-role comm backend: synchronous acknowledged RPC sends with
tensor-aware wire framing.

Parity: the reference's TRPC backend (torch.distributed.rpc/TensorPipe,
fedml_core/distributed/communication/trpc/trpc_comm_manager.py:25) gives
two things its other backends lack: (1) ``send_message`` is an
acknowledged remote call — ``rpc_sync`` blocks until the receiver's
servicer has enqueued the message and returned its "message received"
response (trpc_server.py:28-42); (2) TensorPipe moves tensors without
pickling them. This module reproduces both TPU-natively: every send is a
length-prefixed request frame answered by an ACK on the same connection,
and the payload uses the ``tensor`` wire format
(fedml_tpu.comm.wire — raw array buffers + JSON structure header, no
pickle anywhere on the wire).

Config parity: ``TRPCCommManager(trpc_master_config_path=...)`` reads
the reference's master CSV (header line, then ``address,port`` —
trpc_comm_manager.py:36-39); worker ``w`` listens on
``master_port + w``, mirroring the rendezvous-derived worker addressing.
Tests construct with an explicit ``ip_config`` table instead (same shape
as the TCP backend's).
"""

from __future__ import annotations

import socket
import struct
import threading
from queue import Empty, Queue
from typing import Dict, List, Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import RetryPolicy
from fedml_tpu.comm.wire import (ByteLedger, deserialize_message,
                                 serialize_message)

_ACK = b"\x06"  # the servicer's "message received" response, one byte


def read_master_config(path: str) -> Tuple[str, int]:
    """Reference master CSV: one header line, then ``address,port``."""
    import csv

    with open(path, newline="") as f:
        rows = csv.reader(f)
        next(rows)  # header
        address, port = next(rows)[:2]
    return address.strip(), int(port)


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        part = conn.recv(n)
        if not part:
            return None
        chunks.append(part)
        n -= len(part)
    return b"".join(chunks)


class TRPCCommManager(BaseCommunicationManager):
    """One instance per rank; see module docstring for the contract."""

    #: Upper bound on a single incoming frame's payload length. The frame
    #: header's 64-bit ``n`` comes from an unauthenticated peer; without a
    #: cap, _serve_conn would buffer up to 2^64 bytes on request. 4 GiB
    #: comfortably covers the largest model upload while bounding memory.
    max_frame_bytes: int = 4 << 30

    def __init__(self, ip_config: Optional[Dict[int, Tuple[str, int]]] = None,
                 rank: int = 0, *, trpc_master_config_path: Optional[str] = None,
                 world_size: int = 0,
                 retry_first: Optional[RetryPolicy] = None,
                 retry: Optional[RetryPolicy] = None):
        if ip_config is None:
            if trpc_master_config_path is None:
                raise ValueError(
                    "need ip_config or trpc_master_config_path")
            if world_size <= 0:
                raise ValueError(
                    "trpc_master_config_path requires world_size > 0 "
                    "(worker w listens on master_port + w)")
            host, base = read_master_config(trpc_master_config_path)
            ip_config = {r: (host, base + r) for r in range(world_size)}
        self.rank = rank
        self.ip_config = ip_config  # shared BY REFERENCE (ephemeral ports)
        # The 30 s budget is for the CONNECT only (attempt_timeout_s); a
        # model-sized sendall / ack wait on a slow link must not expire.
        self._retry_first = retry_first or RetryPolicy.first_contact(
            seed=rank, attempt_timeout_s=30.0)
        self._retry = retry or RetryPolicy.established(
            seed=rank, attempt_timeout_s=30.0)
        self._queue: Queue = Queue()
        self.bytes_ledger = ByteLedger()
        self._observers: List[Observer] = []
        self._running = False
        self._stop_requested = False
        self._conns: Dict[int, socket.socket] = {}
        self._send_lock = threading.Lock()
        self._send_seq = 0  # per-sender monotone id; receiver dedupes
        # Fresh random epoch per manager INSTANCE: a restarted sender gets
        # a new sequence space instead of having its messages silently
        # dropped against the old instance's high-water mark.
        import os as _os

        self._send_epoch = int.from_bytes(_os.urandom(8), "little")
        self._last_seq: Dict[tuple, int] = {}  # (sender, epoch) -> last seq
        self._dedupe_lock = threading.Lock()

        self._server = socket.create_server(
            (ip_config[rank][0], ip_config[rank][1]), backlog=64)
        self._server.settimeout(0.2)
        # Ephemeral-port resolution back into the shared table (TCP
        # backend convention: single-host tests bind port 0 first).
        self.ip_config[rank] = (ip_config[rank][0],
                                self._server.getsockname()[1])
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._alive = True
        self._accept_thread.start()

    # -- server side -------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while self._alive:
                head = _recv_exact(conn, 24)
                if head is None:
                    return
                n, epoch, seq = struct.unpack("<QQQ", head)
                if n > self.max_frame_bytes:
                    return  # oversized frame: drop the connection
                payload = _recv_exact(conn, n)
                if payload is None:
                    return
                msg = deserialize_message(payload, "tensor")
                sender = int(msg.get_sender_id())
                # Counted per DELIVERY (a retry after a lost ACK crossed
                # the wire again even though the dedupe drops it).
                self.bytes_ledger.count_rx(sender, n + 24)
                # Idempotent enqueue: a sender retry after a lost ACK
                # re-delivers the same (sender, epoch, seq) — ack it
                # again but never enqueue twice (a duplicate model upload
                # would be double-counted by the aggregator). Check and
                # update under ONE lock: a retry lands on a NEW
                # connection, i.e. a different serve thread, and an
                # unlocked check-then-act would let both copies through.
                # Enqueue inside the lock, BEFORE acking: the ack is the
                # rpc_sync return — after send_message returns, the
                # message is guaranteed queued on the receiver.
                key = (sender, epoch)
                with self._dedupe_lock:
                    if seq > self._last_seq.get(key, -1):
                        self._last_seq[key] = seq
                        self._queue.put(msg)
                conn.sendall(_ACK)

    @property
    def retry_count(self) -> int:
        return self._retry_first.retries + self._retry.retries

    def _send_once(self, receiver: int, head: bytes, blob: bytes,
                   connect_timeout_s: Optional[float] = None) -> None:
        try:
            conn = self._conns.get(receiver)
            if conn is None:
                conn = socket.create_connection(
                    self.ip_config[receiver],
                    timeout=(connect_timeout_s
                             if connect_timeout_s is not None
                             else self._retry.attempt_timeout_s))
                conn.settimeout(None)
                self._conns[receiver] = conn
            # Two sendalls: concatenating would copy the whole (possibly
            # model-sized) blob a second time.
            conn.sendall(head)
            conn.sendall(blob)
            if _recv_exact(conn, 1) != _ACK:
                raise ConnectionError("bad ack")
        except OSError:
            self._conns.pop(receiver, None)
            raise

    # -- BaseCommunicationManager ------------------------------------------
    def send_message(self, msg: Message) -> None:
        """rpc_sync semantics: returns only after the receiver acked the
        enqueue, under the shared RetryPolicy — generous connect retries
        until a peer is first reached (workers start in any order), one
        immediate reconnect+resend afterwards. Retries are SAFE here
        (unlike a naive resend): the receiver dedupes on (sender, epoch,
        seq), so a frame whose ACK was lost is re-acked without a second
        enqueue."""
        receiver = int(msg.get_receiver_id())
        blob = serialize_message(msg, "tensor")
        if len(blob) > self.max_frame_bytes:
            # Fail fast: the receiver would silently drop the connection,
            # and the retry loop would retransmit the whole blob.
            raise ValueError(
                f"message serializes to {len(blob)} bytes, over the "
                f"{self.max_frame_bytes}-byte frame cap")
        with self._send_lock:
            self._send_seq += 1
            head = struct.pack("<QQQ", len(blob), self._send_epoch,
                               self._send_seq)
            policy = (self._retry if receiver in self._conns
                      else self._retry_first)
            # The ACTIVE policy's per-attempt budget governs the connect:
            # a custom first-contact attempt_timeout_s must be honored,
            # not silently replaced by the established policy's.
            timeout = (policy.attempt_timeout_s
                       if policy.attempt_timeout_s is not None
                       else self._retry.attempt_timeout_s)
            policy.run(
                lambda: self._send_once(receiver, head, blob, timeout),
                retriable=lambda e: isinstance(e, OSError),
                describe=f"trpc send rank {self.rank} -> {receiver}")
            self.bytes_ledger.count_tx(receiver, len(blob) + len(head))

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop over the servicer queue (the reference's
        message_handling_subroutine, trpc_comm_manager.py:~128)."""
        # Honor a stop that ran BEFORE the loop started (stop-before-start
        # race: a restored-at-terminal server finishes in send_init_msg).
        self._running = not self._stop_requested
        while self._running:
            try:
                msg = self._queue.get(timeout=0.2)
            except Empty:
                continue
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._stop_requested = True  # latched: stop-before-start must hold
        self._running = False

    def close(self) -> None:
        self._alive = False
        try:
            self._server.close()
        except OSError:
            pass
        for conn in self._conns.values():
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()
