"""gRPC comm backend (cross-silo / DCN, C-core transport).

Role parity with the reference's gRPC manager
(fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:23): every
rank runs an insecure gRPC server and sends ``Message`` envelopes to
``ip_config[receiver]``. Differences, all deliberate:

- One ip table is the source of truth for both listen and send sides
  (the reference listens on 50000+rank but sends to 8888+receiver_id,
  grpc_comm_manager.py:59-63 — a latent port mismatch; SURVEY.md §2.1).
- Receive is event-driven (blocking queue handoff from the rpc thread to
  the dispatch loop) instead of the reference's 0.3 s polling thread
  (grpc_comm_manager.py:89-100 + time.sleep).
- No generated stubs: the image ships grpcio but not grpc_tools, so the
  service is registered through :func:`grpc.method_handlers_generic_handler`
  with identity (de)serializers, and request/ack frames are encoded with a
  ~40-line protobuf wire codec for the schema in ``proto/comm.proto``.
  The bytes on the wire are valid ``fedml.tpu.CommRequest`` protos —
  ``tests/test_grpc_comm.py`` cross-checks the codec against ``protoc
  --encode`` — so peers regenerated from the .proto interoperate.
- Max message size is lifted to 1000 MB on both directions, matching the
  reference (grpc_comm_manager.py:36-38): a serialized model update for
  the larger zoo entries exceeds gRPC's 4 MB default.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Tuple

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.resilience import RetryPolicy
from fedml_tpu.comm.wire import (ByteLedger, WIRE_FORMATS,
                                 deserialize_message, serialize_message)

SERVICE_NAME = "fedml.tpu.CommService"
METHOD_NAME = "SendMessage"
MAX_MESSAGE_MB = 1000


# --------------------------------------------------------------------------
# Minimal protobuf wire codec for proto/comm.proto (proto3).
# Wire format: a message is a sequence of (tag, value); tag = field<<3 | type;
# type 0 = varint, type 2 = length-delimited.


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    val = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def encode_comm_request(sender: int, payload: bytes, wire: str) -> bytes:
    if sender < 0:
        raise ValueError("rank must be non-negative")
    w = wire.encode()
    return (
        b"\x08" + _varint(sender)
        + b"\x12" + _varint(len(payload)) + payload
        + b"\x1a" + _varint(len(w)) + w
    )


def decode_comm_request(buf: bytes) -> Tuple[int, bytes, str]:
    sender, payload, wire = 0, b"", "pickle"
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, i = _read_varint(buf, i)
            if field == 1:
                sender = val
        elif wtype == 2:
            ln, i = _read_varint(buf, i)
            chunk = buf[i:i + ln]
            i += ln
            if field == 2:
                payload = bytes(chunk)
            elif field == 3:
                wire = chunk.decode()
        else:
            raise ValueError(f"unsupported wire type {wtype} in CommRequest")
    return sender, payload, wire


def encode_comm_ack(status: int = 0) -> bytes:
    return b"\x08" + _varint(status)


def decode_comm_ack(buf: bytes) -> int:
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        if tag >> 3 == 1 and tag & 7 == 0:
            val, i = _read_varint(buf, i)
            return val
        raise ValueError("unsupported field in CommAck")
    return 0


# --------------------------------------------------------------------------


class GrpcCommManager(BaseCommunicationManager):
    """One instance per rank.

    ``ip_config``: {rank: (host, port)} — ``fedml_tpu.comm.tcp.read_ip_config``
    parses the reference's ``grpc_ipconfig.csv`` into this shape. Port 0
    binds an ephemeral port and writes the resolved one back into the
    (shared-by-reference) table, mirroring the TCP backend's single-host
    test setup.

    ``serializer``: 'pickle' (fast; TRUSTED silo peers — the reference ships
    pickled dicts over MPI the same way) or 'json' (``Message.to_json``,
    safe for untrusted/mobile edges). The receiver decodes ONLY its
    configured format: frames whose ``wire`` field disagrees are dropped
    with a log line. Honoring the frame's field instead would let an
    untrusted peer force a json-configured edge into ``pickle.loads`` —
    arbitrary code execution — defeating the point of json mode.
    """

    def __init__(self, ip_config: Dict[int, Tuple[str, int]], rank: int,
                 serializer: str = "pickle", max_workers: int = 8,
                 retry_first: Optional[RetryPolicy] = None,
                 retry: Optional[RetryPolicy] = None):
        import grpc
        from concurrent import futures

        if serializer not in WIRE_FORMATS:
            raise ValueError(f"unknown serializer {serializer!r}")
        self._grpc = grpc
        self._serializer = serializer
        # The per-attempt RPC deadline used to be a hardcoded 120 s
        # buried in send_message; it now rides the shared policy.
        self._retry_first = retry_first or RetryPolicy.first_contact(
            seed=rank, attempt_timeout_s=120.0)
        self._retry = retry or RetryPolicy.established(
            seed=rank, attempt_timeout_s=120.0)
        self.rank = rank
        self.ip_config = ip_config
        self.bytes_ledger = ByteLedger()
        self._queue: "queue.Queue[bytes]" = queue.Queue()
        self._observers: List[Observer] = []
        self._running = False
        self._stop_requested = False
        self._contacted: set = set()
        self._channels: Dict[int, object] = {}
        self._lock = threading.Lock()

        opts = [
            ("grpc.max_send_message_length", MAX_MESSAGE_MB * 1024 * 1024),
            ("grpc.max_receive_message_length", MAX_MESSAGE_MB * 1024 * 1024),
        ]
        self._channel_opts = opts

        def _send_message(request: bytes, context) -> bytes:
            self._queue.put(request)
            return encode_comm_ack(0)

        handler = grpc.unary_unary_rpc_method_handler(
            _send_message,  # identity (de)serializers → raw bytes in/out
            request_deserializer=None,
            response_serializer=None,
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers), options=opts
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(
                SERVICE_NAME, {METHOD_NAME: handler}),)
        )
        port = ip_config[rank][1]
        bound = self._server.add_insecure_port(f"0.0.0.0:{port}")
        if bound == 0:
            raise OSError(f"grpc: cannot bind port {port} for rank {rank}")
        self.ip_config[rank] = (self.ip_config[rank][0], bound)
        self._server.start()

    @property
    def port(self) -> int:
        return self.ip_config[self.rank][1]

    def _stub(self, receiver: int):
        with self._lock:
            entry = self._channels.get(receiver)
            if entry is None:
                host, port = self.ip_config[receiver]
                channel = self._grpc.insecure_channel(
                    f"{host}:{port}", options=self._channel_opts)
                call = channel.unary_unary(f"/{SERVICE_NAME}/{METHOD_NAME}")
                entry = (channel, call)
                self._channels[receiver] = entry
            return entry[1]

    @property
    def retry_count(self) -> int:
        return self._retry_first.retries + self._retry.retries

    def _send_once(self, receiver: int, frame: bytes,
                   timeout_s: float) -> None:
        try:
            ack = self._stub(receiver)(frame, timeout=timeout_s)
        except self._grpc.RpcError as err:
            code = err.code() if hasattr(err, "code") else None
            host, port = self.ip_config[receiver]
            exc = ConnectionError(
                f"grpc: send from rank {self.rank} to {receiver} "
                f"({host}:{port}) failed: {code}")
            # Only UNAVAILABLE (peer not up yet / mid-restart) is worth a
            # retry — the policy's predicate reads this marker.
            exc.retriable = code == self._grpc.StatusCode.UNAVAILABLE
            raise exc from err
        if decode_comm_ack(ack) != 0:
            raise ConnectionError(
                f"grpc: rank {receiver} rejected the message")
        self._contacted.add(receiver)

    # -- BaseCommunicationManager ------------------------------------------
    def send_message(self, msg: Message) -> None:
        """Send under the shared RetryPolicy: ``UNAVAILABLE`` retried
        generously until a peer is first reached (ranks start in any
        order; once contacted, a dead silo must surface immediately) —
        same discipline as the TCP backend."""
        receiver = int(msg.get_receiver_id())
        frame = encode_comm_request(
            self.rank, serialize_message(msg, self._serializer),
            self._serializer)
        policy = (self._retry if receiver in self._contacted
                  else self._retry_first)
        policy.run(
            lambda: self._send_once(receiver, frame,
                                    policy.attempt_timeout_s or 120.0),
            retriable=lambda e: getattr(e, "retriable", False),
            describe=f"grpc send rank {self.rank} -> {receiver}")
        # Whole CommRequest frame (payload + proto envelope): what gRPC
        # actually puts on the wire, modulo HTTP/2 framing.
        self.bytes_ledger.count_tx(receiver, len(frame))

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        """Blocking dispatch loop; returns after ``stop_receive_message``.
        Messages are handed off from the rpc thread through a queue so
        observer callbacks run on this (caller's) thread, like every other
        backend — handlers may block without stalling the gRPC server.

        Malformed frames are logged and dropped, not fatal: the gRPC
        server acks before this loop decodes, so letting a decode error
        kill the loop would hang the federation silently while senders
        keep seeing success."""
        import logging

        log = logging.getLogger(__name__)
        # Honor a stop that ran BEFORE the loop started (stop-before-start
        # race: a restored-at-terminal server finishes in send_init_msg).
        self._running = not self._stop_requested
        while self._running:
            try:
                frame = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                sender, payload, wire = decode_comm_request(frame)
                self.bytes_ledger.count_rx(sender, len(frame))
                if wire != self._serializer:
                    log.warning(
                        "rank %d: dropping frame with wire format %r "
                        "(this manager is configured for %r)",
                        self.rank, wire, self._serializer)
                    continue
                msg = deserialize_message(payload, self._serializer)
            except Exception:
                log.exception(
                    "rank %d: dropping undecodable frame (%d bytes)",
                    self.rank, len(frame))
                continue
            for obs in list(self._observers):
                obs.receive_message(msg.get_type(), msg)

    def stop_receive_message(self) -> None:
        self._stop_requested = True  # latched: stop-before-start must hold
        self._running = False

    def close(self) -> None:
        self.stop_receive_message()
        self._server.stop(grace=0.5)
        with self._lock:
            for channel, _ in self._channels.values():
                channel.close()
            self._channels.clear()
