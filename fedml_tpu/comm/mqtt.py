"""MQTT comm backend (device/mobile edge transport).

Parity with the reference's ``MqttCommManager``
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-128):
pub/sub through an external broker, JSON payloads, topic scheme
``fedml_<receiver>`` with per-sender uniqueness appended. Requires
``paho-mqtt`` and a reachable broker — both import- and connect-gated, so
the module is loadable (and the class introspectable) without them; the
constructor raises a clear error if paho is absent.

In the TPU framework this is strictly the DCN-edge bridge for real mobile
devices (SURVEY.md §2.9); simulated federations use the collective path and
cross-silo uses the native TCP backend.
"""

from __future__ import annotations

import uuid
from typing import List

from fedml_tpu.comm.base import BaseCommunicationManager, Observer
from fedml_tpu.comm.message import Message
from fedml_tpu.comm.wire import ByteLedger


def _topic(receiver_id: int) -> str:
    # Reference: server subscribes "fedml_<id>", clients mirror
    # (mqtt_comm_manager.py:47-63).
    return f"fedml_{receiver_id}"


class MqttCommManager(BaseCommunicationManager):
    """``client`` injects a paho-compatible MQTT client (an in-memory
    double in tests — the reference's loopback self-test,
    mqtt_comm_manager.py:130-146, needs a live broker; ours does not);
    ``None`` constructs the real paho client."""

    def __init__(self, host: str, port: int, rank: int, size: int,
                 topic_prefix: str = "fedml", keepalive: int = 180,
                 client=None):
        if client is None:
            try:
                import paho.mqtt.client as mqtt
            except ImportError as e:  # pragma: no cover - env without paho
                raise ImportError(
                    "MqttCommManager requires paho-mqtt and a reachable "
                    "broker; pip install paho-mqtt (the simulated/collective "
                    "and TCP backends have no such dependency)") from e
            client = mqtt.Client(
                client_id=f"{topic_prefix}_{rank}_{uuid.uuid4().hex[:8]}")

        self.rank = rank
        self.size = size
        self.topic_prefix = topic_prefix
        self.bytes_ledger = ByteLedger()
        self._observers: List[Observer] = []
        self._client = client
        self._client.on_connect = self._on_connect
        self._client.on_message = self._on_message
        self._client.connect(host, port, keepalive)

    # -- paho callbacks -----------------------------------------------------
    def _on_connect(self, client, userdata, flags, rc):
        client.subscribe(f"{self.topic_prefix}_{self.rank}", qos=1)

    def _on_message(self, client, userdata, mqtt_msg):
        msg = Message.from_json(mqtt_msg.payload.decode())
        self.bytes_ledger.count_rx(int(msg.get_sender_id()),
                                   len(mqtt_msg.payload))
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)

    # -- BaseCommunicationManager -------------------------------------------
    def send_message(self, msg: Message) -> None:
        receiver = int(msg.get_receiver_id())
        payload = msg.to_json()
        self.bytes_ledger.count_tx(receiver, len(payload))
        self._client.publish(f"{self.topic_prefix}_{receiver}",
                             payload=payload, qos=1)

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._client.loop_forever()

    def stop_receive_message(self) -> None:
        self._client.disconnect()
