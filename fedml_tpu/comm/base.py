"""Backend-independent communication abstractions.

Parity with the reference's ``BaseCommunicationManager``
(fedml_core/distributed/communication/base_com_manager.py:7-27) and
``Observer`` (observer.py:4-7): a backend exposes send / observer
registration / a blocking receive loop; observers get
``receive_message(msg_type, msg)`` callbacks.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from fedml_tpu.comm.message import Message


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type, msg: Message) -> None: ...


class BaseCommunicationManager(ABC):
    @abstractmethod
    def send_message(self, msg: Message) -> None: ...

    @abstractmethod
    def add_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def remove_observer(self, observer: Observer) -> None: ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Blocking receive loop: deliver incoming messages to observers
        until :meth:`stop_receive_message` is called."""

    @abstractmethod
    def stop_receive_message(self) -> None: ...
