"""Control policies: telemetry in, knob proposals out.

A :class:`ControlPolicy` is a pure decision function over a telemetry
snapshot (see :func:`read_telemetry`): it proposes ``{knob: value}``
mutations and never touches a manager directly — the
:class:`FederationController` routes proposals through the manager's
:class:`~fedml_tpu.ctrl.actuator.ActuationSeam`, which owns validation
and boundary discipline. Because policies see only the snapshot dict,
the SAME controller object drives a :class:`~fedml_tpu.sim.FleetSimulator`
run and a real loopback manager run unchanged (the acceptance bar for
this subsystem): telemetry keys are identical in both worlds.

Determinism note: the sim drill pins two-run-identical actuation logs,
so the shipped policies key only on virtually-deterministic signals —
staleness percentiles, eviction counts, progress counters, eval history.
Wall-clock-derived telemetry (dispatch occupancy from ``perf_counter``)
is consumed only by :class:`TimeoutAutoscalePolicy`'s ingest-worker arm,
which real deployments enable and the pinned drills leave cold.

Policy lineage: the guard-band admission controller is the 2307.06561
"steer away from ingest saturation" loop; the window schedule is the
1807.06629 (Parallel Restarted SGD) observation that the averaging
interval should shrink as loss improvement flattens — early in training
a wide window (large ``buffer_k`` / ``aggregate_k``) buys cheap
parallelism, late it only adds averaging error.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Protocol, runtime_checkable


def read_telemetry(manager) -> Dict[str, float]:
    """Flatten a server manager's live observability surfaces into the
    flat snapshot dict policies consume.

    Works against any of the three tiers (and their sim twins): missing
    surfaces contribute nothing rather than raising, so one policy runs
    everywhere. Keys:

    - ``progress``: monotone protocol step — async/fedbuff model
      ``version``, sync completed-round count. The controller's cadence
      and cooldowns count in this unit, not wall time.
    - ``staleness_p95`` / ``staleness_p50``: tail of the recent OFFERED
      staleness window (the async tiers' bounded deque — admitted or
      not, so an armed admission cap cannot blind the loop to offered
      load). Falls back to the cumulative registry histogram when the
      manager keeps no window; cumulative percentiles cannot recover
      after a spike ends, so windowed is strongly preferred.
    - ``evictions`` / ``guard_drops`` / ``admission_drops``: ``health()``.
    - ``accuracy`` / ``loss``: latest server-side eval sample.
    - ``occupancy``: dispatch-thread busy fraction (wall-clock; see
      module note).
    """
    t: Dict[str, float] = {}
    version = getattr(manager, "version", None)
    if version is not None:
        t["progress"] = float(version)
    else:
        t["progress"] = float(getattr(manager, "round_idx", 0))
    recent = getattr(manager, "_stale_recent", None)
    if recent:
        vals = sorted(recent)
        n = len(vals)
        t["staleness_p95"] = float(vals[min(n - 1, int(0.95 * (n - 1) + 0.5))])
        t["staleness_p50"] = float(vals[n // 2])
    else:
        reg = getattr(manager, "registry", None)
        if reg is not None:
            try:
                h = reg.histogram("staleness")
                if h.count:
                    t["staleness_p95"] = float(h.percentile(95))
                    t["staleness_p50"] = float(h.percentile(50))
            except Exception:
                pass
    health = getattr(manager, "health", None)
    if callable(health):
        try:
            hd = health()
        except Exception:
            hd = {}
        for key in ("evictions", "guard_drops", "admission_drops",
                    "buffer_depth", "rounds_completed", "live_workers"):
            if key in hd:
                t[key] = float(hd[key])
    profile = getattr(manager, "ingest_profile", None)
    if callable(profile):
        try:
            p = profile()
            if p.get("ingest_occupancy") is not None:
                t["occupancy"] = float(p["ingest_occupancy"])
        except Exception:
            pass
    hist = getattr(manager, "test_history", None)
    if hist is None:
        agg = getattr(manager, "aggregator", None)
        hist = getattr(agg, "test_history", None)
    if hist:
        last = hist[-1]
        if isinstance(last, dict):
            for src, dst in (("test_acc", "accuracy"), ("accuracy", "accuracy"),
                             ("test_loss", "loss"), ("loss", "loss")):
                if src in last and dst not in t:
                    t[dst] = float(last[src])
    return t


@runtime_checkable
class ControlPolicy(Protocol):
    """One feedback loop: ``propose`` maps a telemetry snapshot to knob
    requests. Policies must be deterministic functions of the snapshot
    stream (internal state is fine; entropy is not)."""

    name: str

    def reset(self) -> None:
        """Forget accumulated state (called when the controller binds to
        a new manager — sim-tuned policies then drive a real run from a
        clean slate)."""

    def propose(self, telemetry: Dict[str, float],
                knobs: Dict[str, float]) -> Dict[str, float]:
        """Return ``{knob_name: target_value}`` — empty dict for "no
        change". ``knobs`` holds current values for the bound manager's
        actual knob surface; proposals for knobs absent from it are
        dropped by the controller, so one policy can serve tiers with
        different surfaces."""


class StalenessAdmissionPolicy:
    """Guard-band admission control on the staleness p95 tail.

    While ``staleness_p95`` stays inside ``[band_lo, band_hi]`` nothing
    moves. A breach above ``band_hi`` is the 2307.06561 saturation
    signature — arrivals are aging faster than the server commits — so
    the policy *slows the version clock and sheds the tail*: it raises
    ``buffer_k`` one step toward ``k_max`` (staleness is measured in
    versions; fewer flushes per arrival directly shrinks the tail) and
    arms/tightens the ``max_staleness`` admission cap at
    ``ceil(band_hi) + cap_slack`` so hopeless stragglers are refused at
    the door instead of poisoning the buffer. On recovery below
    ``band_lo`` it relaxes one step back toward the configured baseline
    and disarms the cap last. ``cooldown`` progress units must elapse
    between actuations so the loop cannot thrash faster than telemetry
    responds.
    """

    def __init__(self, band_lo: float = 2.0, band_hi: float = 6.0, *,
                 k_max: int = 8, cap_slack: int = 2, cooldown: int = 4):
        if not 0.0 <= band_lo < band_hi:
            raise ValueError(
                f"guard band must satisfy 0 <= lo < hi, got [{band_lo}, {band_hi}]")
        self.name = "staleness_admission"
        self.band_lo = float(band_lo)
        self.band_hi = float(band_hi)
        self.k_max = int(k_max)
        self.cap_slack = int(cap_slack)
        self.cooldown = int(cooldown)
        self.reset()

    def reset(self) -> None:
        self._baseline_k: Optional[int] = None
        self._last_actuation = float("-inf")

    def propose(self, telemetry, knobs):
        p95 = telemetry.get("staleness_p95")
        if p95 is None:
            return {}
        progress = telemetry.get("progress", 0.0)
        if progress - self._last_actuation < self.cooldown:
            return {}
        out: Dict[str, float] = {}
        k = knobs.get("buffer_k")
        if k is not None and self._baseline_k is None:
            self._baseline_k = int(k)
        cap = knobs.get("max_staleness")
        if p95 > self.band_hi:
            if k is not None and k < self.k_max:
                out["buffer_k"] = int(k) + 1
            if cap is not None:
                want = int(-(-self.band_hi // 1)) + self.cap_slack
                if cap == 0 or cap > want:
                    out["max_staleness"] = want
        elif p95 < self.band_lo:
            if k is not None and self._baseline_k is not None \
                    and k > self._baseline_k:
                out["buffer_k"] = int(k) - 1
            elif cap is not None and cap != 0:
                # cap disarms only once buffer_k is back at baseline —
                # relax in reverse order of tightening
                out["max_staleness"] = 0
        if out:
            self._last_actuation = progress
        return out


class WindowSchedulePolicy:
    """1807.06629-style averaging-window schedule on eval improvement.

    Tracks the improvement rate of the monitored eval metric per unit of
    progress between consecutive eval samples. While the rate stays at or
    above ``rate_thresh`` (training is still earning its parallelism) the
    window knob — ``buffer_k`` on the buffered tier, ``aggregate_k`` on
    sync — is pushed one step toward ``w_max``; once improvement
    flattens it decays one step toward ``w_min`` per eval sample, since
    further delaying averaging only accumulates divergence. Acts only on
    fresh eval samples, so its cadence is the eval frequency, not the
    controller interval."""

    def __init__(self, *, w_min: int = 1, w_max: int = 8,
                 rate_thresh: float = 0.01, metric: str = "accuracy"):
        if not 1 <= w_min <= w_max:
            raise ValueError(f"need 1 <= w_min <= w_max, got [{w_min}, {w_max}]")
        self.name = "window_schedule"
        self.w_min = int(w_min)
        self.w_max = int(w_max)
        self.rate_thresh = float(rate_thresh)
        self.metric = metric
        self.reset()

    def reset(self) -> None:
        self._last_metric: Optional[float] = None
        self._last_progress: Optional[float] = None

    def propose(self, telemetry, knobs):
        m = telemetry.get(self.metric)
        if m is None:
            return {}
        progress = telemetry.get("progress", 0.0)
        if self._last_metric is None:
            self._last_metric, self._last_progress = m, progress
            return {}
        if progress <= self._last_progress:
            return {}  # same eval sample as last step
        rate = (m - self._last_metric) / (progress - self._last_progress)
        if self.metric == "loss":
            rate = -rate
        self._last_metric, self._last_progress = m, progress
        window = "buffer_k" if "buffer_k" in knobs else "aggregate_k"
        w = knobs.get(window)
        if w is None:
            return {}
        if rate >= self.rate_thresh and w < self.w_max:
            return {window: int(w) + 1}
        if rate < self.rate_thresh and w > self.w_min:
            return {window: int(w) - 1}
        return {}


class TimeoutAutoscalePolicy:
    """Round-timeout and ingest-worker autoscaling on eviction rate and
    dispatch occupancy.

    Evictions since the last step mean the watchdog deadline is cutting
    into the live tail: grow ``round_timeout_s`` by ``grow`` (bounded by
    ``timeout_cap`` × the initial value). After ``calm_steps``
    eviction-free steps it shrinks by the same factor back toward the
    initial value — a spike should not permanently inflate the deadline.
    Separately, sustained dispatch ``occupancy`` above ``occ_hi`` adds
    one ingest worker per step up to ``workers_max`` (grow-only; the
    pool refuses shrink). The occupancy arm is wall-clock-driven and
    therefore inert in pinned deterministic drills."""

    def __init__(self, *, grow: float = 1.5, timeout_cap: float = 4.0,
                 calm_steps: int = 3, occ_hi: float = 0.85,
                 workers_max: int = 8):
        if grow <= 1.0:
            raise ValueError(f"grow factor must exceed 1.0, got {grow}")
        self.name = "timeout_autoscale"
        self.grow = float(grow)
        self.timeout_cap = float(timeout_cap)
        self.calm_steps = int(calm_steps)
        self.occ_hi = float(occ_hi)
        self.workers_max = int(workers_max)
        self.reset()

    def reset(self) -> None:
        self._last_evictions: Optional[float] = None
        self._initial_timeout: Optional[float] = None
        self._calm = 0

    def propose(self, telemetry, knobs):
        out: Dict[str, float] = {}
        timeout = knobs.get("round_timeout_s")
        evictions = telemetry.get("evictions")
        if timeout is not None and evictions is not None:
            if self._initial_timeout is None:
                self._initial_timeout = timeout
            delta = evictions - (self._last_evictions
                                 if self._last_evictions is not None else evictions)
            self._last_evictions = evictions
            cap = self._initial_timeout * self.timeout_cap
            if delta > 0:
                self._calm = 0
                if timeout < cap:
                    out["round_timeout_s"] = min(cap, timeout * self.grow)
            else:
                self._calm += 1
                if self._calm >= self.calm_steps \
                        and timeout > self._initial_timeout:
                    self._calm = 0
                    out["round_timeout_s"] = max(self._initial_timeout,
                                                 timeout / self.grow)
        workers = knobs.get("ingest_workers")
        occ = telemetry.get("occupancy")
        if workers is not None and occ is not None and occ > self.occ_hi \
                and workers < self.workers_max:
            out["ingest_workers"] = int(workers) + 1
        return out


class FederationController:
    """Drives a list of policies against one bound manager.

    The manager invokes :meth:`step` from its safe-boundary hook
    (``_ctrl_boundary``), so every proposal is applied at a quiescent
    point on the dispatch thread — the controller itself owns no thread
    and no clock, which is what lets the identical object drive the
    virtual-time simulator and a real wall-clock run. Policies run in
    list order and later proposals win per knob; put safety policies
    (admission control) last so they override optimism. Every applied /
    refused actuation is visible three ways: the seam's flight events,
    the ``ctrl/actuation_*`` counters, and this object's
    ``actuation_log`` (the reproducibility artifact the drills pin)."""

    def __init__(self, policies: List[ControlPolicy], *, interval: int = 1):
        if interval < 1:
            raise ValueError(f"controller interval must be >= 1, got {interval}")
        self.policies = list(policies)
        self.interval = int(interval)
        self.actuation_log: List[Dict] = []
        self._last_step_progress = float("-inf")

    def bind(self) -> None:
        """Reset for a fresh manager (called by ``attach_controller``)."""
        for p in self.policies:
            p.reset()
        self.actuation_log = []
        self._last_step_progress = float("-inf")

    def step(self, manager) -> int:
        """One control step at a safe boundary: read telemetry, collect
        proposals, apply through the seam. Returns applied count."""
        seam = getattr(manager, "ctrl", None)
        if seam is None:
            return 0
        telemetry = read_telemetry(manager)
        progress = telemetry.get("progress", 0.0)
        if progress - self._last_step_progress < self.interval:
            return 0
        self._last_step_progress = progress
        knobs = seam.values()
        merged: Dict[str, tuple] = {}
        for policy in self.policies:
            for knob, value in policy.propose(telemetry, knobs).items():
                if knob in knobs:
                    merged[knob] = (value, policy.name)
        applied = 0
        from .actuator import ActuationRefused
        for knob in sorted(merged):
            value, why = merged[knob]
            entry = {"progress": progress, "knob": knob,
                     "old": knobs[knob], "new": value, "policy": why}
            try:
                seam.apply(knob, value, reason=why)
                entry["outcome"] = "applied"
                applied += 1
            except ActuationRefused as e:
                entry["outcome"] = f"refused:{e.reason}"
            self.actuation_log.append(entry)
        return applied
