"""Actuation seam: validated, boundary-gated knob setters on the server
managers.

Every robustness lever the control plane exposes — ``aggregate_k``,
``buffer_k``, ``round_timeout_s``, the staleness discount, the admission
cap, the ingest-pool width — is an instance attribute some hot path
reads live (``_k_effective()`` per round, ``self.buffer_k`` per arrival,
the watchdog per poll). A controller may therefore retune them at
runtime, but only under two disciplines this module enforces:

- **Range validation.** Each knob carries structural bounds (the same
  ones the constructors enforce); an out-of-range request REFUSES with a
  named reason instead of clamping silently — a policy that asks for
  ``buffer_k=0`` is a buggy policy, and clamping would hide it.
- **Safe boundaries.** Mutations land only where the protocol is
  quiescent for that knob: between barrier rounds (sync), at a buffer
  flush (fedbuff), never mid-flush — the manager passes a ``busy``
  probe, and an unsafe-time :meth:`ActuationSeam.apply` refuses (the
  caller can :meth:`ActuationSeam.request` instead, which queues the
  mutation for the manager's next ``apply_pending`` at a boundary).

Every outcome is observable post-mortem: applied mutations flight-record
an ``actuation`` event and bump the ``actuation_applied`` counter on the
manager's registry (the per-round ``ctrl/`` metrics stream); refusals
record ``actuation_refused`` with the named reason. A misbehaving policy
is therefore diagnosable from the same flight-recorder ring evictions
already land in (docs/ROBUSTNESS.md "Adaptive control").
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


class ActuationRefused(ValueError):
    """A knob mutation was refused; ``reason`` is the machine-readable
    refusal class (``unknown_knob`` / ``out_of_range`` / ``unsafe_now`` /
    a knob-specific constraint name)."""

    def __init__(self, knob: str, value, reason: str, detail: str = ""):
        self.knob = knob
        self.value = value
        self.reason = reason
        super().__init__(
            f"actuation refused: {knob}={value!r} ({reason})"
            + (f": {detail}" if detail else ""))


class Knob:
    """One tunable: structural bounds + live get/set closures.

    ``lo``/``hi`` are inclusive; ``cast`` coerces the requested value
    (``int`` for count knobs — a fractional ``buffer_k`` refuses via the
    cast mismatch check, not a silent truncation). ``constraint`` may
    veto values the static range admits (e.g. pool shrink): it returns a
    named reason string to refuse, or ``None`` to allow."""

    def __init__(self, name: str, get: Callable[[], float],
                 set_: Callable[[float], None], lo: float, hi: float,
                 cast=float,
                 constraint: Optional[Callable[[float], Optional[str]]] = None):
        self.name = name
        self.get = get
        self.set = set_
        self.lo = lo
        self.hi = hi
        self.cast = cast
        self.constraint = constraint

    def validate(self, value) -> Tuple[Optional[float], str]:
        """``(coerced_value, "")`` when admissible, ``(None, reason)``
        when not."""
        try:
            v = self.cast(value)
        except (TypeError, ValueError):
            return None, "uncastable"
        if self.cast is int and float(v) != float(value):
            return None, "not_integral"
        if not self.lo <= v <= self.hi:
            return None, f"out_of_range[{self.lo},{self.hi}]"
        if self.constraint is not None:
            veto = self.constraint(v)
            if veto:
                return None, veto
        return v, ""


class ActuationSeam:
    """The per-manager knob surface a controller actuates through.

    Built by the server manager's constructor with its own registry,
    flight recorder, and ``busy`` probe; the manager calls
    :meth:`apply_pending` at each safe boundary. ``request`` is
    thread-safe (any thread may queue); ``apply`` executes on the
    caller's thread and refuses when the ``busy`` probe names a reason
    (e.g. ``mid_flush``) — boundary callers (the controller step, which
    runs inside the manager's own boundary hook) apply directly."""

    def __init__(self, owner: str, knobs: List[Knob], *, registry,
                 flight=None, busy: Optional[Callable[[], Optional[str]]] = None,
                 progress: Optional[Callable[[], int]] = None):
        self.owner = owner
        self._knobs: Dict[str, Knob] = {k.name: k for k in knobs}
        self._registry = registry
        self._flight = flight
        self._busy = busy
        self._progress = progress or (lambda: -1)
        self._lock = threading.Lock()
        self._pending: Dict[str, Tuple[float, str]] = {}
        self._c_applied = registry.counter("actuation_applied")
        self._c_refused = registry.counter("actuation_refused")

    # -- introspection -------------------------------------------------------
    @property
    def names(self):
        return tuple(sorted(self._knobs))

    def get(self, knob: str) -> float:
        k = self._knobs.get(knob)
        if k is None:
            raise KeyError(f"{self.owner} has no knob {knob!r}; "
                           f"known: {self.names}")
        return k.get()

    def values(self) -> Dict[str, float]:
        return {name: k.get() for name, k in sorted(self._knobs.items())}

    def add_knob(self, knob: Knob) -> None:
        """Subclass constructors extend the parent's seam (fedbuff adds
        ``buffer_k`` to the async tier's knob set)."""
        self._knobs[knob.name] = knob

    # -- mutation ------------------------------------------------------------
    def _refuse(self, knob: str, value, reason: str) -> ActuationRefused:
        self._c_refused.inc()
        if self._flight is not None:
            self._flight.record("actuation_refused", knob=knob,
                                value=value, reason=reason,
                                progress=self._progress())
            self._flight.dump()
        return ActuationRefused(knob, value, reason)

    def apply(self, knob: str, value, *, reason: str = "manual") -> float:
        """Validate and set ``knob`` now. Returns the applied value;
        raises :class:`ActuationRefused` (after counting and
        flight-recording the refusal) on an unknown knob, an out-of-range
        or vetoed value, or an unsafe call time."""
        k = self._knobs.get(knob)
        if k is None:
            raise self._refuse(knob, value, "unknown_knob")
        busy = self._busy() if self._busy is not None else None
        if busy:
            raise self._refuse(knob, value, busy)
        v, veto = k.validate(value)
        if v is None:
            raise self._refuse(knob, value, veto)
        old = k.get()
        if v == old:
            return old  # no-op: nothing recorded, nothing counted
        k.set(v)
        self._c_applied.inc()
        if self._flight is not None:
            self._flight.record("actuation", knob=knob, old=old, new=v,
                                reason=reason, progress=self._progress())
            self._flight.dump()
        return v

    def request(self, knob: str, value, *, reason: str = "manual") -> None:
        """Queue a mutation for the manager's next safe boundary
        (``apply_pending``). Unknown knobs refuse immediately — the
        caller's mistake should not surface rounds later; range and veto
        checks run at apply time against then-current state."""
        if knob not in self._knobs:
            raise self._refuse(knob, value, "unknown_knob")
        with self._lock:
            self._pending[knob] = (value, reason)

    def apply_pending(self) -> int:
        """Drain the request queue at a safe boundary (called by the
        manager). Refusals are counted and recorded but do not raise —
        one bad queued request must not unwind the manager's round
        commit. Returns the number of applied mutations."""
        with self._lock:
            pending, self._pending = self._pending, {}
        applied = 0
        for knob in sorted(pending):
            value, reason = pending[knob]
            try:
                self.apply(knob, value, reason=reason)
                applied += 1
            except ActuationRefused:
                pass  # counted + flight-recorded by apply()
        return applied
