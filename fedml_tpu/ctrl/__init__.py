"""Self-tuning federation control (ROADMAP item 4).

Closes the loop from the live telemetry the repo already emits (registry
histograms, ``health()``, ``ingest_profile()``, flight events) to the
knobs the server managers already expose — through a validated,
boundary-gated actuation seam. See docs/ROBUSTNESS.md "Adaptive
control" for the operational contract.
"""

from .actuator import ActuationRefused, ActuationSeam, Knob
from .policy import (
    ControlPolicy,
    FederationController,
    StalenessAdmissionPolicy,
    TimeoutAutoscalePolicy,
    WindowSchedulePolicy,
    read_telemetry,
)

__all__ = [
    "ActuationRefused",
    "ActuationSeam",
    "ControlPolicy",
    "FederationController",
    "Knob",
    "StalenessAdmissionPolicy",
    "TimeoutAutoscalePolicy",
    "WindowSchedulePolicy",
    "controller_from_args",
    "read_telemetry",
]


def controller_from_args(args):
    """Build the controller selected by ``--controller`` (None when the
    flag is ``none``, the default — the managers then behave bit-equal
    to a build without this subsystem)."""
    kind = getattr(args, "controller", "none")
    if kind == "none":
        return None
    if kind != "adaptive":
        raise SystemExit(f"unknown --controller {kind!r}; expected none|adaptive")
    band_lo = getattr(args, "controller_band_lo", 2.0)
    band_hi = getattr(args, "controller_band_hi", 6.0)
    return FederationController(
        [
            WindowSchedulePolicy(),
            TimeoutAutoscalePolicy(),
            # safety last: admission control overrides the optimistic arms
            StalenessAdmissionPolicy(band_lo=band_lo, band_hi=band_hi),
        ],
        interval=getattr(args, "controller_interval", 1),
    )
