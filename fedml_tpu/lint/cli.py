"""fedlint command-line interface (``scripts/fedlint.py`` is the entry).

Exit status: 0 when every unsuppressed finding is covered by the
baseline, 1 when new findings exist (for ``--fix``: when new,
non-baselined findings remain that it could not rewrite), 2 on usage
errors, including paths that do not exist — a typo'd gate path must
fail loudly. ``--write-baseline`` snapshots the current findings as
the new debt ledger.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from fedml_tpu.lint.analyzer import RULES, Violation, analyze_paths
from fedml_tpu.lint.baseline import (
    load_baseline,
    new_violations,
    write_baseline,
)
from fedml_tpu.lint.fix import apply_fixes, plan_fixes

DEFAULT_BASELINE = "fedlint.baseline.json"


def _to_json(violations: List[Violation]) -> str:
    return json.dumps(
        [
            {
                "rule": v.rule,
                "slug": RULES[v.rule][0],
                "path": v.path,
                "line": v.line,
                "col": v.col,
                "severity": v.severity,
                "message": v.message,
                "suppressed": v.suppressed,
                "suppress_reason": v.suppress_reason,
                "fixable": v.fix is not None,
            }
            for v in violations
        ],
        indent=2,
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedlint",
        description="AST analysis for the JAX pitfalls this codebase has "
                    "hit (R1 carried rng chains, R2 staging aliasing, R3 "
                    "host syncs in hot paths, R4 recompile hazards, R5 "
                    "donation misuse). See docs/LINT.md.")
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: ./{DEFAULT_BASELINE} "
                         "when it exists; missing file == empty)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings as the baseline and "
                         "exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. R1,R3")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--fix", action="store_true",
                    help="apply the mechanical R1 rewrite "
                         "(split-chain -> fold_in-on-index)")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --fix: print the diff, change nothing")
    args = ap.parse_args(argv)

    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - set(RULES)
        if unknown:
            ap.error(f"unknown rules: {', '.join(sorted(unknown))}")
    else:
        wanted = set(RULES)

    try:
        all_v = [v for v in analyze_paths(args.paths) if v.rule in wanted]
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    active = [v for v in all_v if not v.suppressed]

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        path = baseline_path or DEFAULT_BASELINE
        write_baseline(path, active)
        print(f"fedlint: wrote baseline with {len(active)} finding(s) "
              f"to {path}")
        return 0

    if args.fix:
        plans = plan_fixes(active)
        n = sum(len(e) for e in plans.values())
        diff = apply_fixes(plans, dry_run=args.dry_run)
        if diff:
            sys.stdout.write(diff)
        verb = "would rewrite" if args.dry_run else "rewrote"
        print(f"fedlint --fix: {verb} {n} R1 site(s) in "
              f"{len(plans)} file(s)")
        rest = [v for v in active if not (v.rule == "R1" and v.fix)]
        if rest:
            print(f"fedlint --fix: {len(rest)} finding(s) need manual "
                  "attention:")
            for v in rest:
                print("  " + v.format())
        # Exit status mirrors the gate: only findings NOT covered by the
        # baseline fail the command (grandfathered debt stays exit 0).
        rest_new = new_violations(rest, load_baseline(baseline_path or ""))
        return 0 if not rest_new else 1

    fresh = new_violations(active, load_baseline(baseline_path or ""))
    shown = all_v if args.show_suppressed else active
    if args.format == "json":
        print(_to_json(shown))
    else:
        for v in shown:
            print(v.format())
        known = len(active) - len(fresh)
        summary = (f"fedlint: {len(fresh)} new finding(s), {known} "
                   f"baselined, "
                   f"{sum(1 for v in all_v if v.suppressed)} suppressed "
                   f"across {len(set(v.path for v in all_v)) or 0} "
                   "file(s)")
        print(summary)
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
