"""fedlint command-line interface (``scripts/fedlint.py`` is the entry).

Exit status: 0 when every unsuppressed finding is covered by the
baseline, 1 when new findings exist (for ``--fix``: when new,
non-baselined findings remain that it could not rewrite), 2 on usage
errors, including paths that do not exist — a typo'd gate path must
fail loudly. ``--write-baseline`` snapshots the current findings as
the new debt ledger.

``--changed[=REF]`` narrows the run to files touched vs a git ref
(default ``HEAD``) plus untracked files — same rules, same baseline
semantics, same exit codes; only the file set shrinks (so the
pre-commit loop on a 1-core box stops paying the whole-package sweep).
U1 (dead suppressions) stays advisory unless ``--no-unused-
suppressions`` makes it gate, which is how ci.sh runs it.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from fedml_tpu.lint.analyzer import RULES, Violation, analyze_paths
from fedml_tpu.lint.baseline import (
    load_baseline,
    new_violations,
    write_baseline,
)
from fedml_tpu.lint.fix import apply_fixes, plan_fixes

DEFAULT_BASELINE = "fedlint.baseline.json"


def _to_json(violations: List[Violation]) -> str:
    return json.dumps(
        [
            {
                "rule": v.rule,
                "slug": RULES[v.rule][0],
                "path": v.path,
                "line": v.line,
                "col": v.col,
                "severity": v.severity,
                "message": v.message,
                "suppressed": v.suppressed,
                "suppress_reason": v.suppress_reason,
                "fixable": v.fix is not None,
            }
            for v in violations
        ],
        indent=2,
    )


def _changed_files(ref: str, paths: List[str]) -> Optional[List[str]]:
    """Intersect the expanded ``paths`` file set with the files touched
    vs ``ref`` (diff + untracked). None on git failure (usage error)."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        diff = subprocess.run(
            ["git", "-C", top, "diff", "--name-only",
             "--diff-filter=ACMR", ref],
            capture_output=True, text=True, check=True).stdout
        untracked = subprocess.run(
            ["git", "-C", top, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        msg = getattr(e, "stderr", "") or str(e)
        print(f"fedlint --changed: git failed: {msg.strip()}",
              file=sys.stderr)
        return None
    touched = {os.path.realpath(os.path.join(top, ln))
               for ln in (diff + untracked).splitlines() if ln.strip()}
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for f in sorted(files):
                    fp = os.path.join(root, f)
                    if f.endswith(".py") \
                            and os.path.realpath(fp) in touched:
                        out.append(fp)
        elif os.path.isfile(p) and os.path.realpath(p) in touched:
            out.append(p)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fedlint",
        description="AST analysis for the pitfalls this codebase has "
                    "hit: the JAX family (R1 carried rng chains, R2 "
                    "staging aliasing, R3 host syncs in hot paths, R4 "
                    "recompile hazards, R5 donation misuse) and the "
                    "federation control-plane family (P1 thread-shared "
                    "state, P2 drop-without-reply, P3 flag-refusal "
                    "coverage, P4 copy-divergence, U1 dead "
                    "suppressions). See docs/LINT.md.")
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: ./{DEFAULT_BASELINE} "
                         "when it exists; missing file == empty)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings as the baseline and "
                         "exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. R1,R3")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--fix", action="store_true",
                    help="apply the mechanical R1 rewrite "
                         "(split-chain -> fold_in-on-index)")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --fix: print the diff, change nothing")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="only analyze files touched vs the git ref "
                         "(default HEAD) plus untracked files; exit "
                         "codes and baseline semantics are identical "
                         "to a full run")
    ap.add_argument("--no-unused-suppressions", action="store_true",
                    help="make U1 (dead suppressions / stale twin-of "
                         "annotations) gate the exit code instead of "
                         "being advisory")
    ap.add_argument("--thread-report", action="store_true",
                    help="print the per-class thread model (which "
                         "methods run on which threads, which attrs "
                         "are shared) and exit 0")
    args = ap.parse_args(argv)

    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - set(RULES)
        if unknown:
            ap.error(f"unknown rules: {', '.join(sorted(unknown))}")
    else:
        wanted = set(RULES)

    if args.thread_report:
        from fedml_tpu.lint.protocol import thread_model_report

        report = thread_model_report(args.paths)
        print(report or "fedlint: no multithreaded manager classes found")
        return 0

    paths: List[str] = args.paths
    partial = False
    if args.changed is not None:
        changed = _changed_files(args.changed, args.paths)
        if changed is None:
            return 2
        if not changed:
            print("fedlint --changed: no touched .py files under the "
                  "given paths")
            return 0
        paths, partial = changed, True

    try:
        all_v = [v for v in analyze_paths(paths, partial=partial)
                 if v.rule in wanted]
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    active = [v for v in all_v if not v.suppressed]

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        path = baseline_path or DEFAULT_BASELINE
        write_baseline(path, active)
        print(f"fedlint: wrote baseline with {len(active)} finding(s) "
              f"to {path}")
        return 0

    if args.fix:
        plans = plan_fixes(active)
        n = sum(len(e) for e in plans.values())
        diff = apply_fixes(plans, dry_run=args.dry_run)
        if diff:
            sys.stdout.write(diff)
        verb = "would rewrite" if args.dry_run else "rewrote"
        print(f"fedlint --fix: {verb} {n} R1 site(s) in "
              f"{len(plans)} file(s)")
        rest = [v for v in active if not (v.rule == "R1" and v.fix)]
        if rest:
            print(f"fedlint --fix: {len(rest)} finding(s) need manual "
                  "attention:")
            for v in rest:
                print("  " + v.format())
        # Exit status mirrors the gate: only findings NOT covered by the
        # baseline fail the command (grandfathered debt stays exit 0).
        rest_new = new_violations(rest, load_baseline(baseline_path or ""))
        return 0 if not rest_new else 1

    fresh = new_violations(active, load_baseline(baseline_path or ""))
    # U1 is advisory by default: printed, but only gating under
    # --no-unused-suppressions (ci.sh runs strict).
    gating = fresh if args.no_unused_suppressions \
        else [v for v in fresh if v.rule != "U1"]
    shown = all_v if args.show_suppressed else active
    if args.format == "json":
        print(_to_json(shown))
    else:
        for v in shown:
            print(v.format())
        known = len(active) - len(fresh)
        advisory = len(fresh) - len(gating)
        summary = (f"fedlint: {len(gating)} new finding(s)"
                   + (f" (+{advisory} advisory)" if advisory else "")
                   + f", {known} baselined, "
                   f"{sum(1 for v in all_v if v.suppressed)} suppressed "
                   f"across {len(set(v.path for v in all_v)) or 0} "
                   "file(s)")
        print(summary)
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
