"""``fedlint --fix``: the mechanical R1 rewrite, straight-line cases only.

Rewrites

    for i in ...:
        rng, sub = jax.random.split(rng)        # carried chain
        ...

to

    for i in ...:
        sub = jax.random.fold_in(rng, i)        # prefix-stable
        ...

The analyzer only attaches a fix payload when the case is genuinely
mechanical: a Python ``for`` loop with a simple index variable, a
two-target split of a plain local name, and no other use of the carried
key inside the loop body (checked here against the raw line text of the
loop span — conservative, so a miss means "no fix", never a wrong fix).

NOTE the rewrite is a *migration*, not an identity: fold_in draws a
different stream than the carried chain, so pinned trajectories change.
That is the point — the new stream is prefix-stable — but it is why the
default mode is a dry-run diff and tests/bit-pins must be recalibrated
by the caller.
"""

from __future__ import annotations

import difflib
import re
from typing import Dict, List, Sequence, Tuple

from fedml_tpu.lint.analyzer import Violation


def plan_fixes(violations: Sequence[Violation]
               ) -> Dict[str, List[Tuple[int, str]]]:
    """path -> [(line, replacement_source_line)]. Only R1 violations that
    carry a fix payload and whose source line round-trips the expected
    shape are planned; everything else is left for a human."""
    out: Dict[str, List[Tuple[int, str]]] = {}
    for v in violations:
        if v.rule != "R1" or v.fix is None or v.suppressed:
            continue
        loop_var, key, sub = v.fix
        # Expected shape: "<key>, <sub> = <mod>.split(<key>)" (module
        # path free; trailing comment preserved).
        m = re.match(
            rf"^(\s*){re.escape(key)}\s*,\s*{re.escape(sub)}\s*=\s*"
            rf"([\w.]*?)split\(\s*{re.escape(key)}\s*\)\s*(#.*)?$",
            _line_at(v))
        if not m:
            continue
        indent, mod, comment = m.group(1), m.group(2), m.group(3) or ""
        mod = mod[:-1] if mod.endswith(".") else mod
        fold = f"{mod}.fold_in" if mod else "fold_in"
        new = f"{indent}{sub} = {fold}({key}, {loop_var})"
        if comment:
            new += f"  {comment}"
        out.setdefault(v.path, []).append((v.line, new))
    return out


def _line_at(v: Violation) -> str:
    with open(v.path, "r", encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    return lines[v.line - 1] if 0 < v.line <= len(lines) else ""


def apply_fixes(plans: Dict[str, List[Tuple[int, str]]],
                dry_run: bool = True) -> str:
    """Apply (or just diff, when ``dry_run``) the planned rewrites.
    Returns the unified diff across all touched files."""
    diffs: List[str] = []
    for path, edits in sorted(plans.items()):
        with open(path, "r", encoding="utf-8") as fh:
            old = fh.read().splitlines(keepends=True)
        new = list(old)
        for line, repl in edits:
            new[line - 1] = repl + "\n"
        diff = difflib.unified_diff(old, new, fromfile=f"a/{path}",
                                    tofile=f"b/{path}")
        diffs.extend(diff)
        if not dry_run:
            with open(path, "w", encoding="utf-8") as fh:
                fh.writelines(new)
    return "".join(diffs)
