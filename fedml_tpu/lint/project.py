"""fedlint project-wide passes P3–P4: analyses that need more than one
module at a time.

P3 ``flag-refusal-coverage``
    ``exp/args.py`` defines the shared CLI surface and the
    ``reject_*_flags`` refusal helpers; every *driver* (a module that
    calls ``parse_args``/``add_args`` and then reads ``args``) must,
    for each gated flag group, either consume the flags or call the
    matching refusal helper — otherwise ``--agg_shards 4`` on that
    driver is silently inert (the bug class PRs 4, 6, 12 and 14 fixed
    by hand, one driver at a time). Consumption that happens indirectly
    (through ``config_from_args``/``setup_standard``) is declared with
    a ``consumes(flag_a, flag_b)`` fedlint comment, which is itself
    checked: the declared flag must exist.

    Two secondary warnings close the loop from the other side: a flag
    defined in ``add_args`` that no analyzed module ever reads and no
    helper gates (orphan flag), and a ``FedConfig`` field populated by
    ``config_from_args`` that nothing ever reads (dead config plumbing).

P4 ``copy-divergence``
    Normalized-AST near-clone detection across modules. The sync /
    async / fedbuff / shardplane managers historically copied handler
    logic and then diverged silently (the PR 10 decoder-cache lesson).
    Function pairs in *different* files whose normalized statement
    streams match above a similarity threshold must either be factored
    or carry an explicit ``twin-of(<path>)`` fedlint annotation on
    one side, acknowledging the twin so future edits know to mirror.
"""

from __future__ import annotations

import ast
import difflib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.lint.analyzer import (
    RULES,
    Violation,
    _call_tail,
    _dotted,
    _parse_suppressions,
)

_CONSUMES_RE = re.compile(r"#\s*fedlint:\s*consumes\(([^)]*)\)")
_TWIN_RE = re.compile(r"#\s*fedlint:\s*twin-of\(([^)]*)\)")

#: P4 tuning: functions shorter than this many normalized statements
#: are idiom, not clones; pairs at or above this similarity are twins.
#: 10 is low enough to hold the decode-task closures the sync and shard
#: planes share (the PR 10 divergence site) above the floor.
P4_MIN_STMTS = 10
P4_SIMILARITY = 0.85


@dataclass
class _Module:
    path: str
    source: str
    tree: ast.Module
    lines: List[str]
    suppressions: Dict[int, Dict[str, Optional[str]]]
    consumes: Set[str] = field(default_factory=set)
    #: line -> declared twin path (from the twin-of directive)
    twins: Dict[int, str] = field(default_factory=dict)
    twin_used: Set[int] = field(default_factory=set)


def _load(sources: Dict[str, str]) -> List[_Module]:
    mods: List[_Module] = []
    for path in sorted(sources):
        source = sources[path]
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        m = _Module(path=path, source=source, tree=tree,
                    lines=source.splitlines(),
                    suppressions=_parse_suppressions(source))
        for match in _CONSUMES_RE.finditer(source):
            m.consumes |= {f.strip() for f in match.group(1).split(",")
                           if f.strip()}
        for i, raw in enumerate(m.lines, start=1):
            t = _TWIN_RE.search(raw)
            if t:
                m.twins[i] = t.group(1).strip()
        mods.append(m)
    return mods


def _violation(mod: _Module, rule: str, line: int, message: str,
               severity: Optional[str] = None) -> Violation:
    sup = mod.suppressions.get(line, {})
    v = Violation(
        rule=rule, path=mod.path, line=line, col=0, message=message,
        severity=severity or RULES[rule][1],
        source_line=(mod.lines[line - 1].strip()
                     if 0 < line <= len(mod.lines) else ""))
    if rule in sup:
        v.suppressed = True
        v.suppress_reason = sup[rule]
    return v


# -- P3: flag-refusal coverage -------------------------------------------

@dataclass
class _ArgsSurface:
    mod: _Module
    flags: Set[str] = field(default_factory=set)
    flag_lines: Dict[str, int] = field(default_factory=dict)
    #: reject helper name -> flags it refuses
    helpers: Dict[str, Set[str]] = field(default_factory=dict)
    helper_lines: Dict[str, int] = field(default_factory=dict)
    #: FedConfig field -> line in config_from_args
    cfg_fields: Dict[str, int] = field(default_factory=dict)


def _args_reads(tree: ast.AST, names: Sequence[str] = ("args",)) -> Set[str]:
    """Flags read off an ``args`` namespace: ``args.x`` attribute loads
    and ``getattr(args, "x", ...)``."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id in names:
            out.add(n.attr)
        if isinstance(n, ast.Call) and _call_tail(n) == "getattr" \
                and len(n.args) >= 2 \
                and isinstance(n.args[0], ast.Name) \
                and n.args[0].id in names \
                and isinstance(n.args[1], ast.Constant) \
                and isinstance(n.args[1].value, str):
            out.add(n.args[1].value)
    return out


def _find_args_surface(mods: List[_Module]) -> Optional[_ArgsSurface]:
    for mod in mods:
        funcs = {n.name: n for n in ast.walk(mod.tree)
                 if isinstance(n, ast.FunctionDef)}
        add = funcs.get("add_args")
        if add is None:
            continue
        surface = _ArgsSurface(mod=mod)
        for n in ast.walk(add):
            if isinstance(n, ast.Call) and _call_tail(n) == "add_argument" \
                    and n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str) \
                    and n.args[0].value.startswith("--"):
                flag = n.args[0].value.lstrip("-").replace("-", "_")
                surface.flags.add(flag)
                surface.flag_lines[flag] = n.lineno
        if not surface.flags:
            continue
        for name, fn in funcs.items():
            if not name.startswith("reject_"):
                continue
            gated = _args_reads(fn) & surface.flags
            if gated:
                surface.helpers[name] = gated
                surface.helper_lines[name] = fn.lineno
        cfa = funcs.get("config_from_args")
        if cfa is not None:
            for n in ast.walk(cfa):
                if isinstance(n, ast.Call) and _call_tail(n) \
                        in {"FedConfig", "replace"}:
                    for kw in n.keywords:
                        if kw.arg:
                            surface.cfg_fields[kw.arg] = kw.value.lineno
        return surface
    return None


def _is_driver(mod: _Module, surface: _ArgsSurface) -> bool:
    """A driver binds the SHARED CLI surface: it imports from the args
    module (or calls ``add_args``) and then parses + reads ``args``.
    Merely owning some other argparse CLI (fedlint's own, say) with a
    local ``parse_args`` call does not make a module a driver."""
    if mod is surface.mod:
        return False
    calls = {_call_tail(n) for n in ast.walk(mod.tree)
             if isinstance(n, ast.Call)}
    stem = surface.mod.path.rsplit("/", 1)[-1].rsplit(".", 1)[0]
    imports_surface = any(
        isinstance(n, ast.ImportFrom) and n.module
        and n.module.rsplit(".", 1)[-1] == stem
        for n in ast.walk(mod.tree))
    if "add_args" not in calls and not imports_surface:
        return False
    return bool({"parse_args", "add_args"} & calls) \
        and bool(_args_reads(mod.tree))


def _driver_anchor(mod: _Module) -> int:
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and _call_tail(n) == "parse_args":
            return n.lineno
    for n in mod.tree.body:
        if isinstance(n, ast.FunctionDef) and n.name == "main":
            return n.lineno
    return 1


def _check_p3(mods: List[_Module],
              partial: bool = False) -> List[Violation]:
    surface = _find_args_surface(mods)
    if surface is None:
        return []
    out: List[Violation] = []
    all_reads: Set[str] = set()
    drivers = [m for m in mods if _is_driver(m, surface)]
    for mod in mods:
        names = ("args", "a") if mod is surface.mod else ("args",)
        all_reads |= _args_reads(mod.tree, names) & surface.flags

    for mod in drivers:
        reads = _args_reads(mod.tree) & surface.flags
        called = {_call_tail(n) for n in ast.walk(mod.tree)
                  if isinstance(n, ast.Call)}
        bogus = mod.consumes - surface.flags
        anchor = _driver_anchor(mod)
        if bogus:
            out.append(_violation(
                mod, "P3", anchor,
                "fedlint: consumes() declares flag(s) that exp/args.py "
                f"does not define: {', '.join(sorted(bogus))}",
                severity="warning"))
        covered = reads | mod.consumes
        for helper in sorted(surface.helpers):
            gated = surface.helpers[helper]
            if helper in called:
                continue
            missing = sorted(gated - covered)
            if not missing:
                continue
            out.append(_violation(
                mod, "P3", anchor,
                f"driver neither consumes nor refuses gated flag(s) "
                f"{', '.join('--' + f for f in missing)}: call "
                f"{helper}(args, ...) so the flag fails loudly instead "
                "of being silently inert, or read it (declare indirect "
                "consumption with a fedlint consumes(...) comment)"))

    # The dead-flag / dead-field warnings are WHOLE-PROGRAM properties:
    # a flag is only dead if NO module reads it. On a --changed subset
    # (args.py in the diff, its consumers not) absence of a reader means
    # nothing — skip them rather than spray false positives. The
    # per-driver coverage checks above stay: driver and surface are both
    # in the set, so those judgments are complete.
    if drivers and not partial:
        gated_anywhere: Set[str] = set()
        for gated in surface.helpers.values():
            gated_anywhere |= gated
        for flag in sorted(surface.flags):
            if flag not in all_reads and flag not in gated_anywhere:
                out.append(_violation(
                    surface.mod, "P3", surface.flag_lines[flag],
                    f"--{flag} is defined but no analyzed module reads "
                    "it and no reject_* helper gates it: dead flag "
                    "surface (wire it up, gate it, or drop it)",
                    severity="warning"))
        field_reads: Set[str] = set()
        for mod in mods:
            if mod is surface.mod:
                continue
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.Attribute):
                    field_reads.add(n.attr)
                elif isinstance(n, ast.Call) \
                        and _call_tail(n) == "getattr" \
                        and len(n.args) >= 2 \
                        and isinstance(n.args[1], ast.Constant) \
                        and isinstance(n.args[1].value, str):
                    # getattr(cfg, "field", default) reads count too —
                    # the duck-typed config idiom all over algos/.
                    field_reads.add(n.args[1].value)
        for fld in sorted(surface.cfg_fields):
            if fld not in field_reads:
                out.append(_violation(
                    surface.mod, "P3", surface.cfg_fields[fld],
                    f"FedConfig field {fld!r} is populated by "
                    "config_from_args but never read by any analyzed "
                    "module: dead config plumbing", severity="warning"))
    return out


# -- P4: copy-divergence --------------------------------------------------

@dataclass
class _Fingerprint:
    mod: _Module
    qualname: str
    line: int
    tokens: List[str]
    bag: Set[str]


def _normalize_stmt(stmt: ast.stmt) -> str:
    """One token per statement: the statement's shape with identifiers
    erased but attribute/call vocabulary kept, so renamed locals still
    match while genuinely different protocol logic does not."""
    parts: List[str] = [type(stmt).__name__]
    for n in ast.walk(stmt):
        if isinstance(n, ast.Attribute):
            parts.append(f".{n.attr}")
        elif isinstance(n, ast.Call):
            tail = _call_tail(n)
            if tail:
                parts.append(f"{tail}()")
        elif isinstance(n, ast.Constant):
            parts.append("c")
        elif isinstance(n, (ast.For, ast.While, ast.If, ast.With,
                            ast.Try, ast.Return, ast.Raise)):
            parts.append(type(n).__name__)
    return "|".join(parts)


def _fingerprints(mods: List[_Module]) -> List[_Fingerprint]:
    out: List[_Fingerprint] = []
    for mod in mods:
        stack: List[Tuple[ast.AST, str]] = [(mod.tree, "")]
        while stack:
            node, prefix = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    stack.append((child, f"{prefix}{child.name}."))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    tokens = [_normalize_stmt(s) for s in ast.walk(child)
                              if isinstance(s, ast.stmt)
                              and s is not child]
                    if len(tokens) >= P4_MIN_STMTS:
                        out.append(_Fingerprint(
                            mod=mod, qualname=qual, line=child.lineno,
                            tokens=tokens, bag=set(tokens)))
                    stack.append((child, f"{prefix}{child.name}.<locals>."))
    return out


def _twin_declared(fp: _Fingerprint, other: _Fingerprint) -> bool:
    """True when ``fp``'s def line (or the line above) carries a
    ``twin-of(<path>)`` fedlint comment naming ``other``'s file."""
    for line in (fp.line, fp.line - 1):
        declared = fp.mod.twins.get(line)
        if declared and (other.mod.path.endswith(declared)
                         or declared in other.mod.path):
            fp.mod.twin_used.add(line)
            return True
    return False


def _check_p4(mods: List[_Module]) -> List[Violation]:
    fps = _fingerprints(mods)
    out: List[Violation] = []
    seen: Set[Tuple[str, int]] = set()
    for i, a in enumerate(fps):
        for b in fps[i + 1:]:
            if a.mod.path == b.mod.path:
                continue
            la, lb = len(a.tokens), len(b.tokens)
            if min(la, lb) * 1.0 / max(la, lb) < 0.6:
                continue
            inter = len(a.bag & b.bag)
            union = len(a.bag | b.bag)
            if union == 0 or inter / union < 0.5:
                continue
            ratio = difflib.SequenceMatcher(
                a=a.tokens, b=b.tokens, autojunk=False).ratio()
            if ratio < P4_SIMILARITY:
                continue
            # report on the later file (sorted order) so the finding
            # has one stable home
            first, second = ((a, b) if a.mod.path < b.mod.path
                             else (b, a))
            # Evaluate BOTH sides (no short-circuit): either side's
            # annotation acknowledges the pair, and both must be marked
            # used or the quieter side's annotation reads as dead (U1).
            declared_second = _twin_declared(second, first)
            declared_first = _twin_declared(first, second)
            suppressed_by_twin = declared_second or declared_first
            if (second.mod.path, second.line) in seen:
                continue
            seen.add((second.mod.path, second.line))
            v = _violation(
                second.mod, "P4", second.line,
                f"{second.qualname} is a near-clone of "
                f"{first.mod.path}:{first.line} ({first.qualname}, "
                f"similarity {ratio:.2f}): protocol twins diverge "
                "silently — factor the shared logic or annotate "
                "the def with a fedlint twin-of(<path>) comment so "
                "future edits mirror "
                "both sides")
            if suppressed_by_twin:
                v.suppressed = True
                v.suppress_reason = "twin-of annotation"
            out.append(v)
    return out


def _unused_twins(mods: List[_Module]) -> List[Violation]:
    out: List[Violation] = []
    for mod in mods:
        for line in sorted(set(mod.twins) - mod.twin_used):
            out.append(_violation(
                mod, "U1", line,
                f"twin-of({mod.twins[line]}) annotation matches no "
                "P4 near-clone pair: the twin diverged past the "
                "similarity threshold (re-mirror it) or the annotation "
                "is stale (drop it)"))
    return out


def analyze_project(sources: Dict[str, str],
                    partial: bool = False) -> List[Violation]:
    """Run the project-wide passes over ``{path: source}``. Used by
    ``analyze_paths`` for real trees and directly by fixture tests.
    ``partial=True`` marks the set as a subset of the real project
    (``--changed``): the whole-program P3 warnings and the stale
    twin-of sweep are skipped — their judgments need every file."""
    mods = _load(sources)
    out = _check_p3(mods, partial=partial) + _check_p4(mods)
    if not partial:
        out.extend(_unused_twins(mods))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
