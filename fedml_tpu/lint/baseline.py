"""Baseline workflow: the CLI exits nonzero only on NEW violations.

A baseline entry fingerprints a finding by ``(rule, path, normalized
source line, occurrence index)`` — deliberately NOT the line number, so
unrelated edits above a grandfathered finding do not churn the file.
The checked-in ``fedlint.baseline.json`` is the debt ledger: an empty
one (the state this repo keeps) means the tree is clean and every new
finding fails CI immediately.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Sequence

from fedml_tpu.lint.analyzer import Violation

_VERSION = 1


def _norm_path(path: str) -> str:
    return os.path.relpath(path).replace(os.sep, "/")


def fingerprint(violations: Sequence[Violation]) -> List[str]:
    """Stable ids, disambiguating repeats of the same source line with
    an occurrence counter."""
    seen: Dict[str, int] = {}
    out = []
    for v in violations:
        base = f"{v.rule}|{_norm_path(v.path)}|{' '.join(v.source_line.split())}"
        n = seen.get(base, 0)
        seen[base] = n + 1
        out.append(f"{base}|#{n}")
    return out


def load_baseline(path: str) -> List[str]:
    """Missing file == empty baseline (a fresh tree owes nothing)."""
    if not path or not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return list(data.get("violations", []))


def write_baseline(path: str, violations: Sequence[Violation]) -> None:
    data = {"version": _VERSION, "violations": fingerprint(violations)}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def new_violations(violations: Sequence[Violation],
                   baseline: Iterable[str]) -> List[Violation]:
    known = set(baseline)
    fps = fingerprint(violations)
    return [v for v, fp in zip(violations, fps) if fp not in known]
