"""The fedlint AST analyzer: rules R1–R5 over one module at a time.

Scope and honesty notes. This is a *project* linter, not a general JAX
verifier: resolution is per-module and name-based (a function passed to
``lax.scan`` in another module is invisible), and the rules encode the
failure modes this repo has actually shipped, with allowlists tuned to
its idioms (shape/ndim/len reads are static, ``is None`` tests are
static, ...). False negatives are accepted; false positives are meant
to be rare enough that ``# fedlint: disable=RULE(reason)`` stays an
explicit, reviewed act rather than reflex.

Traced-context discovery: a function is **hot** when it is (a) passed
to / decorated with a tracing entry point (``jit``, ``pmap``, ``vmap``,
``grad``, ``value_and_grad``, ``checkpoint``/``remat``, ``shard_map``),
(b) passed to a structured-control primitive (``lax.scan``,
``fori_loop``, ``while_loop``, ``cond``, ``switch``, ``associative_
scan`` — additionally marked as a *scan body*), or (c) called by a hot
function defined in the same module. R1 severities key off this: a
carried split chain inside a scan body or a loop in hot code is an
error (its stream depends on the traced trip count — PR 1's bug); the
same chain in a host-side loop is a warning (prefix-stable in round
order, but worth an explicit suppression where it is deliberate).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: rule id -> (slug, default severity, one-line description)
RULES: Dict[str, Tuple[str, str, str]] = {
    "R1": (
        "carried-rng-chain",
        "error",
        "carried random.split chain / key reuse in a scan-or-loop body; "
        "derive per-step keys with fold_in on the step index",
    ),
    "R2": (
        "staging-alias",
        "error",
        "device_put/window_put of a buffer that is mutated later in the "
        "same scope (zero-copy aliasing corrupts the device array)",
    ),
    "R3": (
        "host-sync-in-hot-path",
        "error",
        "host synchronization inside a jit/scan/shard_map-reachable "
        "function (.item(), float()/int()/np.asarray of device values)",
    ),
    "R4": (
        "recompile-hazard",
        "warning",
        "recompile/trace hazard inside traced code (Python branch on a "
        "tracer, unhashable static arg, print, Python-state mutation)",
    ),
    "R5": (
        "donation-misuse",
        "error",
        "argument read after being passed in a donate_argnums position "
        "(the buffer is deleted by donation)",
    ),
    # protocol/concurrency family (lint/protocol.py, lint/project.py):
    # the control-plane bug classes, not the JAX ones
    "P1": (
        "thread-shared-state",
        "error",
        "self attribute shared across manager thread classes (dispatch "
        "/ watchdog / beat / ingest-pool) accessed outside the lock",
    ),
    "P2": (
        "drop-without-reply",
        "error",
        "upload-handler path drops a message with no reply, refusal "
        "helper, eviction, flush-barrier deferral, or recorded progress",
    ),
    "P3": (
        "flag-refusal-coverage",
        "error",
        "driver neither consumes nor refuses a gated CLI flag (the "
        "flag would be silently inert); plus orphan-flag / dead-config "
        "warnings",
    ),
    "P4": (
        "copy-divergence",
        "warning",
        "near-clone of a protocol twin in another module: factor the "
        "shared logic or annotate the def with twin-of(<path>)",
    ),
    "U1": (
        "unused-suppression",
        "warning",
        "fedlint suppression (or twin-of annotation) whose rule no "
        "longer fires on the covered line",
    ),
}

#: rules that need the whole file set at once (lint/project.py); the
#: rest run per-module.
PROJECT_RULES = frozenset({"P3", "P4"})

_TRACING = {"jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
            "checkpoint", "remat", "shard_map"}
_LOOPING = {"scan", "fori_loop", "while_loop", "associative_scan",
            "cond", "switch"}
# NOTE: no "update" — optax GradientTransformation.update is a pure
# function and is everywhere in this codebase's hot bodies.
_MUTATORS = {"append", "extend", "insert", "add", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "write"}
_STATIC_CALLS = {"len", "isinstance", "issubclass", "list", "tuple",
                 "dict", "set", "type", "getattr", "hasattr", "sorted",
                 "range", "enumerate", "zip", "min", "max", "str",
                 "repr", "format"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "keys", "items",
                 "values", "axis_names"}
_PUT_NAMES = {"device_put", "window_put", "put"}

_SUPPRESS_RE = re.compile(r"#\s*fedlint:\s*disable=(.+)$")
_SUPPRESS_ITEM_RE = re.compile(r"([A-Z]\d+)\s*(?:\(([^)]*)\))?")


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str
    source_line: str = ""
    suppressed: bool = False
    suppress_reason: Optional[str] = None
    #: R1 straight-line autofix payload: (loop_var, key_repr, sub_repr)
    fix: Optional[Tuple[str, str, str]] = None

    def format(self) -> str:
        tag = " (suppressed: %s)" % (self.suppress_reason or "no reason") \
            if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}{tag}")


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_tail(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    return d.rsplit(".", 1)[-1] if d else None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _contains_static_escape(node: ast.AST) -> bool:
    """True when the expression reads only trace-static facts (shapes,
    dtypes, lengths) or routes through static-returning builtins."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return True
        if isinstance(n, ast.Call):
            tail = _call_tail(n)
            if tail in _STATIC_CALLS:
                return True
    return False


def _is_staticish(node: ast.AST) -> bool:
    """Conservative 'this cannot be a live device value' check."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.UnaryOp,)):
        return _is_staticish(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_staticish(node.left) and _is_staticish(node.right)
    return _contains_static_escape(node)


def _dynamic_test_names(test: ast.AST) -> Set[str]:
    """Names that appear inside a dynamic comparison or arithmetic in a
    branch test (Compare with value ops, or BinOp) — the concretization
    shape, as opposed to static truthiness/identity checks."""
    out: Set[str] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Compare) and not all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            out |= _names_in(n)
        elif isinstance(n, ast.BinOp):
            out |= _names_in(n)
    return out


@dataclass
class _Directive:
    """One ``# fedlint: disable=RULE(reason)`` occurrence — kept as a
    first-class object so dead suppressions are themselves lintable
    (U1)."""
    line: int
    rule: str
    reason: Optional[str]
    covers: Tuple[int, ...]


def _suppression_directives(source: str) -> List[_Directive]:
    out: List[_Directive] = []
    for i, raw in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        covers = (i, i + 1) if raw.lstrip().startswith("#") else (i,)
        for rule, reason in _SUPPRESS_ITEM_RE.findall(m.group(1)):
            out.append(_Directive(line=i, rule=rule, reason=reason or None,
                                  covers=covers))
    return out


def _parse_suppressions(source: str) -> Dict[int, Dict[str, Optional[str]]]:
    """line -> {rule: reason}. A directive suppresses findings on its own
    line; a comment-only directive line also covers the next line."""
    out: Dict[int, Dict[str, Optional[str]]] = {}
    for d in _suppression_directives(source):
        for line in d.covers:
            out.setdefault(line, {})[d.rule] = d.reason
    return out


def unused_suppressions(sources: Dict[str, str],
                        violations: Sequence[Violation],
                        rules: Optional[Set[str]] = None) -> List[Violation]:
    """U1: directives whose rule fired on none of their covered lines.
    ``rules`` limits the check to rules that actually ran — a partial
    analysis (``--changed``) must not call a project-rule suppression
    dead just because its pass had no file set to run over."""
    fired: Set[Tuple[str, str, int]] = {
        (v.path, v.rule, v.line) for v in violations if v.suppressed}
    out: List[Violation] = []
    for path in sorted(sources):
        lines = sources[path].splitlines()
        sup = _parse_suppressions(sources[path])
        for d in _suppression_directives(sources[path]):
            if d.rule not in RULES or (rules is not None
                                       and d.rule not in rules):
                continue
            if d.rule == "U1":
                continue  # disable=U1 is a deliberate opt-out, not debt
            if any((path, d.rule, ln) in fired for ln in d.covers):
                continue
            v = Violation(
                rule="U1", path=path, line=d.line, col=0,
                message=f"suppression 'fedlint: disable={d.rule}' is "
                        f"dead: {d.rule} no longer fires on the covered "
                        "line — drop the directive (or re-check the "
                        "fix it was excusing)",
                severity=RULES["U1"][1],
                source_line=(lines[d.line - 1].strip()
                             if 0 < d.line <= len(lines) else ""))
            if "U1" in sup.get(d.line, {}):
                v.suppressed = True
                v.suppress_reason = sup[d.line]["U1"]
            out.append(v)
    return out


@dataclass
class _FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    params: Set[str] = field(default_factory=set)
    #: params annotated with Python scalar types (int/float/bool/str):
    #: trace-static by declaration, never tainted as tracers
    static_params: Set[str] = field(default_factory=set)
    calls: Set[str] = field(default_factory=set)  # simple callee names
    hot: bool = False
    scan_body: bool = False


class _Analyzer:
    def __init__(self, tree: ast.Module, path: str, source: str):
        self.tree = tree
        self.path = path
        self.lines = source.splitlines()
        self.suppressions = _parse_suppressions(source)
        self.violations: List[Violation] = []
        self.funcs: List[_FuncInfo] = []
        self._func_of_node: Dict[ast.AST, _FuncInfo] = {}
        self._by_name: Dict[str, List[_FuncInfo]] = {}

    # -- plumbing ------------------------------------------------------
    def report(self, rule: str, node: ast.AST, message: str,
               severity: Optional[str] = None,
               fix: Optional[Tuple[str, str, str]] = None) -> None:
        line, col = node.lineno, getattr(node, "col_offset", 0)
        sup = self.suppressions.get(line, {})
        v = Violation(
            rule=rule, path=self.path, line=line, col=col, message=message,
            severity=severity or RULES[rule][1],
            source_line=(self.lines[line - 1].strip()
                         if 0 < line <= len(self.lines) else ""),
            fix=fix,
        )
        if rule in sup:
            v.suppressed = True
            v.suppress_reason = sup[rule]
        self.violations.append(v)

    # -- pass 1: function table + traced roots -------------------------
    def _collect_functions(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                name = getattr(node, "name", "<lambda>")
                info = _FuncInfo(node=node, name=name)
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs
                            + ([a.vararg] if a.vararg else [])
                            + ([a.kwarg] if a.kwarg else [])):
                    info.params.add(arg.arg)
                    ann = getattr(arg, "annotation", None)
                    if isinstance(ann, ast.Name) \
                            and ann.id in {"int", "float", "bool", "str"}:
                        info.static_params.add(arg.arg)
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for sub in body:
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Call):
                            d = _dotted(n.func)
                            if d and "." not in d:
                                info.calls.add(d)
                            # partial(f, ...) binds f for a later call —
                            # a call edge for reachability purposes (the
                            # carry-protocol callbacks are exactly this
                            # shape: cross=partial(psum, ...)).
                            if _call_tail(n) == "partial" and n.args:
                                t = _dotted(n.args[0])
                                if t and "." not in t:
                                    info.calls.add(t)
                self.funcs.append(info)
                self._func_of_node[node] = info
                self._by_name.setdefault(name, []).append(info)

    def _mark_roots(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    names = {_call_tail(dec)} if isinstance(dec, ast.Call) \
                        else {_dotted(dec) and _dotted(dec).rsplit(".", 1)[-1]}
                    if isinstance(dec, ast.Call):
                        # @partial(jax.jit, ...) / @partial(shard_map, ...)
                        for a in dec.args:
                            d = _dotted(a)
                            if d:
                                names.add(d.rsplit(".", 1)[-1])
                    if names & _TRACING:
                        self._func_of_node[node].hot = True
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node)
            if tail not in _TRACING and tail not in _LOOPING:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                # Resolve partial(f, ...) -> f: a function handed to a
                # tracing/looping entry point through functools.partial
                # is traced exactly like the bare function would be
                # (lax.scan(partial(body, cfg), ...)).
                if isinstance(arg, ast.Call) and _call_tail(arg) == \
                        "partial" and arg.args:
                    arg = arg.args[0]
                target: Optional[_FuncInfo] = None
                if isinstance(arg, ast.Lambda):
                    target = self._func_of_node.get(arg)
                else:
                    d = _dotted(arg)
                    if d and "." not in d and d in self._by_name:
                        # name-based: every local def with that name
                        for cand in self._by_name[d]:
                            cand.hot = True
                            if tail in _LOOPING:
                                cand.scan_body = True
                        continue
                if target is not None:
                    target.hot = True
                    if tail in _LOOPING:
                        target.scan_body = True

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for f in self.funcs:
                if not f.hot:
                    continue
                for callee in f.calls:
                    for cand in self._by_name.get(callee, []):
                        if not cand.hot:
                            cand.hot = True
                            changed = True

    # -- R1 ------------------------------------------------------------
    def _check_r1(self) -> None:
        for f in self.funcs:
            body = f.node.body if isinstance(f.node.body, list) \
                else [f.node.body]
            for stmt in body:
                self._r1_walk(stmt, f, loops=[])

    def _r1_walk(self, node: ast.AST, f: _FuncInfo,
                 loops: List[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not f.node:
            return  # nested functions get their own _FuncInfo pass
        if isinstance(node, (ast.For, ast.While)):
            for child in ast.iter_child_nodes(node):
                self._r1_walk(child, f, loops + [node])
            return
        if isinstance(node, ast.Assign):
            self._r1_check_assign(node, f, loops)
        for child in ast.iter_child_nodes(node):
            self._r1_walk(child, f, loops)

    def _r1_check_assign(self, node: ast.Assign, f: _FuncInfo,
                         loops: List[ast.AST]) -> None:
        call = node.value
        if not isinstance(call, ast.Call):
            return
        d = _dotted(call.func)
        if not d or not d.endswith("split") or "random" not in d:
            return
        if not call.args:
            return
        key = _dotted(call.args[0])
        if key is None:
            return
        targets: List[str] = []
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                targets.extend(_dotted(e) or "" for e in t.elts)
            else:
                targets.append(_dotted(t) or "")
        if key not in targets:
            return
        in_scan = f.scan_body
        in_loop = bool(loops)
        if not in_scan and not in_loop:
            return
        fix = None
        if (in_loop and not in_scan and isinstance(loops[-1], ast.For)
                and isinstance(loops[-1].target, ast.Name)
                and len(targets) == 2 and "." not in key
                and len(call.args) == 1):
            others = [t for t in targets if t != key]
            # Straight-line only: the carried key must not be read
            # anywhere else in the loop body, or dropping its rebinding
            # would change more than the stream derivation.
            other_uses = [n for n in ast.walk(loops[-1])
                          if _dotted(n) == key
                          and getattr(n, "lineno", node.lineno)
                          != node.lineno]
            if len(others) == 1 and "." not in others[0] and not other_uses:
                fix = (loops[-1].target.id, key, others[0])
        if in_scan:
            self.report(
                "R1", node,
                f"carried random.split chain on {key!r} inside a scan "
                "body: the stream depends on the traced trip count and is "
                "not prefix-stable in the step count; fold_in on the step "
                "index instead (see trainer/local.py)",
                severity="error")
        else:
            self.report(
                "R1", node,
                f"carried random.split chain on {key!r} in a "
                f"{'hot ' if f.hot else ''}loop body: round/iteration "
                "streams depend on every prior iteration; prefer fold_in "
                "on the loop index (or suppress where the chain is a "
                "pinned, deliberate round-order stream)",
                severity="error" if f.hot else "warning",
                fix=fix)

    # -- R2 ------------------------------------------------------------
    def _scopes(self):
        yield None, self.tree.body
        for f in self.funcs:
            body = f.node.body if isinstance(f.node.body, list) \
                else [f.node.body]
            yield f, body

    @staticmethod
    def _walk_scope(body: Sequence[ast.AST], yield_nested: bool = False):
        """Walk a scope's statements WITHOUT descending into nested
        function/lambda bodies — those are their own scopes (every
        FunctionDef gets its own _scopes()/_FuncInfo entry), and
        descending here double-reports their findings at the enclosing
        scope. ``yield_nested`` yields the nested def node itself
        (callers that need its NAME, e.g. for local-binding sets)
        while still not descending into it."""
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                if yield_nested:
                    yield n
                continue  # a nested scope: do not descend
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _check_r2(self) -> None:
        for f, body in self._scopes():
            puts: List[Tuple[ast.Call, Set[str]]] = []
            mutations: List[Tuple[int, str, ast.AST]] = []
            for n in self._walk_scope(body):
                if isinstance(n, ast.Call):
                    tail = _call_tail(n)
                    if tail in _PUT_NAMES and n.args:
                        names = set()
                        for a in n.args:
                            names |= _names_in(a)
                        puts.append((n, names))
                    # out=<name> keyword writes (np.take(..., out=x))
                    for kw in n.keywords:
                        if kw.arg == "out":
                            d = _dotted(kw.value)
                            if d:
                                mutations.append((n.lineno, d, n))
                    if (isinstance(n.func, ast.Attribute)
                            and n.func.attr in {"fill", "sort",
                                                "resize", "itemset"}):
                        d = _dotted(n.func.value)
                        if d:
                            mutations.append((n.lineno, d, n))
                if isinstance(n, (ast.Assign, ast.AugAssign)):
                    targets = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            d = _dotted(t.value)
                            if d:
                                mutations.append((n.lineno, d, n))
            for call, names in puts:
                later = [(ln, nm) for ln, nm, _ in mutations
                         if ln > call.lineno and nm in names]
                if later:
                    ln, nm = later[0]
                    self.report(
                        "R2", call,
                        f"{_call_tail(call)} of {nm!r} which is mutated "
                        f"later in the same scope (line {ln}): device_put "
                        "may alias host memory zero-copy — copy before "
                        "the put (np.array) or restructure",
                    )

    # -- R3 / R4 -------------------------------------------------------
    def _check_hot_bodies(self) -> None:
        for f in self.funcs:
            if not f.hot:
                continue
            tainted = set(f.params) - {"self", "cls"} - f.static_params
            body = f.node.body if isinstance(f.node.body, list) \
                else [f.node.body]
            local_binds = set(f.params)
            # Scope-pruned walks (nested defs are their own _FuncInfo
            # pass — walking into them here would double-report their
            # findings AND judge them against the wrong tainted/
            # local_binds sets).
            for n in self._walk_scope(body, yield_nested=True):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local_binds.add(n.name)
                    continue
                if isinstance(n, ast.Lambda):
                    continue
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        for nm in ast.walk(t):
                            if isinstance(nm, ast.Name):
                                local_binds.add(nm.id)
                    if (_names_in(n.value) & tainted
                            and not _is_staticish(n.value)):
                        for t in n.targets:
                            for nm in ast.walk(t):
                                if isinstance(nm, ast.Name):
                                    tainted.add(nm.id)
                if isinstance(n, (ast.For,)):
                    for nm in ast.walk(n.target):
                        if isinstance(nm, ast.Name):
                            local_binds.add(nm.id)
            for n in self._walk_scope(body):
                self._r3_node(n, f, tainted)
                self._r4_node(n, f, tainted, local_binds)

    def _r3_node(self, n: ast.AST, f: _FuncInfo, tainted: Set[str]) -> None:
        if not isinstance(n, ast.Call):
            return
        d = _dotted(n.func)
        tail = _call_tail(n)
        if tail in {"float", "int", "bool"} and d == tail and n.args:
            if not _is_staticish(n.args[0]) and _names_in(n.args[0]) & tainted:
                self.report(
                    "R3", n,
                    f"{tail}() of a traced value inside hot function "
                    f"{f.name!r}: forces a device sync (or a "
                    "ConcretizationError under trace); keep the value on "
                    "device or move the sync outside the hot path")
            return
        if d and tail in {"asarray", "array"} and (
                d.startswith("np.") or d.startswith("numpy.")
                or d.startswith("onp.")):
            if n.args and _names_in(n.args[0]) & tainted \
                    and not _is_staticish(n.args[0]):
                self.report(
                    "R3", n,
                    f"{d} of a traced value inside hot function "
                    f"{f.name!r}: device-to-host copy in a hot path")
            return
        if d and d.endswith("device_get"):
            self.report(
                "R3", n,
                f"jax.device_get inside hot function {f.name!r}: "
                "device-to-host copy in a hot path")
            return
        if isinstance(n.func, ast.Attribute) \
                and n.func.attr in {"item", "tolist"}:
            base = _names_in(n.func.value)
            if base & tainted or not base:
                self.report(
                    "R3", n,
                    f".{n.func.attr}() inside hot function {f.name!r}: "
                    "blocks on the device value (host sync per call)")

    def _r4_node(self, n: ast.AST, f: _FuncInfo, tainted: Set[str],
                 local_binds: Set[str]) -> None:
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d == "print":
                self.report(
                    "R4", n,
                    f"print() inside hot function {f.name!r}: runs at "
                    "trace time only (or forces a sync via callbacks); "
                    "use jax.debug.print for traced values")
            elif (isinstance(n.func, ast.Attribute)
                  and n.func.attr in _MUTATORS):
                base = _dotted(n.func.value)
                if base and "." not in base and base not in local_binds:
                    self.report(
                        "R4", n,
                        f"mutation of closed-over Python state "
                        f"{base!r}.{n.func.attr}() inside hot function "
                        f"{f.name!r}: runs once at trace time, not per "
                        "step — a silent correctness/recompile hazard")
            return
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            self.report(
                "R4", n,
                f"{'global' if isinstance(n, ast.Global) else 'nonlocal'} "
                f"state mutation inside hot function {f.name!r}: runs at "
                "trace time, not per executed step")
            return
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute) \
                        and _dotted(t.value) == "self":
                    self.report(
                        "R4", n,
                        f"assignment to self.{t.attr} inside hot function "
                        f"{f.name!r}: Python-state mutation under trace "
                        "happens once per (re)compilation, not per call")
            return
        if isinstance(n, (ast.If, ast.While)) or isinstance(n, ast.IfExp):
            test = n.test
            if _is_staticish(test):
                return
            # Bare-name truthiness (`if remat:`, `if not nan_guard:`) is
            # overwhelmingly static builder config in this codebase; the
            # tracer hazard we have actually hit is a *dynamic
            # comparison/arithmetic* on a traced value (`if nb > 0:`).
            hits = _dynamic_test_names(test) & tainted
            if hits:
                self.report(
                    "R4", n,
                    "Python branch on a possibly-traced value "
                    f"({', '.join(sorted(hits))}) inside hot function "
                    f"{f.name!r}: concretizes the tracer (error under "
                    "jit) or forks compilation per value; use "
                    "lax.cond/jnp.where or hoist the branch")

    # -- R4d: unhashable static args; R5: donation ---------------------
    def _check_jit_bindings(self) -> None:
        for f, body in self._scopes():
            static_of: Dict[str, Set[int]] = {}
            donate_of: Dict[str, Set[int]] = {}
            stmts: List[ast.AST] = list(self._walk_scope(body))
            for n in stmts:
                if not isinstance(n, ast.Assign) \
                        or not isinstance(n.value, ast.Call):
                    continue
                call = n.value
                if _call_tail(call) not in {"jit", "pjit"}:
                    continue
                statics, donated = set(), set()
                for kw in call.keywords:
                    if kw.arg in {"static_argnums", "static_argnames"}:
                        statics |= self._int_elems(kw.value)
                    if kw.arg == "donate_argnums":
                        donated |= self._int_elems(kw.value)
                for t in n.targets:
                    d = _dotted(t)
                    if d is None:
                        continue
                    if statics:
                        static_of[d] = statics
                    if donated:
                        donate_of[d] = donated
            for n in stmts:
                if not isinstance(n, ast.Call):
                    continue
                d = _dotted(n.func)
                if d in static_of:
                    for pos in static_of[d]:
                        if pos < len(n.args) and isinstance(
                                n.args[pos], (ast.List, ast.Dict, ast.Set)):
                            self.report(
                                "R4", n.args[pos],
                                f"unhashable literal passed in static arg "
                                f"position {pos} of jitted {d!r}: every "
                                "call re-traces (lists/dicts never hash-"
                                "hit the jit cache); pass a tuple or "
                                "hashable config object")
                if d in donate_of:
                    self._r5_check_call(n, d, donate_of[d], body)

    @staticmethod
    def _int_elems(node: ast.AST) -> Set[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return {node.value}
        out: Set[int] = set()
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
        return out

    def _r5_check_call(self, call: ast.Call, fname: str,
                       donated: Set[int], scope_body: Sequence[ast.AST]):
        rebound_same_stmt: Set[str] = set()
        assign_of_call = None
        for n in self._walk_scope(scope_body):
            if isinstance(n, ast.Assign) and n.value is call:
                assign_of_call = n
        if assign_of_call is not None:
            for t in assign_of_call.targets:
                for e in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    d = _dotted(e)
                    if d:
                        rebound_same_stmt.add(d)
        for pos in donated:
            if pos >= len(call.args):
                continue
            arg = _dotted(call.args[pos])
            if arg is None or arg in rebound_same_stmt:
                continue
            # any Load of `arg` after the call line, with no rebinding
            # assignment in between, is a read of a donated buffer
            loads: List[int] = []
            stores: List[int] = []
            for n in self._walk_scope(scope_body):
                if _dotted(n) == arg and hasattr(n, "lineno") \
                        and n.lineno > call.lineno:
                    ctx = getattr(n, "ctx", None)
                    (stores if isinstance(ctx, ast.Store)
                     else loads).append(n.lineno)
            for ln in sorted(loads):
                if not any(s <= ln for s in stores):
                    self.report(
                        "R5", call,
                        f"{arg!r} is donated to {fname!r} "
                        f"(donate_argnums={sorted(donated)}) but read "
                        f"again at line {ln}: donated buffers are "
                        "deleted — copy first or drop the donation")
                    break

    # -- driver --------------------------------------------------------
    def run(self) -> List[Violation]:
        self._collect_functions()
        self._mark_roots()
        self._propagate()
        self._check_r1()
        self._check_r2()
        self._check_hot_bodies()
        self._check_jit_bindings()
        # P1/P2 live in their own module but report through self so
        # suppressions and the baseline behave identically (imported
        # lazily: protocol.py imports helpers from this module).
        from fedml_tpu.lint import protocol

        protocol.check_module(self)
        self.violations.sort(key=lambda v: (v.line, v.col, v.rule))
        return self.violations


def analyze_source(source: str, path: str = "<string>") -> List[Violation]:
    tree = ast.parse(source)
    return _Analyzer(tree, path, source).run()


def analyze_file(path: str) -> List[Violation]:
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    return analyze_source(src, path)


def analyze_paths(paths: Sequence[str],
                  partial: bool = False) -> List[Violation]:
    """Walk files/dirs (``.py`` only, ``__pycache__`` skipped). A path
    that does not exist (or is a non-.py file) raises — a typo'd path in
    a CI gate must fail loudly, not report a clean run over nothing.

    Runs the per-module rules on each file, then the project-wide
    passes (P3/P4) over the whole set, then the dead-suppression check
    (U1). ``partial=True`` marks the file set as a subset of the real
    project (``--changed``): project passes still run over what is
    there, but U1 only judges per-module rules — a project rule that
    happened not to fire because its counterpart file is outside the
    set does not make a suppression "dead"."""
    import os

    sources: Dict[str, str] = {}
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        fp = os.path.join(root, f)
                        with open(fp, "r", encoding="utf-8") as fh:
                            sources[fp] = fh.read()
        elif os.path.isfile(p) and p.endswith(".py"):
            with open(p, "r", encoding="utf-8") as fh:
                sources[p] = fh.read()
        else:
            raise FileNotFoundError(
                f"fedlint: {p!r} is not a directory or .py file")

    out: List[Violation] = []
    for fp in sorted(sources):
        out.extend(analyze_source(sources[fp], fp))

    from fedml_tpu.lint import project

    out.extend(project.analyze_project(sources, partial=partial))
    u1_rules = set(RULES) - {"U1"}
    if partial:
        u1_rules -= set(PROJECT_RULES)
    out.extend(unused_suppressions(sources, out, rules=u1_rules))
    return out
