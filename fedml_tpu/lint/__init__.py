"""fedlint — AST static analysis for the JAX pitfalls this codebase has hit.

PR 1 shipped two bug classes found only by hand-auditing: ``device_put``
zero-copy aliasing of reused host staging buffers, and rng streams that
were not prefix-stable in the step count (carried ``random.split``
chains inside scan bodies). Both silently break the bit-equality the
windowed/streaming execution tiers rest on. ``fedlint`` walks the
package AST and flags those classes before review has to:

- **R1** carried ``random.split`` chains inside scan-or-loop bodies
  (fold_in-on-index is required for prefix stability);
- **R2** ``device_put``/``window_put`` of a buffer mutated later in the
  same scope (staging-buffer aliasing);
- **R3** host syncs inside jit/scan/shard_map-reachable functions
  (``.item()``, ``float()``/``int()``/``np.asarray`` on device values);
- **R4** recompile hazards (Python branches on tracer values, unhashable
  static args, ``print``/Python-state mutation inside traced code);
- **R5** donation misuse (reading an argument after it was donated).

Every finding carries a ``# fedlint: disable=RULE(reason)`` suppression
syntax, a severity, and a file:line report; ``scripts/fedlint.py`` is
the CLI (text/json output, baseline-gated exit status, ``--fix`` for
the mechanical R1 rewrite). The runtime complement — transfer-guard +
recompile counting for the steady-state round loop — lives in
``fedml_tpu.obs.sanitizer``. See docs/LINT.md.
"""

from fedml_tpu.lint.analyzer import (
    RULES,
    Violation,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from fedml_tpu.lint.baseline import (
    fingerprint,
    load_baseline,
    new_violations,
    write_baseline,
)

__all__ = [
    "RULES",
    "Violation",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "fingerprint",
    "load_baseline",
    "new_violations",
    "write_baseline",
]
