"""fedlint — AST static analysis for the JAX pitfalls this codebase has hit.

PR 1 shipped two bug classes found only by hand-auditing: ``device_put``
zero-copy aliasing of reused host staging buffers, and rng streams that
were not prefix-stable in the step count (carried ``random.split``
chains inside scan bodies). Both silently break the bit-equality the
windowed/streaming execution tiers rest on. ``fedlint`` walks the
package AST and flags those classes before review has to:

- **R1** carried ``random.split`` chains inside scan-or-loop bodies
  (fold_in-on-index is required for prefix stability);
- **R2** ``device_put``/``window_put`` of a buffer mutated later in the
  same scope (staging-buffer aliasing);
- **R3** host syncs inside jit/scan/shard_map-reachable functions
  (``.item()``, ``float()``/``int()``/``np.asarray`` on device values);
- **R4** recompile hazards (Python branches on tracer values, unhashable
  static args, ``print``/Python-state mutation inside traced code);
- **R5** donation misuse (reading an argument after it was donated).

The protocol/concurrency family (``lint/protocol.py`` per-module,
``lint/project.py`` project-wide) covers the control-plane bug classes
the federation managers have actually shipped:

- **P1** thread-shared state: ``self.<attr>`` reachable from two
  manager thread classes (dispatch / watchdog / beat / ingest pool)
  accessed outside ``with self._lock``;
- **P2** drop-without-reply: an upload-handler path that rejects a
  message without a reply, refusal helper, eviction, flush-barrier
  deferral, or recorded progress (the PR 5/PR 10 deadlock class);
- **P3** flag-refusal coverage: a driver that neither consumes nor
  refuses a gated CLI flag (silently-inert flags), plus orphan-flag
  and dead-FedConfig-field warnings;
- **P4** copy-divergence: near-clones across the sync/async/fedbuff/
  shardplane twins must be factored or carry
  a ``twin-of(<path>)`` fedlint annotation;
- **U1** dead suppressions: a disable directive (or twin-of
  annotation) whose rule no longer fires is itself a warning.

Every finding carries a ``# fedlint: disable=RULE(reason)`` suppression
syntax, a severity, and a file:line report; ``scripts/fedlint.py`` is
the CLI (text/json output, baseline-gated exit status, ``--fix`` for
the mechanical R1 rewrite, ``--changed[=REF]`` for the pre-commit
fast path, ``--thread-report`` for the inferred per-class thread
model). The runtime complement — transfer-guard + recompile counting
for the steady-state round loop — lives in ``fedml_tpu.obs.sanitizer``.
See docs/LINT.md.
"""

from fedml_tpu.lint.analyzer import (
    PROJECT_RULES,
    RULES,
    Violation,
    analyze_file,
    analyze_paths,
    analyze_source,
    unused_suppressions,
)
from fedml_tpu.lint.project import analyze_project
from fedml_tpu.lint.protocol import thread_model_report
from fedml_tpu.lint.baseline import (
    fingerprint,
    load_baseline,
    new_violations,
    write_baseline,
)

__all__ = [
    "PROJECT_RULES",
    "RULES",
    "Violation",
    "analyze_file",
    "analyze_paths",
    "analyze_project",
    "analyze_source",
    "fingerprint",
    "load_baseline",
    "new_violations",
    "thread_model_report",
    "unused_suppressions",
    "write_baseline",
]
