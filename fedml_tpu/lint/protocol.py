"""fedlint protocol/concurrency passes P1–P2 over one module at a time.

The control-plane bug classes this file mechanizes are the ones this
repo has actually shipped (see docs/LINT.md for the post-mortems):

P1 ``thread-shared-state``
    Every hard race so far had the same shape: a manager class whose
    methods run on different threads (the dispatch loop, a watchdog
    ``threading.Thread``, a ``HeartbeatSender`` beat thread, or the
    ``IngestPool`` workers) touching the same ``self.<attr>`` where at
    least one side skipped ``with self._lock``. PR 5's unlocked
    ``sorted(self._done_set)`` in the watchdog is the canonical case.
    The pass classifies each method by the thread classes that can run
    it, closes the classification over ``self.m()`` calls, tracks
    ``with self._lock`` regions per method, and flags cross-thread
    attributes accessed outside them.

P2 ``drop-without-reply``
    A server upload handler that rejects a message and simply returns
    leaves the sender waiting forever — the PR 5 / PR 10 deadlock.
    Every handler path must end in a *terminal action* (send a reply,
    route through a shared ``_refuse*``/``_evict*``/``_notify*``/
    ``_send*`` helper, re-raise to the flush barrier via an
    ``IngestPool`` submit, call ``finish()``, or raise) or *recorded
    progress* (the upload folded into protocol state), or carry an
    explicit ``disable=P2(reason)`` fedlint suppression.

Resolution is per-module and name-based, like the R-rules: methods a
class inherits from another module are invisible, so handler discovery
falls back to the repo-wide ``_?handle_*`` naming convention and
terminal discovery falls back to the shared helper-name prefixes.
False negatives are accepted; false positives should be rare enough
that suppressions stay reviewed, deliberate acts.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from fedml_tpu.lint.analyzer import _call_tail, _dotted

# Thread-entry discovery --------------------------------------------------

#: ``with self.<attr>:`` context managers treated as lock regions.
_LOCKISH_RE = re.compile(r"(^|_)(lock|locks|cv|cond|condition|mutex)s?$",
                         re.IGNORECASE)
#: attribute names whose ``.submit(fn)`` / ``.run(fn)`` hand ``fn`` to
#: worker threads (comm/ingest.py IngestPool and friends).
_POOLISH_RE = re.compile(r"(^|_)pool$", re.IGNORECASE)
#: message-type constant names whose registered handler is an *upload*
#: handler for P2 (model uploads, delta frames, shard partials).
_UPLOAD_MSG_RE = re.compile(r"SEND_MODEL|UPLOAD|DELTA|PARTIAL")
#: method-name fallbacks for helpers inherited from other modules: these
#: prefixes are the repo's shared refusal/reply vocabulary.
_TERMINAL_NAME_RE = re.compile(
    r"^(_?send_|_send\b|_refuse|_evict|_notify|_post_tick|finish$)")
_HANDLERISH_RE = re.compile(r"^_?handle_")

#: method calls that mutate a collection in place (P1 write detection;
#: broader than analyzer._MUTATORS — ``update`` here is dict.update on
#: self state, not optax).
_P1_MUTATORS = {"append", "extend", "insert", "add", "setdefault",
                "pop", "popitem", "remove", "discard", "clear",
                "update", "fill", "sort"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``"x"`` (one level only), else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclass
class _Access:
    attr: str
    write: bool
    locked: bool
    latch: bool  # plain ``self.x = True/False/None`` store
    node: ast.AST


@dataclass
class _Method:
    name: str
    node: ast.AST
    is_init: bool = False
    tags: Set[str] = field(default_factory=set)
    self_concurrent: bool = False
    calls: Set[str] = field(default_factory=set)  # self.m() callee names
    accesses: List[_Access] = field(default_factory=list)


@dataclass
class _ClassModel:
    name: str
    node: ast.ClassDef
    methods: Dict[str, _Method] = field(default_factory=dict)
    #: (message-constant tail, handler method name) registrations
    registrations: List[Tuple[str, str]] = field(default_factory=list)
    locked_attrs: Set[str] = field(default_factory=set)


def _method_scope(node: ast.AST):
    """Walk a method body without descending into nested defs/lambdas;
    yields the nested def node itself once (callers decide what to do
    with it)."""
    stack = list(node.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# -- model construction ---------------------------------------------------

def build_class_model(cls: ast.ClassDef) -> _ClassModel:
    model = _ClassModel(name=cls.name, node=cls)
    defs = [n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for d in defs:
        m = _Method(name=d.name, node=d, is_init=(d.name == "__init__"))
        model.methods[d.name] = m
        # nested defs (the IngestPool task closures) are pseudo-methods:
        # they run wherever they are handed to, not where they are
        # written.
        for n in _method_scope(d):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.methods[f"{d.name}.{n.name}"] = _Method(
                    name=f"{d.name}.{n.name}", node=n)
    for d in defs:
        _scan_entries(model, model.methods[d.name])
    for m in list(model.methods.values()):
        _collect_accesses(model, m)
    _classify(model)
    return model


def _entry_target(model: _ClassModel, parent: _Method,
                  node: ast.AST) -> Optional[str]:
    """Resolve a callable handed to a thread entry point to a method
    name in this class: ``self.m`` or a nested def bound in ``parent``."""
    a = _self_attr(node)
    if a is not None and a in model.methods:
        return a
    if isinstance(node, ast.Name):
        nested = f"{parent.name}.{node.id}"
        if nested in model.methods:
            return nested
    return None


def _scan_entries(model: _ClassModel, m: _Method) -> None:
    """Tag methods by the thread classes that can invoke them."""
    # The manager run loop *is* the dispatch thread (managers.py:
    # ``run()`` drives ``handle_receive_message``), and registered
    # handlers run on it. ``_?handle_*`` covers handlers whose
    # registration lives in a base class in another module.
    if m.name == "run" or _HANDLERISH_RE.match(m.name):
        m.tags.add("dispatch")

    def tag(target: Optional[str], label: str, concurrent: bool) -> None:
        if target is None or target not in model.methods:
            return
        tgt = model.methods[target]
        tgt.tags.add(label)
        tgt.self_concurrent |= concurrent

    loop_depth = 0

    def walk(node: ast.AST, loops: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not m.node:
            return
        bump = loops + (1 if isinstance(
            node, (ast.For, ast.While, ast.ListComp, ast.SetComp,
                   ast.DictComp, ast.GeneratorExp)) else 0)
        if isinstance(node, ast.Call):
            tail = _call_tail(node)
            if tail == "register_message_receive_handler" \
                    and len(node.args) >= 2:
                tgt = _entry_target(model, m, node.args[1])
                if tgt is not None:
                    model.methods[tgt].tags.add("dispatch")
                const = _dotted(node.args[0])
                if const and tgt is not None:
                    model.registrations.append(
                        (const.rsplit(".", 1)[-1], tgt))
            elif tail == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = _entry_target(model, m, kw.value)
                        tag(tgt, f"thread:{tgt}", bump > 0)
            elif tail == "Timer" and len(node.args) >= 2:
                tgt = _entry_target(model, m, node.args[1])
                tag(tgt, f"thread:{tgt}", bump > 0)
            elif tail == "HeartbeatSender" and node.args:
                tgt = _entry_target(model, m, node.args[0])
                tag(tgt, f"beat:{tgt}", bump > 0)
            elif tail in {"submit", "run"} \
                    and isinstance(node.func, ast.Attribute):
                base = _self_attr(node.func.value)
                if base is not None and _POOLISH_RE.search(base) \
                        and node.args:
                    tgt = _entry_target(model, m, node.args[0])
                    # IngestPool runs N workers: pool entries are
                    # concurrent with themselves by construction.
                    tag(tgt, "pool", True)
        for child in ast.iter_child_nodes(node):
            walk(child, bump)

    for stmt in m.node.body:
        walk(stmt, loop_depth)


def _lock_item(item: ast.withitem) -> bool:
    expr = item.context_expr
    # ``with self._lock:`` and ``with self._cv:`` open a guarded region.
    a = _self_attr(expr)
    return a is not None and bool(_LOCKISH_RE.search(a))


def _collect_accesses(model: _ClassModel, m: _Method) -> None:
    def record(attr: str, write: bool, locked: bool, latch: bool,
               node: ast.AST) -> None:
        if _LOCKISH_RE.search(attr):
            return
        if locked:
            model.locked_attrs.add(attr)
        m.accesses.append(_Access(attr, write, locked, latch, node))

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not m.node:
            return  # nested defs are their own pseudo-methods
        if isinstance(node, ast.With):
            inner = locked or any(_lock_item(i) for i in node.items)
            for item in node.items:
                visit(item.context_expr, locked)
                if item.optional_vars is not None:
                    visit(item.optional_vars, locked)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            latch = isinstance(node.value, ast.Constant) \
                and node.value.value in (True, False, None) \
                and len(node.targets) == 1
            for t in node.targets:
                for sub in ast.walk(t):
                    a = _self_attr(sub)
                    if a is not None \
                            and isinstance(sub.ctx, (ast.Store, ast.Del)):
                        record(a, True, locked, latch, node)
            visit(node.value, locked)
            # subscript stores on self state: self.d[k] = v
            for t in node.targets:
                if isinstance(t, (ast.Subscript,)):
                    a = _self_attr(t.value)
                    if a is not None:
                        record(a, True, locked, False, node)
                    visit(t.slice, locked)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            t = node.target
            a = _self_attr(t)
            if a is not None:
                record(a, True, locked, False, node)
            elif isinstance(t, ast.Subscript):
                a = _self_attr(t.value)
                if a is not None:
                    record(a, True, locked, False, node)
            if node.value is not None:
                visit(node.value, locked)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                a = _self_attr(t)
                if a is None and isinstance(t, ast.Subscript):
                    a = _self_attr(t.value)
                if a is not None:
                    record(a, True, locked, False, node)
            return
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _P1_MUTATORS:
                a = _self_attr(node.func.value)
                if a is not None:
                    record(a, True, locked, False, node)
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            a = _self_attr(node)
            if a is not None:
                record(a, False, locked, False, node)
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    for stmt in m.node.body:
        visit(stmt, False)


def _classify(model: _ClassModel) -> None:
    """Close thread tags over ``self.m()`` calls (a helper called from
    the watchdog runs on the watchdog thread)."""
    for m in model.methods.values():
        for n in _method_scope(m.node):
            if isinstance(n, ast.Call):
                a = _self_attr(n.func)
                if a is not None and a in model.methods:
                    m.calls.add(a)
    changed = True
    while changed:
        changed = False
        for m in model.methods.values():
            if not m.tags:
                continue
            for callee in m.calls:
                tgt = model.methods[callee]
                if tgt.is_init:
                    continue
                if not (m.tags <= tgt.tags):
                    tgt.tags |= m.tags
                    changed = True
                if m.self_concurrent and not tgt.self_concurrent:
                    tgt.self_concurrent = True
                    changed = True


# -- P1: thread-shared-state ---------------------------------------------

@dataclass
class _AttrFacts:
    written_outside_init: bool = False
    all_latch: bool = True
    writer_tags: Set[str] = field(default_factory=set)
    tagsets: List[Tuple[Set[str], bool]] = field(default_factory=list)


def _attr_facts(model: _ClassModel) -> Dict[str, _AttrFacts]:
    facts: Dict[str, _AttrFacts] = {}
    for m in model.methods.values():
        for acc in m.accesses:
            f = facts.setdefault(acc.attr, _AttrFacts())
            if acc.write and not m.is_init:
                f.written_outside_init = True
                if not acc.latch:
                    f.all_latch = False
                if m.tags:
                    f.writer_tags |= m.tags
            if m.tags and not m.is_init:
                f.tagsets.append((m.tags, m.self_concurrent))
    return facts


def _shared_attrs(model: _ClassModel) -> Dict[str, _AttrFacts]:
    """Attributes reachable from >= 2 thread classes (or one
    self-concurrent class) with at least one real post-init write."""
    out: Dict[str, _AttrFacts] = {}
    for attr, f in _attr_facts(model).items():
        if not f.written_outside_init or f.all_latch:
            continue  # immutable config / stop-latch idiom: exempt
        tags: Set[str] = set()
        concurrent = False
        for tagset, conc in f.tagsets:
            tags |= tagset
            concurrent |= conc
        if len(tags) >= 2 or (concurrent and tags):
            out[attr] = f
    return out


def _check_p1(analyzer, model: _ClassModel) -> None:
    shared = _shared_attrs(model)
    if not shared:
        return
    reported: Set[Tuple[str, str]] = set()
    for m in model.methods.values():
        if not m.tags or m.is_init:
            continue
        for acc in m.accesses:
            f = shared.get(acc.attr)
            if f is None or acc.locked:
                continue
            if (acc.attr, m.name) in reported:
                continue
            guarded = acc.attr in model.locked_attrs
            if not acc.write:
                # A read on the single writer thread is sequential with
                # every write — the snapshot discipline only matters
                # across threads.
                if f.writer_tags and m.tags == f.writer_tags \
                        and len(f.writer_tags) == 1 \
                        and not m.self_concurrent:
                    continue
                if not guarded and not f.writer_tags:
                    # never-locked attr written only from unclassified
                    # helpers: flag the writes, not every read
                    continue
            reported.add((acc.attr, m.name))
            tags = ", ".join(sorted(m.tags))
            if guarded:
                analyzer.report(
                    "P1", acc.node,
                    f"self.{acc.attr} is lock-guarded elsewhere in "
                    f"{model.name} but "
                    f"{'mutated' if acc.write else 'read'} here without "
                    f"the lock; this method runs on [{tags}] while "
                    "other threads touch the same attribute — take the "
                    "lock or use the *_snapshot() idiom")
            else:
                analyzer.report(
                    "P1", acc.node,
                    f"self.{acc.attr} is shared across thread classes "
                    f"[{', '.join(sorted(set().union(*[t for t, _ in f.tagsets])))}] "
                    f"in {model.name} but never lock-guarded; "
                    f"{'this write' if acc.write else 'this read'} races "
                    "— guard it with the manager lock")


# -- P2: drop-without-reply ----------------------------------------------

def _primitive_terminal(node: ast.AST, model: _ClassModel) -> bool:
    if isinstance(node, ast.Raise):
        return True
    if not isinstance(node, ast.Call):
        return False
    tail = _call_tail(node)
    if tail in {"send_message", "finish"}:
        return True
    a = _self_attr(node.func)
    if a is not None and _TERMINAL_NAME_RE.match(a):
        return True
    if tail in {"submit", "run"} and isinstance(node.func, ast.Attribute):
        base = _self_attr(node.func.value)
        # handing the upload to the IngestPool defers the refusal to
        # the flush barrier (drain() replays errors through the shared
        # refusal helper) — terminal by design.
        if base is not None and _POOLISH_RE.search(base):
            return True
    return False


def _primitive_progress(node: ast.AST) -> bool:
    """The upload was folded into protocol state: a collection on self
    mutated (arrived maps, done sets, pending buffers)."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript) \
                    and _self_attr(t.value) is not None:
                return True
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _P1_MUTATORS \
            and _self_attr(node.func.value) is not None:
        return True
    return False


def _acting_methods(model: _ClassModel) -> Set[str]:
    """Fixpoint of methods that terminate or progress the protocol
    somewhere in their body (callees count)."""
    acting: Set[str] = set()
    for name, m in model.methods.items():
        for n in _method_scope(m.node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if _primitive_terminal(n, model) or _primitive_progress(n):
                acting.add(name)
                break
    changed = True
    while changed:
        changed = False
        for name, m in model.methods.items():
            if name in acting:
                continue
            if m.calls & acting:
                acting.add(name)
                changed = True
    return acting


def _upload_handlers(model: _ClassModel) -> List[_Method]:
    names: Set[str] = set()
    for const, meth in model.registrations:
        if _UPLOAD_MSG_RE.search(const):
            names.add(meth)
    # inherited registrations are invisible per-module: fall back to the
    # handler naming convention for upload-shaped names
    for name in model.methods:
        if _HANDLERISH_RE.match(name) and re.search(
                r"upload|model_from_client|partial|delta", name):
            names.add(name)
    return [model.methods[n] for n in sorted(names) if n in model.methods]


def _check_p2(analyzer, model: _ClassModel) -> None:
    acting = _acting_methods(model)

    def stmt_acts(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if _primitive_terminal(n, model) or _primitive_progress(n):
                return True
            if isinstance(n, ast.Call):
                a = _self_attr(n.func)
                if a is not None and a in acting:
                    return True
        return False

    def bad_return(node: ast.Return) -> bool:
        return node.value is None or not stmt_acts(node.value)

    def check_block(stmts: Sequence[ast.stmt], acted: bool,
                    handler: _Method) -> Tuple[bool, bool]:
        """-> (acted_at_fall_through, terminated). Reports P2 at any
        return reached with nothing done for the sender."""
        for stmt in stmts:
            if isinstance(stmt, ast.Return):
                if not acted and bad_return(stmt):
                    analyzer.report(
                        "P2", stmt,
                        f"upload-handler path in {model.name}."
                        f"{handler.name} returns without a terminal "
                        "action (reply / shared refusal helper / "
                        "eviction / pool deferral / finish / raise) or "
                        "recorded progress — the PR 5/PR 10 "
                        "drop-without-reply deadlock shape; reply or "
                        "evict before dropping, or suppress with the "
                        "reason the sender cannot be waiting")
                return True, True
            if isinstance(stmt, ast.Raise):
                return True, True
            if isinstance(stmt, ast.If):
                acted_in = acted or stmt_acts(stmt.test)
                a_body, t_body = check_block(stmt.body, acted_in, handler)
                a_else, t_else = check_block(stmt.orelse, acted_in, handler)
                if t_body and t_else:
                    return True, True
                conts = [a for a, t in ((a_body, t_body), (a_else, t_else))
                         if not t]
                acted = all(conts) if conts else acted
                continue
            if isinstance(stmt, ast.Try):
                a_body, t_body = check_block(stmt.body, acted, handler)
                for h in stmt.handlers:
                    check_block(h.body, acted, handler)
                if stmt.orelse:
                    check_block(stmt.orelse, a_body, handler)
                if stmt.finalbody:
                    check_block(stmt.finalbody, acted, handler)
                acted = acted or stmt_acts(stmt)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                check_block(stmt.body, acted, handler)
                check_block(stmt.orelse, acted, handler)
                acted = acted or stmt_acts(stmt)
                continue
            if isinstance(stmt, ast.With):
                acted_in = acted or any(
                    stmt_acts(i.context_expr) for i in stmt.items)
                a_body, t_body = check_block(stmt.body, acted_in, handler)
                if t_body:
                    return True, True
                acted = a_body
                continue
            if stmt_acts(stmt):
                acted = True
        return acted, False

    for handler in _upload_handlers(model):
        acted, terminated = check_block(handler.node.body, False, handler)
        if not terminated and not acted:
            analyzer.report(
                "P2", handler.node,
                f"upload handler {model.name}.{handler.name} can fall "
                "through having neither replied, refused, evicted, "
                "deferred to the flush barrier, nor recorded the "
                "upload — the sender would wait forever")


# -- entry points ---------------------------------------------------------

def check_module(analyzer) -> None:
    """Run P1 + P2 over every class in ``analyzer.tree``; violations go
    through ``analyzer.report`` so suppressions/baseline Just Work."""
    for node in ast.walk(analyzer.tree):
        if isinstance(node, ast.ClassDef):
            model = build_class_model(node)
            _check_p1(analyzer, model)
            _check_p2(analyzer, model)


def thread_model_report(paths: Sequence[str]) -> str:
    """Human-readable per-class thread model (``fedlint
    --thread-report``): which methods run on which threads, and which
    attributes are shared across them."""
    import os

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    lines: List[str] = []
    for path in files:
        with open(path, "r", encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read())
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            model = build_class_model(node)
            tagged = {n: m for n, m in model.methods.items() if m.tags}
            if not any(t != {"dispatch"} for t in
                       (m.tags for m in tagged.values())):
                continue  # single-threaded class: nothing to report
            lines.append(f"{path}:{node.lineno}: class {model.name}")
            for name in sorted(tagged):
                m = tagged[name]
                conc = " (self-concurrent)" if m.self_concurrent else ""
                lines.append(
                    f"  {name}: [{', '.join(sorted(m.tags))}]{conc}")
            shared = _shared_attrs(model)
            for attr in sorted(shared):
                guard = ("locked" if attr in model.locked_attrs
                         else "UNGUARDED")
                lines.append(f"  shared self.{attr}: {guard}")
    return "\n".join(lines)
