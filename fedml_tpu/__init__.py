"""tpu-fed: a TPU-native federated-learning framework built from scratch in JAX/XLA.

Capability parity target: FedML v1 (arXiv:2007.13518); see SURVEY.md for the
structural analysis. Reference anchors are cited in docstrings as
``<path>:<line>`` relative to the reference tree.

Design stance (TPU-first, not a port):

- A *simulated* client is an index into a sharded array, not an OS process.
  Local client SGD is a jit-compiled ``lax.scan`` train step, ``vmap``-ed over
  the clients resident on one chip and ``shard_map``-ed over the ``clients``
  mesh axis. Server aggregation is a ``lax.psum`` weighted average over ICI —
  replacing the reference's MPI send/recv of pickled state_dicts
  (fedml_core/distributed/communication/mpi/com_manager.py:13).
- True cross-silo federation (separate trust domains over DCN) keeps a
  message-passing layer: ``fedml_tpu.comm`` (Message envelope, observer
  dispatch, loopback backend for tests, gRPC backend) — under construction;
  see SURVEY.md §7 for the build order.
- Everything on the compute path is functional and static-shaped: ragged
  client datasets are padded to rectangular ``[clients, steps, batch, ...]``
  layouts with masks so weighted averages stay exact.
"""

__version__ = "0.1.0"
