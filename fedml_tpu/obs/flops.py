"""Static model cost analysis.

Parity with the reference's ptflops check (fedml_api/model/cv/test_cnn.py:
1-13 prints MACs + params) via XLA's own compiled cost analysis — exact for
the graph XLA actually runs, not an operator-table estimate.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import numpy as np


def count_params(params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(params)))


def model_cost(model, sample_x, train: bool = False) -> Dict[str, float]:
    """{"flops", "params", "bytes_accessed"} for one forward pass of a
    registry model on ``sample_x`` (batched)."""
    from fedml_tpu.trainer.local import model_fns

    fns = model_fns(model)
    net = fns.init(jax.random.PRNGKey(0), sample_x)
    # Dropout-bearing models need an rng in train mode; a fixed key is fine
    # for a static cost analysis.
    rng = jax.random.PRNGKey(1) if train else None

    def fwd(net, x):
        logits, _ = fns.apply(net, x, train=train, rng=rng)
        return logits

    compiled = jax.jit(fwd).lower(net, sample_x).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "flops": float(ca.get("flops", float("nan"))),
        "bytes_accessed": float(ca.get("bytes accessed", float("nan"))),
        "params": count_params(net.params),
    }


def flops_str(cost: Dict[str, float]) -> str:
    """Human-readable 'X.XX GMac, Y.YY M params' (ptflops format)."""
    macs = cost["flops"] / 2.0
    return f"{macs / 1e9:.2f} GMac, {cost['params'] / 1e6:.2f} M params"
