"""Round timing + profiling.

The reference logs coarse aggregation wall-clock (FedAVGAggregator.py:60,
86-87) and nothing else. Here timing is a first-class subsystem:

- ``RoundTimer`` — per-phase wall-clock with jax ``block_until_ready``
  fencing so device work is actually measured (an async dispatch would
  otherwise clock ~0);
- ``trace`` — context manager around ``jax.profiler`` producing a
  TensorBoard-loadable XLA trace directory for the real TPU hot loop.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

# One warning per failure site per process: the profiler backend being
# unavailable (axon tunnel, missing plugin) is worth saying exactly once,
# not once per round — and never worth crashing the run over.
_WARNED: set = set()


def _warn_once(key: str, msg: str, *args) -> None:
    if key not in _WARNED:
        _WARNED.add(key)
        log.warning(msg, *args)


class RoundTimer:
    """Usage::

        t = RoundTimer()
        with t.phase("local_train"):
            out = round_fn(...)
            t.fence(out)          # block_until_ready inside the phase
        t.summary()  # {"local_train": {"mean_s": ..., "total_s": ..., "n": ...}}
    """

    def __init__(self):
        self._acc: Dict[str, List[float]] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self._acc.setdefault(name, []).append(time.perf_counter() - t0)

    def fence(self, tree):
        import jax

        jax.block_until_ready(tree)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for k, v in self._acc.items():
            out[k] = {
                "mean_s": sum(v) / len(v),
                "total_s": sum(v),
                "n": len(v),
                "last_s": v[-1],
            }
        return out

    def mark(self):
        """Snapshot phase counts; ``flat_metrics`` then reports only phases
        that recorded since the mark (so a round that ran no eval does not
        re-log the previous eval's duration)."""
        self._mark = {k: len(v) for k, v in self._acc.items()}

    def flat_metrics(self) -> Dict[str, float]:
        """{"time/<phase>_s": last} for phases recorded since ``mark()``
        (all phases if ``mark`` was never called)."""
        mark = getattr(self, "_mark", {})
        return {
            f"time/{k}_s": v[-1]
            for k, v in self._acc.items()
            if len(v) > mark.get(k, 0)
        }


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
    """XLA/TPU profiler trace (view in TensorBoard / xprof). No-op fallback
    if the profiler backend is unavailable on this platform."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(log_dir, create_perfetto_link=False)
        started = True
    except Exception as e:  # noqa: BLE001 — degrade to no-op, visibly
        _warn_once("start_trace",
                   "jax profiler start_trace failed (%s: %s) — running "
                   "WITHOUT an XLA trace; no artifacts will land in %r",
                   type(e).__name__, e, log_dir)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — artifacts may be partial
                _warn_once("stop_trace",
                           "jax profiler stop_trace failed (%s: %s) — trace "
                           "artifacts in %r may be incomplete",
                           type(e).__name__, e, log_dir)
