"""Federation flight recorder — span tracing + bounded control-plane event
ring.

Every postmortem in CHANGES.md (livelocked rounds, stranded receive
loops, chaos-delayed dones) was debugged by live CLI drives; this module
turns those into ARTIFACTS:

- :class:`SpanTracer` — a low-overhead span tracer over an injected
  monotonic clock (pass a ``sim.VirtualClock`` and a fleet drill traces
  in virtual time). Spans carry a correlation key — ``(epoch, round,
  sender, task_seq)`` — so one upload's lifecycle lines up across client
  serialize → wire → codec decode → accumulator fold → round commit.
  Dumps Chrome trace-event JSON (load in Perfetto / ``chrome://tracing``)
  plus raw JSONL.
- :data:`NULL` / :class:`NullTracer` — the disabled path. ``active()``
  returns it when nothing is installed; every call is a no-op returning
  a shared null context manager, so instrumented hot paths cost one
  attribute lookup + an empty ``with`` when tracing is off (pinned
  within 2% of uninstrumented in tests/test_trace.py).
- :class:`FlightRecorder` — a bounded ring buffer of recent control-plane
  events (beats, evictions, re-admissions, codec refusals, epoch drops;
  on the sharded aggregation plane also ``shard_eviction`` /
  ``shard_readmission`` and per-``round_commit`` shard membership)
  the server managers dump to the run directory on eviction / abort /
  ``CodecError``, so the minutes BEFORE a failure survive it.

The tracer is installed process-globally (``install`` / ``tracing_to``):
the message-passing tiers run one federation per process (or one drill
per test, via the ``using`` context manager), and a global hook is what
lets ``comm/codec.py`` and the sim fabric trace without threading a
tracer handle through every constructor. Deliberately stdlib-only at
import time.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

log = logging.getLogger(__name__)


def corr(epoch=None, round=None, sender=None, task_seq=None) -> Dict[str, int]:
    """The per-message correlation key. Drops unset fields so sync-tier
    spans (no task_seq) and async-tier spans (no barrier round) share one
    vocabulary."""
    out = {}
    if epoch is not None:
        out["epoch"] = int(epoch)
    if round is not None:
        out["round"] = int(round)
    if sender is not None:
        out["sender"] = int(sender)
    if task_seq is not None:
        out["task_seq"] = int(task_seq)
    return out


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The traced-off path: every method a no-op. Falsy, so call sites
    that must avoid even building a kwargs dict can guard with
    ``if tracer:``."""

    enabled = False

    def __bool__(self):
        return False

    def now(self) -> float:
        return 0.0

    def span(self, name, cat="", corr=None, **args):
        return _NULL_SPAN

    def complete(self, name, t0, t1=None, cat="", corr=None, **args):
        pass

    def instant(self, name, cat="", corr=None, **args):
        pass


NULL = NullTracer()
_ACTIVE = NULL
_INSTALL_LOCK = threading.Lock()


def active():
    """The installed tracer, or :data:`NULL` — ALWAYS safe to call."""
    return _ACTIVE


def install(tracer) -> None:
    """Install ``tracer`` process-wide (``None`` disables)."""
    global _ACTIVE
    with _INSTALL_LOCK:
        _ACTIVE = tracer if tracer is not None else NULL


@contextlib.contextmanager
def using(tracer):
    """Scoped install/restore — the test/drill idiom."""
    prev = _ACTIVE
    install(tracer)
    try:
        yield tracer
    finally:
        install(prev)


class _Span:
    __slots__ = ("_tr", "name", "cat", "args", "_t0")

    def __init__(self, tr, name, cat, args):
        self._tr = tr
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = self._tr.now()
        return self

    def __exit__(self, *exc):
        self._tr.complete(self.name, self._t0, cat=self.cat,
                          **(self.args or {}))
        return False


class SpanTracer:
    """Collects trace events in memory; dump at end of run.

    ``clock`` is any zero-arg monotone callable — ``time.perf_counter``
    for wall-clock runs, a ``sim.VirtualClock`` instance for virtual-time
    fleet drills (timestamps are then virtual seconds). Timestamps are
    recorded relative to the tracer's construction instant, in
    microseconds (the Chrome trace-event unit). Bounded: past
    ``max_events`` new events are counted in ``dropped`` instead of
    stored, so a long run cannot OOM the tracer."""

    enabled = True

    def __init__(self, clock=time.perf_counter, max_events: int = 200_000):
        self.clock = clock
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._tids: Dict[int, int] = {}
        self.dropped = 0
        self._t0 = float(clock())
        self._pid = os.getpid()

    def now(self) -> float:
        return float(self.clock())

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    # -- recording -----------------------------------------------------------
    def span(self, name, cat="", corr=None, **args):
        """Context manager timing its body as one complete ("X") event."""
        if corr:
            args.update(corr)
        return _Span(self, name, cat, args)

    def complete(self, name, t0, t1=None, cat="", corr=None, **args):
        """One complete event from an explicit start time — the form for
        spans whose start and end live on different callbacks (a sim
        message in flight: posted at t0, delivered now)."""
        if t1 is None:
            t1 = self.now()
        if corr:
            args.update(corr)
        self._emit({"name": name, "cat": cat or "span", "ph": "X",
                    "ts": round((float(t0) - self._t0) * 1e6, 3),
                    "dur": round(max(float(t1) - float(t0), 0.0) * 1e6, 3),
                    "pid": self._pid, "tid": self._tid(), "args": args})

    def instant(self, name, cat="", corr=None, **args):
        if corr:
            args.update(corr)
        self._emit({"name": name, "cat": cat or "event", "ph": "i",
                    "ts": round((self.now() - self._t0) * 1e6, 3),
                    "s": "t", "pid": self._pid, "tid": self._tid(),
                    "args": args})

    # -- reading / dumping ---------------------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def to_chrome(self) -> dict:
        """The Chrome trace-event JSON object format (Perfetto /
        ``chrome://tracing`` loadable)."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def dump_chrome(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
        return path

    def dump_jsonl(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        return path


@contextlib.contextmanager
def tracing_to(run_dir: Optional[str], clock=time.perf_counter,
               max_events: int = 200_000, suffix: str = ""):
    """Install a :class:`SpanTracer` for the body and dump
    ``trace<suffix>.chrome.json`` + ``trace<suffix>.jsonl`` into
    ``run_dir`` on exit — the one-liner the runners use (``suffix``
    disambiguates multi-process runs sharing one run_dir, e.g.
    ``.rank2`` per cross-silo rank). A falsy ``run_dir`` yields the
    :data:`NULL` tracer and touches nothing (the disabled path)."""
    if not run_dir:
        yield NULL
        return
    tracer = SpanTracer(clock=clock, max_events=max_events)
    with using(tracer):
        try:
            yield tracer
        finally:
            try:
                tracer.dump_chrome(
                    os.path.join(run_dir, f"trace{suffix}.chrome.json"))
                tracer.dump_jsonl(
                    os.path.join(run_dir, f"trace{suffix}.jsonl"))
            except (OSError, TypeError, ValueError) as e:
                # Diagnostics must not fail the run: TypeError/ValueError
                # cover a non-JSON-serializable span arg (span(**args)
                # accepts arbitrary values) raised by json.dump AT
                # TEARDOWN — after the federation already succeeded.
                log.warning("could not dump trace artifacts to %s: %s",
                            run_dir, e)


class FlightRecorder:
    """Bounded ring of recent control-plane events. ``record`` is a deque
    append; ``dump`` rewrites the whole ring as JSONL (small: ``capacity``
    lines), so each trigger leaves a complete picture of the run's last
    ``capacity`` events on disk. A dump failure logs and returns None —
    the recorder is a diagnostic, never a new way to crash the control
    plane."""

    def __init__(self, capacity: int = 512, clock=time.monotonic,
                 path: Optional[str] = None):
        self.clock = clock
        self.path = path
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))

    def record(self, kind: str, **fields) -> None:
        ev = {"t": round(float(self.clock()), 6), "kind": kind, **fields}
        with self._lock:
            self._events.append(ev)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        path = path or self.path
        if not path:
            return None
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                for ev in self.snapshot():
                    f.write(json.dumps(ev) + "\n")
            return path
        except (OSError, TypeError, ValueError) as e:
            log.warning("flight recorder dump to %s failed: %s", path, e)
            return None
