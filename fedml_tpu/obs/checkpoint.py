"""Checkpoint / resume (orbax).

The reference has NO general mechanism — only FedGKT's ad-hoc best/last
``.pth`` saves (GKTServerTrainer.py:212-219); nothing can resume a federated
run mid-training (SURVEY.md §5). Here any ``FederatedLoop`` run checkpoints
its full state — global model, server optimizer state, PRNG key, round
index — and resumes bit-exactly.

Layout: ``<dir>/<step>/state`` via orbax CheckpointManager (rotating
``max_to_keep``, optional best-metric retention).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import numpy as np


@dataclasses.dataclass
class RunState:
    """Everything needed to resume a federated run."""

    round_idx: int
    net: Any                      # NetState pytree
    rng: Any                      # jax PRNG key
    server_opt_state: Any = None  # FedOpt family; None for plain FedAvg
    extra: Any = None             # algorithm-specific state (Ditto's
                                  # personal models etc.) via the
                                  # checkpoint_extra_state hooks

    def to_pytree(self) -> Dict:
        return {
            "round_idx": np.asarray(self.round_idx, np.int64),
            "net": self.net,
            "rng": jax.random.key_data(self.rng) if hasattr(
                self.rng, "dtype") and jax.dtypes.issubdtype(
                    self.rng.dtype, jax.dtypes.prng_key) else self.rng,
            "server_opt_state": self.server_opt_state,
            "extra": self.extra,
        }


class CheckpointManager:
    """Thin orbax wrapper: ``save(step, state)`` / ``latest()`` /
    ``restore(step, like=)``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        import os

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._ocp = ocp

    def save(self, step: int, pytree: Dict, wait: bool = True):
        self._mgr.save(step, args=self._ocp.args.StandardSave(pytree))
        if wait:
            self._mgr.wait_until_finished()

    def wait(self):
        """Block until any in-flight async save has committed."""
        self._mgr.wait_until_finished()

    def steps(self):
        """Committed checkpoint steps (ascending)."""
        return sorted(self._mgr.all_steps())

    def latest(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, like: Optional[Dict] = None):
        step = self.latest() if step is None else step
        if step is None:
            return None
        if like is not None:
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(like)
            )
        return self._mgr.restore(step)

    def close(self):
        self._mgr.close()


def save_federation(mgr: CheckpointManager, net, round_idx: int, epoch: int,
                    wait: bool = False):
    """Checkpoint the message-passing federation's server state (the
    distributed control plane — algos/fedavg_distributed.py): the global
    net, the NEXT round to run, and the server epoch. ``wait=False`` by
    default: the save runs async, off the round critical path. A step
    that is already durable is skipped — a restarted server replaying
    its restored round would otherwise collide with the crashed
    instance's own save (orbax refuses to overwrite a committed step)."""
    if round_idx in mgr.steps():
        return
    try:
        mgr.save(round_idx, {
            "round_idx": np.asarray(round_idx, np.int64),
            "epoch": np.asarray(epoch, np.int64),
            "net": net,
        }, wait=wait)
    except ValueError as err:
        # steps() can be stale: the crashed instance's ASYNC save for
        # this step may commit between the check and our save. Either
        # way the step is durable — that is all this function promises.
        if "already exists" not in str(err):
            raise


def allocate_epoch(mgr: CheckpointManager, restored_epoch: int = -1) -> int:
    """Allocate a strictly monotonic server epoch for a (re)starting
    federation server. The epoch cannot ride the orbax step cadence: a
    restored instance cannot re-save its bumped epoch at the restored
    round (the step is already durable), so two crashes inside one
    checkpoint window would both restore the SAME stored epoch, bump it
    to the SAME value, and the pre-crash-upload fence would pass the
    previous incarnation's in-flight uploads. Instead a tiny ``EPOCH``
    sidecar in the checkpoint directory records the last epoch ever
    handed out; every server start takes
    ``max(restored_epoch, sidecar) + 1`` and persists it synchronously
    (write-then-rename) before any message is sent."""
    import os

    path = os.path.join(mgr._dir, "EPOCH")
    prev = -1
    try:
        with open(path) as f:
            prev = int(f.read().strip())
    except (OSError, ValueError):
        pass
    epoch = max(int(restored_epoch), prev) + 1
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(epoch))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return epoch


def restore_federation(mgr: CheckpointManager, like_net) -> Optional[Dict]:
    """Restore the latest federation checkpoint; returns
    ``{"round_idx", "epoch", "net"}`` or None when no checkpoint exists.
    The stored value is the epoch the crashed instance ran under; a
    restarted server must run under a fresh one via
    :func:`allocate_epoch` (NOT a plain ``+ 1`` — see its docstring)."""
    template = {
        "round_idx": np.asarray(0, np.int64),
        "epoch": np.asarray(0, np.int64),
        "net": like_net,
    }
    restored = mgr.restore(like=template)
    if restored is None:
        return None
    return {
        "round_idx": int(restored["round_idx"]),
        "epoch": int(restored["epoch"]),
        "net": restored["net"],
    }


def save_run(mgr: CheckpointManager, api, round_idx: int):
    """Checkpoint a ``FederatedLoop`` API (FedAvg family) after
    ``round_idx`` completed rounds. APIs with state beyond
    (net, rng, server opt) — e.g. Ditto's personal models — expose it via
    ``checkpoint_extra_state() -> pytree`` and
    ``load_checkpoint_extra_state(pytree)``; forgetting the hook would
    silently reset that state on resume."""
    extra_fn = getattr(api, "checkpoint_extra_state", None)
    state = RunState(
        round_idx=round_idx,
        net=api.net,
        rng=api.rng,
        server_opt_state=getattr(api, "server_opt_state", None),
        extra=extra_fn() if extra_fn is not None else None,
    )
    mgr.save(round_idx, state.to_pytree())


def restore_run(mgr: CheckpointManager, api) -> int:
    """Restore the latest checkpoint into ``api`` (in place). Returns the
    next round index to run (0 when no checkpoint exists)."""
    extra_fn = getattr(api, "checkpoint_extra_state", None)
    template = RunState(
        round_idx=0,
        net=api.net,
        rng=api.rng,
        server_opt_state=getattr(api, "server_opt_state", None),
        extra=extra_fn() if extra_fn is not None else None,
    ).to_pytree()
    restored = mgr.restore(like=template)
    if restored is None:
        return 0
    api.net = restored["net"]
    rng = restored["rng"]
    # key_data round-trips as uint32 array; wrap back into a typed key.
    api.rng = jax.random.wrap_key_data(np.asarray(rng))
    if restored.get("server_opt_state") is not None and hasattr(api, "server_opt_state"):
        api.server_opt_state = restored["server_opt_state"]
    if restored.get("extra") is not None:
        api.load_checkpoint_extra_state(restored["extra"])
    return int(restored["round_idx"]) + 1
