"""Metrics logging — the reference's W&B-everywhere pattern
(FedAVGAggregator.py:140-161, wandb.init at main_fedavg.py:430-443) behind a
pluggable sink so runs work with no external service.

``MetricsLogger.log(metrics, step)`` fans out to sinks:
- ``JsonlSink`` — one JSON object per line (the offline default; doubles as
  the machine-readable run record the reference keeps in wandb-summary.json)
- ``StdoutSink`` — human-readable via ``logging``
- ``WandbSink`` — real W&B when the package + a login exist (import-gated)
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional


class JsonlSink:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def log(self, metrics: Dict, step: int):
        self._f.write(json.dumps({"step": step, **metrics}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


class StdoutSink:
    def __init__(self, name: str = "fedml_tpu"):
        self._log = logging.getLogger(name)

    def log(self, metrics: Dict, step: int):
        self._log.info("step=%d %s", step, json.dumps(metrics))

    def close(self):
        pass


class WandbSink:
    """Real Weights & Biases, constructed only if importable (the reference
    hard-depends on wandb; we degrade gracefully)."""

    def __init__(self, project: str, config: Optional[Dict] = None, **kw):
        import wandb  # gated; raises ImportError when absent

        self._run = wandb.init(project=project, config=config, **kw)
        self._wandb = wandb

    def log(self, metrics: Dict, step: int):
        # ``step`` rides the wandb axis, not the metric dict (the full
        # entry now includes it for the file sinks).
        self._wandb.log({k: v for k, v in metrics.items() if k != "step"},
                        step=step)

    def close(self):
        self._run.finish()


class MetricsLogger:
    """Fan-out logger + in-memory history (so callers can assert on curves
    the way the reference's CI reads wandb-summary.json)."""

    def __init__(self, sinks=()):
        self.sinks = list(sinks)
        self.history: List[Dict] = []

    @classmethod
    def for_run(cls, run_dir: Optional[str] = None, stdout: bool = True,
                wandb_project: Optional[str] = None, config: Optional[Dict] = None):
        sinks = []
        if run_dir:
            sinks.append(JsonlSink(os.path.join(run_dir, "metrics.jsonl")))
        if stdout:
            sinks.append(StdoutSink())
        if wandb_project:
            try:
                sinks.append(WandbSink(wandb_project, config))
            except Exception:
                logging.getLogger(__name__).warning(
                    "wandb unavailable; continuing without it")
        return cls(sinks)

    def log(self, metrics: Dict, step: int, prefix: Optional[str] = None):
        """``prefix`` namespaces the keys (``"ctrl"`` → ``ctrl/evictions``)
        so structured subsystem streams — e.g. the distributed control
        plane's per-round health counters (evictions, readmissions,
        duplicate/epoch drops, send retries) — coexist with the training
        curves in one history/sink without key collisions."""
        if prefix:
            metrics = {f"{prefix}/{k}": v for k, v in metrics.items()}
        entry = {"step": step, "ts": time.time(), **metrics}
        self.history.append(entry)
        # Sinks receive the FULL entry, ``ts`` included: metrics.jsonl
        # rows from different processes (server + silo ranks appending to
        # one run_dir) are only orderable by wall clock, and the old
        # metrics-only fan-out silently dropped it.
        for s in self.sinks:
            s.log(entry, step)

    def summary(self) -> Dict:
        """Last value per key — the wandb-summary.json equivalent the
        reference's equivalence CI asserts on (CI-script-fedavg.sh:40-45)."""
        out: Dict = {}
        for e in self.history:
            out.update(e)
        return out

    def close(self):
        for s in self.sinks:
            s.close()
