"""Runtime sanitizer: the dynamic complement to fedlint's static rules.

fedlint (fedml_tpu.lint) catches the pitfall *patterns* in the AST; this
module catches the two **runtime symptoms** those pitfalls produce in a
steady-state round loop, cheaply enough to leave on in tests and bench:

- **unplanned transfers** — ``sanitized()`` arms
  ``jax.transfer_guard("disallow")``, so any *implicit* host<->device
  copy (a numpy argument leaking into a jitted call, eager mixing of
  host and device operands — the R3 class at runtime) raises inside the
  guarded region. Deliberate staging transfers (the streaming store's
  H2D of gathered cohorts) are marked with ``planned_transfer()``,
  which locally re-allows them: "zero unplanned transfers" then means
  exactly what it says.
- **recompiles** — a process-wide ``jax.monitoring`` listener counts
  backend-compile events (they fire only on true cache misses, never on
  hits). ``sanitized()`` snapshots the counter around its body and, in
  strict mode, raises ``SanitizerError`` if the steady-state region
  compiled anything (the R4 class at runtime).

Both guards are thread-scoped the way JAX scopes them: the transfer
guard is a thread-local context, so prefetcher worker threads (whose
staging H2D is planned by construction) are unaffected; the compile
counter is global, so a recompile triggered from any thread inside the
region is charged to it — which is the honest accounting for "zero
recompiles after warmup".
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

import jax


class SanitizerError(AssertionError):
    """Steady-state contract violated (recompiles in a sanitized region)."""


class _CompileCounter:
    """Process-wide compile-event counter. jax.monitoring listeners
    cannot be unregistered individually, so install exactly one for the
    process lifetime and read deltas."""

    _instance = None
    _install_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

        def _on_duration(name: str, duration: float, **kw) -> None:
            # '/jax/core/compile/backend_compile_duration' fires once per
            # actual XLA compilation; jit cache hits record nothing.
            if name.endswith("backend_compile_duration"):
                with self._lock:
                    self._count += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)

    @classmethod
    def install(cls) -> "_CompileCounter":
        with cls._install_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


def compile_count() -> int:
    """Monotonic count of XLA compilations since the counter was first
    installed (installs it on first use)."""
    return _CompileCounter.install().count


@dataclass
class SanitizerReport:
    """What the sanitized region observed. ``compiles`` is filled in on
    exit; inside the region it reads the running delta."""

    transfer: str = "disallow"
    max_compiles: int = 0
    compiles: int = 0
    _start: int = field(default=0, repr=False)
    _counter: object = field(default=None, repr=False)
    _closed: bool = field(default=False, repr=False)

    def compiles_so_far(self) -> int:
        if self._closed:
            return self.compiles
        return self._counter.count - self._start

    def assert_clean(self) -> None:
        n = self.compiles_so_far()
        if n > self.max_compiles:
            raise SanitizerError(
                f"sanitized region compiled {n} executable(s) "
                f"(allowed: {self.max_compiles}): the steady-state loop "
                "is re-tracing — look for shape churn (unbucketed step "
                "counts), unhashable static args, or weak_type/dtype "
                "drift (fedlint R4; docs/LINT.md)")


@contextmanager
def sanitized(transfer: str = "disallow", max_compiles: int = 0,
              strict: bool = True):
    """Run the body as a steady-state region: implicit transfers raise
    immediately (``jax.transfer_guard(transfer)``), and on exit the
    region must not have compiled more than ``max_compiles`` executables
    (``SanitizerError`` when ``strict``; inspect the yielded report when
    not). Warm the loop up OUTSIDE the region first — compilation of the
    first window/round is planned, re-compilation afterwards is the bug.
    """
    counter = _CompileCounter.install()
    report = SanitizerReport(transfer=transfer, max_compiles=max_compiles,
                             _start=counter.count, _counter=counter)
    with jax.transfer_guard(transfer):
        yield report
    report.compiles = counter.count - report._start
    report._closed = True
    if strict:
        report.assert_clean()


@dataclass
class DonationAudit:
    """Counts LIVE device copies of model-sized buffers — the runtime
    complement to fedlint's static R5 (read-after-donation): a donation
    regression (a dispatch that stops donating its carry, or a stray
    host reference pinning the old model) shows up as a copies() > 1
    steady state a test can assert on, instead of a profile someone has
    to read.

    Mechanism: the template net's leaf signatures (shape, dtype) are
    matched against ``jax.live_arrays()`` — donated (deleted) buffers
    drop out of that listing, so a fused round loop that donates its
    ``(net, extra)`` carry holds exactly ONE live copy of the model
    between dispatches, while an undonated round holds the old net AND
    the round average/new net simultaneously (>= 2). ``sample()`` after
    each round records the running peak.

    Honest-accounting caveat: matching is by (shape, dtype) signature,
    so an unrelated live array that happens to share a leaf's signature
    counts too (optimizer state held OUTSIDE the dispatch, a user's
    deliberate copy). Audit with the federation data's shapes disjoint
    from the model's (true for every model here — data is [S, B, ...])
    and treat copies() as an upper bound pinned against a known-good
    value."""

    template: object
    peak: float = 0.0

    def __post_init__(self):
        leaves = jax.tree.leaves(self.template)
        self._sigs = frozenset(
            (tuple(l.shape), str(l.dtype)) for l in leaves)
        self._bytes_one = float(sum(
            l.size * l.dtype.itemsize for l in leaves)) or 1.0

    def copies(self) -> float:
        """Live bytes matching the template's leaf signatures, in units
        of one whole model copy."""
        live = 0.0
        for a in jax.live_arrays():
            try:
                sig = (tuple(a.shape), str(a.dtype))
            except RuntimeError:  # deleted between listing and probing
                continue
            if sig in self._sigs:
                live += a.size * a.dtype.itemsize
        return live / self._bytes_one

    def sample(self) -> float:
        n = self.copies()
        self.peak = max(self.peak, n)
        return n


@contextmanager
def donation_audit(template):
    """Audit a steady-state round loop for model-buffer copies: yields a
    :class:`DonationAudit` built from ``template`` (the model's NetState
    or params pytree); call ``sample()`` after each round dispatch and
    assert on ``peak`` (fused donated rounds: 1.0)."""
    yield DonationAudit(template)


@contextmanager
def planned_transfer():
    """Mark a deliberate host<->device staging copy inside a
    ``sanitized()`` region (the streaming store's cohort/window H2D, the
    end-of-loop loss fetch). Locally re-allows transfers; a no-op when
    no sanitizer is active."""
    with jax.transfer_guard("allow"):
        yield
