"""Process-wide metrics registry: counters, gauges, log-bucketed histograms.

The latency-attribution half of the federation flight recorder
(obs/trace.py is the when-did-it-happen half). The server-ingest path is
the engineering bottleneck at scale (arXiv:2307.06561), but until now the
repo could only see coarse per-phase wall clock (``RoundTimer``) and
scalar ``ctrl/`` counters — nothing that says where one upload's time
goes across decode → fold → commit, or what the tails look like. A
:class:`Histogram` here is a few hundred integer buckets, so the server
managers record EVERY upload's decode/fold milliseconds, staleness, and
payload bytes with nanosecond-scale overhead and snapshot p50/p95 into
the existing ``MetricsLogger`` ``ctrl/`` stream each round.

Bucket math: log-spaced buckets with ratio ``growth`` (default 2**0.25 ≈
1.19, ≤ ~9% relative quantile error). Bucket 0 absorbs everything at or
below ``lo``; bucket ``i ≥ 1`` covers ``(lo·g^(i-1), lo·g^i]``.
Percentiles return the geometric midpoint of the selected bucket,
clamped to the observed min/max — pinned against numpy percentiles in
tests/test_trace.py.

The ``ctrl/`` metric names the registry snapshot emits
(``decode_ms_p50``, ``fold_ms_p95``, ``bytes_per_upload_mean``,
``staleness_p95``, ``ingest_queue_depth``, …) are a STABLE surface —
docs/OBSERVABILITY.md documents them; benches and dashboards key on
them.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional


class Counter:
    """Monotone event counter. Single-writer by design (the dispatch
    thread); reads from other threads see a consistent int."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-value-wins instantaneous reading (queue depth, buffer fill)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value: Optional[float] = None

    def set(self, v) -> None:
        self.value = float(v)


class Histogram:
    """Log-bucketed histogram of a positive-valued stream.

    ``record`` is O(1): one ``log`` plus a dict increment. Values at or
    below ``lo`` (including zero/negative — a sub-resolution duration)
    land in bucket 0 and estimate as the observed minimum.
    """

    def __init__(self, lo: float = 1e-3, growth: float = 2.0 ** 0.25):
        if lo <= 0 or growth <= 1:
            raise ValueError(f"need lo > 0 and growth > 1, got {lo}, {growth}")
        self.lo = float(lo)
        self.growth = float(growth)
        self._log_g = math.log(self.growth)
        self._buckets: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, v) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= self.lo:
            i = 0
        else:
            i = 1 + int(math.floor(math.log(v / self.lo) / self._log_g - 1e-12))
        self._buckets[i] = self._buckets.get(i, 0) + 1

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-th percentile (q in [0, 100]): geometric midpoint
        of the bucket holding the rank, clamped to the observed range."""
        if not self.count:
            return None
        rank = min(max(int(math.ceil(q / 100.0 * self.count)), 1), self.count)
        cum = 0
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if cum >= rank:
                if i == 0:
                    est = self.min
                else:
                    est = self.lo * self.growth ** (i - 0.5)
                return min(max(est, self.min), self.max)
        return self.max  # unreachable; defensive

    def snapshot(self) -> Dict[str, Optional[float]]:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": round(self.mean, 6),
            "p50": round(self.percentile(50), 6),
            "p95": round(self.percentile(95), 6),
            "max": round(self.max, 6),
        }


class MetricsRegistry:
    """Named metric namespace. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent, so call sites never coordinate);
    ``snapshot`` flattens everything into one dict of scalars, ready for
    ``MetricsLogger.log(..., prefix="ctrl")``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str, lo: float = 1e-3,
                  growth: float = 2.0 ** 0.25) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(lo=lo, growth=growth)
            return h

    def snapshot(self) -> Dict[str, object]:
        """Flat scalars: ``<counter>``, ``<gauge>``, and per histogram
        ``<name>_count/_mean/_p50/_p95/_max``. Empty metrics are omitted
        so a quiet subsystem adds no noise to the ctrl/ stream."""
        out: Dict[str, object] = {}
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        for name, c in counters:
            out[name] = c.value
        for name, g in gauges:
            if g.value is not None:
                out[name] = g.value
        for name, h in hists:
            if h.count:
                for k, v in h.snapshot().items():
                    out[f"{name}_{k}"] = v
        return out


_GLOBAL = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry. Subsystem owners (the server
    managers) keep their OWN instances for isolation; this one serves
    code with no natural owner to thread an instance through."""
    return _GLOBAL


def payload_nbytes(tree) -> int:
    """Approximate bytes-on-wire of an upload payload: the sum of its
    array leaves' buffer sizes (scalars/strings are header noise next to
    model tensors). Wire-format independent, so the loopback
    by-reference drill still histograms honest payload sizes."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "__array__"):
            total += int(np.asarray(leaf).nbytes)
    return total


def hist_fields(hist: Histogram, name: str) -> Dict[str, Optional[float]]:
    """``{name_p50, name_p95, name_mean, name_count}`` — the compact
    per-histogram record the bench's ``ingest_profile`` section reports."""
    if not hist.count:
        return {f"{name}_count": 0}
    return {
        f"{name}_count": hist.count,
        f"{name}_mean": round(hist.mean, 4),
        f"{name}_p50": round(hist.percentile(50), 4),
        f"{name}_p95": round(hist.percentile(95), 4),
    }
