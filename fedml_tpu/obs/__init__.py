"""Observability: metrics sinks, round timing/profiling, checkpoint/resume.

The reference's equivalents: wandb calls hard-wired into aggregators
(FedAVGAggregator.py:140-161), coarse wall-clock logs, and no checkpointing
(SURVEY.md §5). Here all three are framework subsystems.
"""

from fedml_tpu.obs.logger import JsonlSink, MetricsLogger, StdoutSink, WandbSink
# NOTE: ``fedml_tpu.obs.trace`` is the span-tracer MODULE (the federation
# flight recorder); the XLA profiler context manager formerly re-exported
# here under the same name stays importable as ``obs.timing.trace``.
from fedml_tpu.obs import trace
from fedml_tpu.obs.timing import RoundTimer
from fedml_tpu.obs.trace import (
    FlightRecorder,
    NullTracer,
    SpanTracer,
    tracing_to,
)
from fedml_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from fedml_tpu.obs.checkpoint import (
    CheckpointManager,
    RunState,
    allocate_epoch,
    restore_federation,
    restore_run,
    save_federation,
    save_run,
)
from fedml_tpu.obs.flops import count_params, flops_str, model_cost
from fedml_tpu.obs.sanitizer import (
    DonationAudit,
    SanitizerError,
    SanitizerReport,
    compile_count,
    donation_audit,
    planned_transfer,
    sanitized,
)

__all__ = [
    "JsonlSink",
    "MetricsLogger",
    "StdoutSink",
    "WandbSink",
    "RoundTimer",
    "trace",
    "FlightRecorder",
    "NullTracer",
    "SpanTracer",
    "tracing_to",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "CheckpointManager",
    "RunState",
    "allocate_epoch",
    "restore_federation",
    "restore_run",
    "save_federation",
    "save_run",
    "count_params",
    "flops_str",
    "model_cost",
    "DonationAudit",
    "SanitizerError",
    "SanitizerReport",
    "compile_count",
    "donation_audit",
    "planned_transfer",
    "sanitized",
]
