"""Per-rank cross-silo FedAvg entry — the reference's mpirun story with
separate OS processes over the native TCP transport (or gRPC via
``--comm_backend GRPC``).

The reference launches `mpirun -np W+1 python main_fedavg.py` and every rank
runs the same program (run_fedavg_distributed_pytorch.sh:21). Here each silo
process runs:

    python -m fedml_tpu.exp.main_cross_silo --rank 0 --size 3 \
        --host_table hosts.csv --model lr --dataset mnist ...   # server
    python -m fedml_tpu.exp.main_cross_silo --rank 1 --size 3 ...  # silo 1
    python -m fedml_tpu.exp.main_cross_silo --rank 2 --size 3 ...  # silo 2

``--host_table`` is the grpc_ipconfig.csv-format rank→host[,port] table
(defaults: every rank on 127.0.0.1 with port ``--port_base``+rank). Every
rank loads the dataset with identical flags/seed (as the reference does,
main_fedavg.py:133 — "every rank loads the full dataset"), so client shards
agree across processes without shipping data.

The server prints one JSON line with the final test metrics when done.
"""

from __future__ import annotations

import argparse
import json
import logging

import jax
import jax.numpy as jnp

from fedml_tpu.algos.fedavg_distributed import (
    FedAVGAggregator,
    FedAVGClientManager,
    FedAVGServerManager,
)
from fedml_tpu.exp.args import add_args
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)

DEFAULT_PORT_BASE = 50100


def build_host_table(args):
    if args.host_table:
        from fedml_tpu.comm.tcp import read_ip_config

        return read_ip_config(args.host_table, base_port=args.port_base)
    return {r: ("127.0.0.1", args.port_base + r) for r in range(args.size)}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--size", type=int, required=True,
                        help="total processes = 1 server + W silos")
    parser.add_argument("--host_table", type=str, default=None,
                        help="grpc_ipconfig.csv-format rank,host[,port] table")
    parser.add_argument("--port_base", type=int, default=DEFAULT_PORT_BASE)
    parser.add_argument("--comm_backend", type=str, default="TCP",
                        choices=["TCP", "GRPC", "TRPC"],
                        help="cross-silo transport: native C++ msgnet TCP, "
                             "grpcio (proto/comm.proto wire), or TRPC "
                             "(acknowledged RPC sends, pickle-free tensor "
                             "wire)")
    # --compress comes from the shared add_args flag set: here it is the
    # legacy on-device codec (none | topk<ratio> with error feedback |
    # q<bits> stochastic quantization), decoded by the server per frame.
    # --wire_codec (also shared) is the NEGOTIATED wire codec
    # (comm/codec.py: bf16/fp16/int8/topk/randmask, composable, error
    # feedback on sparsifiers) — mutually exclusive with --compress.
    # --ingest_workers (also shared) arms the server's parallel ingest
    # pool (comm/ingest.py; rank 0 only — silos ignore it): decode +
    # mean-fold off the dispatch thread, bit-equal for any worker count.
    # The read happens through cfg on the rank-0 manager:
    # fedlint: consumes(ingest_workers)
    # --secagg / --secagg_t (also shared) arm dropout-robust secure
    # aggregation (comm/secagg.py): pairwise-masked int64 uploads that
    # cancel exactly in the pool's fixed-point fold, with t-of-n Shamir
    # seed reveal on eviction. Sync tier only; the rank-0 manager reads
    # both through cfg (and refuses without an ingest pool):
    # fedlint: consumes(secagg, secagg_t)
    parser.add_argument("--aggregate_k", type=int, default=0,
                        help="straggler-tolerant first-k rounds: aggregate "
                             "as soon as k fresh uploads arrive (0 = wait "
                             "for all silos)")
    # Control plane (docs/ROBUSTNESS.md): --round_timeout_s /
    # --heartbeat_interval_s come from the shared flag set;
    # --checkpoint_frequency + --run_dir arm the server's crash-resume
    # checkpoints (kill rank 0, rerun the same command: it restores the
    # latest checkpoint, bumps its epoch, and the federation continues).
    parser.add_argument("--idle_timeout_s", type=float, default=0.0,
                        help="silo self-termination bound: exit after this "
                             "many seconds without server contact (0 = "
                             "wait forever)")
    add_args(parser)
    args = parser.parse_args(argv)
    if not 0 <= args.rank < args.size:
        raise SystemExit(f"--rank {args.rank} outside [0, {args.size})")
    if args.client_selection != "random":
        raise SystemExit(
            f"--client_selection {args.client_selection} is a simulator "
            "feature; the cross-silo server samples uniformly (it has no "
            "access to silo-local losses before assignment)")
    from fedml_tpu.exp.args import (reject_adapter_flags,
                                    reject_agg_shards_flag,
                                    reject_async_tier_flags,
                                    reject_controller_flags,
                                    reject_fedavg_family_flags,
                                    reject_pod_plane_flags,
                                    reject_serve_flags)

    # The cross-silo server reduces with FedAVGAggregator-parity math —
    # the simulator's pluggable aggregator/corruption drill would be
    # silently inert here, and the barrier rounds have no staleness
    # stream for the async-tier knobs to act on.
    reject_fedavg_family_flags(args, "the cross-silo pipeline")
    reject_async_tier_flags(args, "the cross-silo pipeline")
    # Silos shard by RANK, not by mesh (need_mesh=False below), and the
    # silo trainers are built directly from fns.apply — none of the pod
    # compute-plane knobs (bf16 client step, DCN group reduce, the mesh
    # factorization) reach this path.
    reject_pod_plane_flags(args, "the cross-silo pipeline")
    # Ditto the frozen-base adapter knobs: the silo trainer below is
    # built from plain model_fns, so --adapter_rank would silently
    # train the dense arm while reporting the adapter experiment.
    reject_adapter_flags(args, "the cross-silo pipeline")
    # The sharded aggregation plane needs M extra in-process shard ranks
    # between server and silos — a topology the rank-per-process CLI does
    # not launch. It rides the loopback/sim runner:
    # FedML_FedAvg_distributed(..., agg_shards=M) (comm/shardplane.py).
    reject_agg_shards_flag(args, "the cross-silo pipeline")
    # No serving plane on the rank-per-process CLI either — serving
    # rides main_extra's FedBuff runner (fedml_tpu.serve).
    reject_serve_flags(args, "the cross-silo pipeline")
    # The adaptive controller is wired through main_extra's
    # FedAsync/FedBuff runners only; until a cross-silo deployment
    # threads controller_from_args through to its rank-0 manager the
    # flag would be silently inert here (fedml_tpu.ctrl).
    reject_controller_flags(args, "the cross-silo pipeline")

    logging.basicConfig(
        level=logging.INFO,
        format=f"[cross-silo rank {args.rank}] %(asctime)s %(message)s")

    from fedml_tpu.exp.setup import setup_standard

    # Client silos never evaluate and shard clients by rank, not by mesh —
    # skip the global test-set concat (rank 0 only) and mesh build.
    fed, arrays, test, model, cfg, _ = setup_standard(
        args, need_test=(args.rank == 0), need_mesh=False)
    worker_num = args.size - 1
    if worker_num > fed.client_num:
        raise SystemExit(
            f"--size {args.size} needs {worker_num} clients but the dataset "
            f"has only {fed.client_num}; reduce --size or raise "
            "--client_num_in_total")
    cfg.client_num_per_round = worker_num
    fns = model_fns(model)

    class NetArgs:
        pass

    net_args = NetArgs()
    net_args.host_table = build_host_table(args)

    # --trace: each rank traces its own half of the upload lifecycle and
    # dumps rank-suffixed artifacts into the shared run_dir (the server's
    # ingest spans and the silos' train/serialize spans correlate by
    # (epoch, round, sender) — docs/OBSERVABILITY.md).
    from fedml_tpu.exp.args import trace_dir_from
    from fedml_tpu.obs import trace as obs_trace

    trace_dir = trace_dir_from(args)
    if args.rank == 0:
        import os

        sample_x = jnp.zeros((1,) + arrays.x.shape[3:], arrays.x.dtype)
        net0 = fns.init(jax.random.PRNGKey(cfg.seed), sample_x)
        eval_fn = jax.jit(make_eval_fn(fns.apply)) if test is not None else None
        aggregator = FedAVGAggregator(net0, worker_num, cfg, eval_fn, test)
        checkpoint_dir = None
        metrics = None
        if args.run_dir:
            from fedml_tpu.obs import MetricsLogger

            metrics = MetricsLogger.for_run(run_dir=args.run_dir,
                                            stdout=False)
            if args.checkpoint_frequency or args.resume:
                checkpoint_dir = os.path.join(args.run_dir, "ckpt")
        server = FedAVGServerManager(net_args, aggregator, cfg, args.size,
                                     backend=args.comm_backend,
                                     compress=args.compress,
                                     aggregate_k=args.aggregate_k,
                                     checkpoint_dir=checkpoint_dir,
                                     metrics=metrics, flight_dir=trace_dir)
        with obs_trace.tracing_to(trace_dir, suffix=".rank0"):
            server.run()
        if metrics is not None:
            metrics.close()
        final = aggregator.test_history[-1] if aggregator.test_history else {}
        print(json.dumps({"rank": 0, **final, **server.health(),
                          "ingest": server.ingest_profile()}))
    else:
        optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd,
                                          cfg.grad_clip)
        local_train = jax.jit(make_local_train_fn_from_cfg(
            fns.apply, optimizer, cfg, loss_fn=softmax_ce))
        client = FedAVGClientManager(net_args, args.rank, args.size, arrays,
                                     local_train, cfg,
                                     backend=args.comm_backend,
                                     compress=args.compress,
                                     wire_codec_spec=args.wire_codec,
                                     idle_timeout_s=args.idle_timeout_s)
        with obs_trace.tracing_to(trace_dir, suffix=f".rank{args.rank}"):
            client.run()
        print(json.dumps({"rank": args.rank, "status": "done"}))


if __name__ == "__main__":
    main()
