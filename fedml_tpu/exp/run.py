"""Generalized experiment runner — the fed_launch equivalent
(fedml_experiments/distributed/fed_launch/main.py): one entry, an
``--algorithm`` switch, round-level LR schedules and grad clipping.

Each per-algorithm ``main_<algo>.py`` is a thin wrapper over ``run(args)``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import sys

from fedml_tpu.exp.args import parse_args
from fedml_tpu.exp.setup import setup_standard


def round_lr(base_lr: float, schedule: str, round_idx: int, total_rounds: int,
             decay_rate: float = 0.992, buckets: int = 16) -> float:
    """Per-round client LR. Values are quantized to ``buckets`` distinct
    levels so ``set_client_lr`` re-jits at most ``buckets`` times per run."""
    if schedule == "none":
        return base_lr
    if schedule == "cosine":
        frac = round_idx / max(total_rounds - 1, 1)
        scale = 0.5 * (1 + math.cos(math.pi * frac))
    elif schedule == "step":
        scale = decay_rate ** round_idx
    else:
        raise ValueError(f"unknown lr_schedule {schedule!r}")
    q = max(round(scale * buckets), 1) / buckets
    return base_lr * q


SEQ_DATASETS = {"shakespeare", "fed_shakespeare", "stackoverflow_nwp"}


def make_api(algorithm: str, args, model, arrays, test, cfg, mesh,
             class_num: int | None = None):
    from fedml_tpu import algos
    from fedml_tpu.trainer.local import seq_softmax_ce

    common = dict(mesh=mesh) if mesh is not None else {}
    if args.dataset in SEQ_DATASETS:
        # Sequence tasks: per-position CE with pad positions masked out.
        # TFF datasets pad with id 0; LEAF shakespeare has no pad (id 0 is a
        # real char) and marks unknown chars -1 instead.
        pad_id = -1 if args.dataset == "shakespeare" else 0
        from functools import partial

        common["loss_fn"] = partial(seq_softmax_ce, pad_id=pad_id)
        common["pad_id"] = pad_id
    table = {
        "FedAvg": algos.FedAvgAPI,
        "FedAdapter": algos.FedAdapterAPI,
        "FedAc": algos.FedAcAPI,
        "ServerAvg": algos.ServerAvgAPI,
        "FedOpt": algos.FedOptAPI,
        "FedProx": algos.FedProxAPI,
        "FedNova": algos.FedNovaAPI,
        "FedAvgRobust": algos.FedAvgRobustAPI,
        "TurboAggregate": algos.TurboAggregateAPI,
        "Ditto": algos.DittoAPI,
        "QFedAvg": algos.QFedAvgAPI,
        "Scaffold": algos.ScaffoldAPI,
        "FedDyn": algos.FedDynAPI,
        "FedBN": algos.FedBNAPI,
    }
    if algorithm == "Ditto":
        common["lam"] = args.ditto_lam
    elif algorithm == "QFedAvg":
        common["q"] = args.qffl_q
    elif algorithm == "FedDyn":
        common["alpha"] = args.feddyn_alpha
    elif algorithm == "FedAdapter":
        if not int(getattr(args, "adapter_rank", 0) or 0):
            raise SystemExit(
                "FedAdapter needs --adapter_rank > 0 (the rank of the "
                "LoRA pairs injected into the transformer; 0 would "
                "silently train nothing)")
        if args.model != "transformer_lm":
            raise SystemExit(
                f"FedAdapter needs --model transformer_lm (got "
                f"{args.model!r}): adapter injection lives in "
                "models/transformer.py")
        if args.dataset not in SEQ_DATASETS:
            raise SystemExit(
                f"FedAdapter finetunes a token LM; --dataset "
                f"{args.dataset!r} is not a sequence dataset "
                f"(expected one of {sorted(SEQ_DATASETS)})")
    elif algorithm == "FedAc":
        common["gamma"] = getattr(args, "fedac_gamma", 2.0)
    elif algorithm == "ServerAvg":
        common["avg_coef"] = getattr(args, "server_avg_coef", 0.5)
    if algorithm in table:
        return table[algorithm](model, arrays, test, cfg, **common)
    if algorithm == "FedSeg":
        if class_num is None:
            raise ValueError("FedSeg needs class_num (the dataset's classes)")
        if args.dataset in SEQ_DATASETS:
            raise ValueError(
                "FedSeg is a segmentation task; it cannot run on sequence "
                f"dataset {args.dataset!r}")
        return algos.FedSegAPI(model, arrays, test, cfg,
                               num_classes=class_num, **common)
    if algorithm == "HierarchicalFL":
        import numpy as np

        # Round-robin group assignment over --group_num groups.
        group_ids = np.arange(cfg.client_num_in_total) % max(args.group_num, 1)
        return algos.HierarchicalFedAvgAPI(
            model, arrays, test, cfg, group_ids=group_ids, **common
        )
    raise ValueError(
        f"unknown algorithm {algorithm!r}; known: "
        f"{sorted(table) + ['FedSeg', 'HierarchicalFL']}"
    )


def run(args, algorithm: str = "FedAvg"):
    logging.basicConfig(
        level=logging.INFO,
        format=f"[{algorithm} %(asctime)s] %(message)s",
    )
    if args.backend != "collective":
        raise NotImplementedError(
            f"--backend {args.backend!r}: the exp runner drives the "
            "on-device collective simulator; for message-passing cross-silo "
            "runs use fedml_tpu.algos.fedavg_distributed with a comm "
            "backend from fedml_tpu.comm")
    # The synchronous simulator tiers have no arrival buffer or
    # staleness stream — those knobs belong to main_extra's
    # FedAsync/FedBuff runners and must refuse, not no-op. Same for the
    # parallel ingest pool: the simulator aggregates inside the jitted
    # round, there is no server dispatch thread to unblock.
    from fedml_tpu.exp.args import (reject_adapter_flags,
                                    reject_agg_shards_flag,
                                    reject_async_tier_flags,
                                    reject_controller_flags,
                                    reject_ingest_pool_flag,
                                    reject_secagg_flags,
                                    reject_serve_flags)

    reject_async_tier_flags(args, algorithm)
    reject_ingest_pool_flag(args, algorithm)
    reject_agg_shards_flag(args, algorithm)
    # The adaptive controller actuates a message-passing server manager's
    # knob seam between rounds — the jitted simulator round has no
    # manager, no seam, and no safe boundary to step from.
    reject_controller_flags(args, algorithm)
    # Secure aggregation rides the message-passing tier's fixed-point
    # ingest pool — the jitted simulator round materializes every client
    # update in the clear by construction, so the flag must refuse.
    reject_secagg_flags(args, algorithm)
    # No simulator tier serves: the serving plane rides main_extra's
    # FedBuff runner only (fedml_tpu.serve).
    reject_serve_flags(args, algorithm)
    # The FedAvg-family knobs are LIVE on this tier, read through cfg
    # rather than args: --aggregator/--corrupt_mode by FedAvgAPI's
    # pluggable reduce + corruption drill, and the pod compute-plane
    # trio by the shared round builders under setup_standard.
    # fedlint: consumes(aggregator, corrupt_mode)
    # fedlint: consumes(client_step_dtype, group_reduce, dcn_hosts)
    if algorithm != "FedAdapter":
        # Frozen-base adapter knobs configure FedAdapter only on this
        # tier — on any other algorithm they would silently train the
        # DENSE arm (the PR 4/14 convention; the FedAvgAPI constructor
        # backstops cfg.adapter_rank the same way).
        reject_adapter_flags(args, algorithm)
    fed, arrays, test, model, cfg, mesh = setup_standard(args)
    api = make_api(algorithm, args, model, arrays, test, cfg, mesh,
                   class_num=fed.class_num)

    # Per-client TEST shards (the reference's test_data_local_dict leg),
    # built once when the per-client eval cadence is on and the loader
    # kept local test arrays.
    test_fed_arrays = None
    if getattr(args, "eval_on_clients", False):
        from fedml_tpu.data.loaders import to_federated_arrays as _tfa

        test_fed_arrays = _tfa(fed, args.batch_size, split="test")

    from fedml_tpu.exp.args import trace_dir_from
    from fedml_tpu.obs import MetricsLogger, RoundTimer
    from fedml_tpu.obs import trace as obs_trace

    logger = MetricsLogger.for_run(
        run_dir=args.run_dir, stdout=True,
        wandb_project=getattr(args, "wandb_project", None),
        config=vars(args),
    )
    timer = RoundTimer()
    ckpt_mgr = None
    start_round = 0
    history = []
    # --trace on the simulator tier: per-round train/eval spans (the
    # message-passing tiers trace the full upload lifecycle; here the
    # round IS the unit of work) dumped to run_dir as Chrome trace JSON.
    tracing = contextlib.ExitStack()
    tracer = tracing.enter_context(obs_trace.tracing_to(trace_dir_from(args)))
    try:
        if args.run_dir and (args.checkpoint_frequency or args.resume):
            import os

            from fedml_tpu.obs import CheckpointManager, restore_run, save_run

            ckpt_mgr = CheckpointManager(os.path.join(args.run_dir, "ckpt"))
            if args.resume:
                start_round = restore_run(ckpt_mgr, api)
                if start_round:
                    logging.info("resumed from checkpoint at round %d", start_round)

        for r in range(start_round, cfg.comm_round):
            if hasattr(api, "set_client_lr"):
                api.set_client_lr(
                    round_lr(args.lr, cfg.lr_schedule, r, cfg.comm_round,
                             cfg.lr_decay_rate)
                )
            timer.mark()
            with timer.phase("round"), tracer.span(
                    "round", cat="round", corr=obs_trace.corr(round=r)):
                metrics = api.train_one_round(r)
                timer.fence(api.net)
            # Reference cadence: every frequency_of_the_test rounds + final
            # round; --ci evaluates the final round only (the flag's purpose
            # is to cut eval cost, FedAVGAggregator.py:127-132).
            do_eval = (r == cfg.comm_round - 1) or (
                not args.ci and r % cfg.frequency_of_the_test == 0
            )
            if do_eval:
                with timer.phase("eval"):
                    metrics.update(api.evaluate())
                    if getattr(args, "eval_on_clients", False):
                        metrics.update(api.evaluate_on_clients())
                        if test_fed_arrays is not None:
                            metrics.update(api.evaluate_on_clients(
                                test_fed_arrays, prefix="clients_test"))
                        # Same flag gates the personalized fleet eval —
                        # both are full per-client passes whose cost
                        # scales with N. Skip when evaluate() already
                        # produced the personal keys (FedBN's headline
                        # eval IS the personalized pass).
                        if (hasattr(api, "evaluate_personalized")
                                and "personal_accuracy" not in metrics):
                            metrics.update(api.evaluate_personalized())
            metrics.update(timer.flat_metrics())
            logger.log(metrics, step=r)
            history.append(metrics)
            if ckpt_mgr is not None and args.checkpoint_frequency and (
                (r + 1) % args.checkpoint_frequency == 0 or r == cfg.comm_round - 1
            ):
                save_run(ckpt_mgr, api, r)
    finally:
        # Flush/close sinks, the checkpoint manager and the tracer (its
        # dump runs on close) even on mid-run failure (OOM, NaN guard,
        # KeyboardInterrupt).
        tracing.close()
        if ckpt_mgr is not None:
            ckpt_mgr.close()
        logger.close()
    if getattr(args, "sweep_pipe", None):
        from fedml_tpu.utils import post_complete_message_to_sweep_process

        post_complete_message_to_sweep_process(vars(args),
                                               pipe_path=args.sweep_pipe)
    return api, history


def main(argv=None, algorithm: str = "FedAvg"):
    args = parse_args(argv)
    _, history = run(args, algorithm)
    # Empty history = resumed a run that had already completed.
    print(json.dumps(history[-1] if history else {"status": "already_complete"}))
    return history


if __name__ == "__main__":
    # fed_launch style: --algorithm as the first-class switch.
    import argparse

    from fedml_tpu.exp.args import add_args

    parser = argparse.ArgumentParser()
    parser.add_argument("--algorithm", type=str, default="FedAvg")
    add_args(parser)
    ns = parser.parse_args()
    _, hist = run(ns, ns.algorithm)
    print(json.dumps(hist[-1] if hist else {"status": "already_complete"}))
