"""Generalized experiment runner — the fed_launch equivalent
(fedml_experiments/distributed/fed_launch/main.py): one entry, an
``--algorithm`` switch, round-level LR schedules and grad clipping.

Each per-algorithm ``main_<algo>.py`` is a thin wrapper over ``run(args)``.
"""

from __future__ import annotations

import json
import logging
import math
import sys

from fedml_tpu.exp.args import parse_args
from fedml_tpu.exp.setup import setup_standard


def round_lr(base_lr: float, schedule: str, round_idx: int, total_rounds: int,
             decay_rate: float = 0.992, buckets: int = 16) -> float:
    """Per-round client LR. Values are quantized to ``buckets`` distinct
    levels so ``set_client_lr`` re-jits at most ``buckets`` times per run."""
    if schedule == "none":
        return base_lr
    if schedule == "cosine":
        frac = round_idx / max(total_rounds - 1, 1)
        scale = 0.5 * (1 + math.cos(math.pi * frac))
    elif schedule == "step":
        scale = decay_rate ** round_idx
    else:
        raise ValueError(f"unknown lr_schedule {schedule!r}")
    q = max(round(scale * buckets), 1) / buckets
    return base_lr * q


SEQ_DATASETS = {"shakespeare", "fed_shakespeare", "stackoverflow_nwp"}


def make_api(algorithm: str, args, model, arrays, test, cfg, mesh):
    from fedml_tpu import algos
    from fedml_tpu.trainer.local import seq_softmax_ce

    common = dict(mesh=mesh) if mesh is not None else {}
    if args.dataset in SEQ_DATASETS:
        # Sequence tasks: per-position CE with pad positions masked out.
        # TFF datasets pad with id 0; LEAF shakespeare has no pad (id 0 is a
        # real char) and marks unknown chars -1 instead.
        pad_id = -1 if args.dataset == "shakespeare" else 0
        from functools import partial

        common["loss_fn"] = partial(seq_softmax_ce, pad_id=pad_id)
        common["pad_id"] = pad_id
    table = {
        "FedAvg": algos.FedAvgAPI,
        "FedOpt": algos.FedOptAPI,
        "FedProx": algos.FedProxAPI,
        "FedNova": algos.FedNovaAPI,
        "FedAvgRobust": algos.FedAvgRobustAPI,
        "TurboAggregate": algos.TurboAggregateAPI,
    }
    if algorithm in table:
        return table[algorithm](model, arrays, test, cfg, **common)
    if algorithm == "HierarchicalFL":
        import numpy as np

        # Round-robin group assignment over --group_num groups.
        group_ids = np.arange(cfg.client_num_in_total) % max(args.group_num, 1)
        return algos.HierarchicalFedAvgAPI(
            model, arrays, test, cfg, group_ids=group_ids, **common
        )
    raise ValueError(
        f"unknown algorithm {algorithm!r}; known: {sorted(table) + ['HierarchicalFL']}"
    )


def run(args, algorithm: str = "FedAvg"):
    logging.basicConfig(
        level=logging.INFO,
        format=f"[{algorithm} %(asctime)s] %(message)s",
    )
    fed, arrays, test, model, cfg, mesh = setup_standard(args)
    cfg.lr_schedule = args.lr_schedule
    cfg.lr_decay_rate = args.lr_decay_rate
    cfg.grad_clip = args.grad_clip
    if args.ci:
        # The reference's --ci flag shrinks eval cost
        # (FedAVGAggregator.py:127-132); here rounds are already cheap, so
        # just evaluate only at the end.
        cfg.frequency_of_the_test = max(cfg.frequency_of_the_test, cfg.comm_round)
    api = make_api(algorithm, args, model, arrays, test, cfg, mesh)

    history = []
    for r in range(cfg.comm_round):
        if hasattr(api, "set_client_lr"):
            api.set_client_lr(
                round_lr(args.lr, cfg.lr_schedule, r, cfg.comm_round, cfg.lr_decay_rate)
            )
        metrics = api.train_one_round(r)
        if r % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1:
            metrics.update(api.evaluate())
        logging.info(json.dumps(metrics))
        history.append(metrics)
    return api, history


def main(argv=None, algorithm: str = "FedAvg"):
    args = parse_args(argv)
    _, history = run(args, algorithm)
    print(json.dumps(history[-1]))
    return history


if __name__ == "__main__":
    # fed_launch style: --algorithm as the first-class switch.
    import argparse

    from fedml_tpu.exp.args import add_args

    parser = argparse.ArgumentParser()
    parser.add_argument("--algorithm", type=str, default="FedAvg")
    add_args(parser)
    ns = parser.parse_args()
    _, hist = run(ns, ns.algorithm)
    print(json.dumps(hist[-1]))
