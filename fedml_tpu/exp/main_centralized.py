"""Centralized baseline CLI — the reference's accuracy anchor
(fedml_experiments/centralized/main.py, 382 LoC; DDP at :376).

Trains the pooled (non-federated) dataset conventionally over the same
model/dataset registries as the federated mains; ``--num_devices N``
shards every global batch over an N-device mesh (the DDP equivalent —
GSPMD inserts the gradient all-reduce). ``--comm_round`` counts outer
passes of ``--epochs`` epochs each, so total epochs = comm_round x epochs
(the reference's single ``--epochs`` loop with eval cadence folded in).

Usage:
  python -m fedml_tpu.exp.main_centralized --dataset cifar10 \
      --model resnet56 --batch_size 64 --lr 0.001 --epochs 5 \
      --comm_round 20 --num_devices 8
"""

from __future__ import annotations

import json
import logging
import sys


def run_centralized(args):
    from functools import partial

    from fedml_tpu.algos.centralized import CentralizedTrainer
    from fedml_tpu.exp.args import (config_from_args,
                                    reject_adapter_flags,
                                    reject_agg_shards_flag,
                                    reject_async_tier_flags,
                                    reject_controller_flags,
                                    reject_fedavg_family_flags,
                                    reject_ingest_pool_flag,
                                    reject_pod_plane_flags,
                                    reject_secagg_flags,
                                    reject_serve_flags)
    from fedml_tpu.exp.run import SEQ_DATASETS

    # The pooled baseline has no client step and no client axis — every
    # pod compute-plane knob (bf16 client step, DCN group reduce, the
    # mesh factorization) would be silently inert here, skewing any A/B
    # that uses this anchor.
    reject_pod_plane_flags(args, "the centralized baseline")
    # The frozen-base adapter finetune is a FEDERATED wire/perf story;
    # the pooled baseline trains every param — --adapter_rank here
    # would report an "adapter" anchor that actually trained dense.
    reject_adapter_flags(args, "the centralized baseline")
    # No aggregation step at all: the fedavg-family knobs (trimmed-mean
    # aggregator, corruption injection), the async tier, the ingest
    # pool, and the shard plane are all server-side machinery this
    # baseline does not instantiate. Refuse rather than silently train
    # a pooled run labeled with federation knobs.
    reject_fedavg_family_flags(args, "the centralized baseline")
    reject_async_tier_flags(args, "the centralized baseline")
    reject_ingest_pool_flag(args, "the centralized baseline")
    reject_agg_shards_flag(args, "the centralized baseline")
    # No uploads to mask either: the pooled baseline never federates.
    reject_secagg_flags(args, "the centralized baseline")
    # ...and no serving plane: serving rides main_extra's FedBuff runner.
    reject_serve_flags(args, "the centralized baseline")
    # ...and no server manager for a controller to actuate: the pooled
    # loop has no knobs, no telemetry stream, no safe boundaries.
    reject_controller_flags(args, "the centralized baseline")
    from fedml_tpu.exp.setup import (
        build_mesh,
        create_model_for,
        global_test_batches,
        global_train_batches,
        load_data,
    )
    from fedml_tpu.trainer.local import seq_softmax_ce, softmax_ce

    fed = load_data(args)
    train = global_train_batches(fed, args.batch_size)
    test = global_test_batches(fed, args.batch_size)
    model = create_model_for(args, fed)
    cfg = config_from_args(args)
    mesh = build_mesh(args.num_devices)

    if args.dataset in SEQ_DATASETS:
        pad_id = -1 if args.dataset == "shakespeare" else 0
        loss_fn = partial(seq_softmax_ce, pad_id=pad_id)
    else:
        loss_fn = softmax_ce

    if train is None:
        raise ValueError(
            f"dataset {args.dataset!r} produced no pooled train split "
            "(train_data_global is empty); the centralized baseline needs "
            "one")
    trainer = CentralizedTrainer(model, cfg, loss_fn=loss_fn, mesh=mesh)
    history = []
    for r in range(cfg.comm_round):
        metrics = {"round": r, "train_loss": trainer.train(*train)}
        if (test is not None
                and (r % cfg.frequency_of_the_test == 0
                     or r == cfg.comm_round - 1)):
            metrics.update(trainer.evaluate(*test))
        logging.info("%s", json.dumps(metrics))
        history.append(metrics)
    print(json.dumps(history[-1]))
    return trainer, history


def main(argv=None):
    from fedml_tpu.exp.args import parse_args

    logging.basicConfig(level=logging.INFO,
                        format="[Centralized %(asctime)s] %(message)s")
    args = parse_args(sys.argv[1:] if argv is None else argv)
    return run_centralized(args)


if __name__ == "__main__":
    main()
