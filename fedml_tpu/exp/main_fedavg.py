"""Distributed/standalone FedAvg entry — the north-star CLI
(fedml_experiments/distributed/fedavg/main_fedavg.py:392-491). On TPU the
"distributed" and "standalone" modes are the same program: clients are
sharded over the device mesh (``--num_devices``) instead of MPI ranks."""

from fedml_tpu.exp.run import main

if __name__ == "__main__":
    main(algorithm="FedAvg")
