"""Canonical experiment flags.

Mirrors the reference's argparse set 1:1 (fedml_experiments/distributed/
fedavg/main_fedavg.py:46-130) plus fed_launch's scheduler/clipping flags
(fed_launch/main.py:148-165), so reference launch commands port unchanged:

    python -m fedml_tpu.exp.main_fedavg --model resnet56 --dataset cifar10 \
        --partition_method hetero --client_num_in_total 10 ...
"""

from __future__ import annotations

import argparse

from fedml_tpu.algos.config import FedConfig


def add_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    p = parser
    p.add_argument("--model", type=str, default="resnet56")
    p.add_argument("--dataset", type=str, default="cifar10")
    p.add_argument("--data_dir", type=str, default=None)
    p.add_argument("--partition_method", type=str, default="hetero")
    p.add_argument("--partition_alpha", type=float, default=0.5)
    p.add_argument("--client_num_in_total", type=int, default=10)
    p.add_argument("--client_num_per_round", type=int, default=10)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--client_optimizer", type=str, default="sgd")
    p.add_argument("--backend", type=str, default="collective",
                   help="collective (on-device) | loopback | tcp")
    p.add_argument("--lr", type=float, default=0.03)
    p.add_argument("--wd", type=float, default=1e-4)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--comm_round", type=int, default=10)
    # fedlint: disable=P3(reference-parity flag: the FedML launch scripts pass it; nothing in the JAX port branches on mobile clients)
    p.add_argument("--is_mobile", type=int, default=0)
    p.add_argument("--frequency_of_the_test", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ci", type=int, default=0)
    # server optimizer family (main_fedopt.py:54-66)
    p.add_argument("--server_optimizer", type=str, default="sgd")
    p.add_argument("--server_lr", type=float, default=1.0)
    p.add_argument("--server_momentum", type=float, default=0.9)
    # fedprox
    p.add_argument("--fedprox_mu", type=float, default=0.1)
    # robust (main_fedavg_robust.py; --attack_freq is the reference's
    # poisoned-worker cadence flag, main_fedavg_robust.py:120)
    p.add_argument("--norm_bound", type=float, default=5.0)
    p.add_argument("--stddev", type=float, default=0.0)
    p.add_argument("--attack_freq", type=int, default=0)
    p.add_argument("--attack_num_adversaries", type=int, default=1)
    # Byzantine-robust aggregation + device-side corruption drill (new
    # capability beyond the reference's clip+noise; docs/ROBUSTNESS.md)
    p.add_argument("--aggregator", type=str, default="mean",
                   help="server aggregation: mean | coord_median | "
                        "trimmed_mean<beta> | krum<f> | "
                        "multi_krum<f>-<m> | geometric_median<iters>")
    p.add_argument("--corrupt_mode", type=str, default="none",
                   choices=["none", "sign_flip", "scale", "nan", "random"],
                   help="device-side update corruption by the adversary "
                        "clients (FedAvgRobust attack drill)")
    p.add_argument("--corrupt_scale", type=float, default=10.0,
                   help="corruption magnitude for sign_flip/scale/random")
    # hierarchical (hierarchical_fl/main.py)
    p.add_argument("--group_comm_round", type=int, default=1)
    p.add_argument("--group_num", type=int, default=2)
    # fed_launch extras (fed_launch/main.py:148-165)
    p.add_argument("--lr_schedule", type=str, default="none",
                   help="none | cosine | step")
    p.add_argument("--lr_decay_rate", type=float, default=0.992)
    p.add_argument("--grad_clip", type=float, default=0.0,
                   help="max grad norm; 0 disables")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize activations in backprop (less HBM)")
    # mesh / sharding (TPU-native replacement for gpu_mapping yaml)
    p.add_argument("--num_devices", type=int, default=0,
                   help="shard clients over this many devices; 0 = single-device vmap")
    # observability (fedml_tpu.obs; the reference hard-wires wandb instead)
    p.add_argument("--run_dir", type=str, default=None,
                   help="directory for metrics.jsonl + checkpoints")
    p.add_argument("--checkpoint_frequency", type=int, default=0,
                   help="save full run state every N rounds; 0 disables "
                        "(also cfg.checkpoint_every for the distributed "
                        "server's crash-resume checkpoints)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --run_dir "
                        "(with --checkpoint_frequency the distributed "
                        "server auto-resumes on restart — that is the "
                        "crash-resume contract: rerunning the same "
                        "command continues the run; this flag arms "
                        "restore when checkpointing itself is off, or a "
                        "fresh run needs a clean --run_dir)")
    # Async / buffered serving tiers (algos/fedasync.py, algos/fedbuff.py;
    # docs/ROBUSTNESS.md "Serving under churn"). Read only by the
    # message-passing FedAsync/FedBuff runners — every other main refuses
    # a non-default value via reject_async_tier_flags.
    p.add_argument("--fedasync_alpha", type=float, default=-1.0,
                   help="async mixing rate / fedbuff server step size; "
                        "< 0 keeps the tier default (0.6 async, 1.0 "
                        "fedbuff)")
    p.add_argument("--staleness_exp", type=float, default=0.5,
                   help="polynomial staleness-discount exponent a in "
                        "1/(1+s)^a (fedasync mixing, fedbuff buffer "
                        "weights)")
    p.add_argument("--buffer_k", type=int, default=2,
                   help="fedbuff: aggregate every k accepted arrivals "
                        "(the semi-sync buffer depth)")
    # Distributed control plane (docs/ROBUSTNESS.md "Control plane";
    # read only by the message-passing federations)
    p.add_argument("--round_timeout_s", type=float, default=0.0,
                   help="distributed server: abandon a round after this "
                        "many seconds by evicting the silent ranks and "
                        "aggregating over the survivors (0 = wait forever)")
    p.add_argument("--heartbeat_interval_s", type=float, default=0.0,
                   help="distributed workers: liveness beat cadence while "
                        "training long rounds (0 = uploads only)")
    p.add_argument("--trace", action="store_true",
                   help="federation flight recorder (obs/trace.py): dump "
                        "upload-lifecycle spans as Perfetto-loadable "
                        "Chrome trace JSON + JSONL into --run_dir "
                        "(required), plus the control-plane flight-"
                        "recorder ring on eviction/abort/codec refusal; "
                        "off = strict no-op (docs/OBSERVABILITY.md)")
    p.add_argument("--wandb_project", type=str, default=None)
    p.add_argument("--client_selection", type=str, default="random",
                   choices=["random", "pow_d", "oort"],
                   help="client sampling: uniform (reference parity), "
                        "Power-of-Choice loss-biased selection, or Oort "
                        "epsilon-greedy utility selection")
    p.add_argument("--pow_d_candidates", type=int, default=0,
                   help="pow_d candidate pool size (0 = 2x clients/round)")
    p.add_argument("--oort_epsilon", type=float, default=0.2,
                   help="oort explore fraction per round")
    p.add_argument("--oort_staleness_coef", type=float, default=0.1,
                   help="oort staleness bonus weight")
    p.add_argument("--compress", type=str, default="none",
                   help="update compression. Simulator rounds: none | "
                        "topk<ratio> (on-device, inside the jitted "
                        "round). Cross-silo CLI: none | topk<ratio> "
                        "(wire-level with error feedback) | q<bits> "
                        "(stochastic quantization)")
    p.add_argument("--wire_codec", type=str, default="none",
                   help="negotiated wire codec for message-passing "
                        "uploads (cross-silo / FedAsync / FedBuff): none "
                        "| bf16 | fp16 | int8 | topk<ratio> | "
                        "randmask<ratio>, composable as sparsifier+value "
                        "(e.g. topk0.01+int8). Sparsifiers carry "
                        "per-client error feedback; falls back loudly "
                        "against a codec-ignorant peer (comm/codec.py)")
    p.add_argument("--ingest_workers", type=int, default=0,
                   help="parallel server-ingest pool for the message-"
                        "passing tiers (cross-silo / FedAsync / FedBuff, "
                        "comm/ingest.py): N decode+fold worker threads "
                        "pull codec decode and the mean accumulator fold "
                        "off the server's dispatch thread; per-worker "
                        "fixed-point partials merge associative-exactly, "
                        "so any N is bit-equal to N=1. 0 (default) keeps "
                        "the inline fold; mean aggregation only — "
                        "non-mean --aggregator combos refuse loudly")
    p.add_argument("--agg_shards", type=int, default=0,
                   help="sharded aggregation plane for the loopback "
                        "cross-silo runner (comm/shardplane.py): M "
                        "aggregator-shard processes each ingest their "
                        "own client partition (full codec negotiation + "
                        "ingest pool), and the rank-0 coordinator wire-"
                        "merges their int64 fixed-point partials "
                        "BIT-EQUAL to the single-process pool for any "
                        "M. Sync FedAvg + mean aggregation only; 0 "
                        "(default) keeps the single-server ingest path")
    p.add_argument("--secagg", action="store_true",
                   help="dropout-robust secure aggregation "
                        "(comm/secagg.py): pairwise seed-expanded masks "
                        "over the fixed-point int64 uploads cancel "
                        "exactly in the pooled fold, so the server only "
                        "materializes the sum; an eviction triggers a "
                        "t-of-n Shamir seed reveal that subtracts the "
                        "orphaned masks. Sync FedAvg + mean aggregation "
                        "only; needs --ingest_workers > 0 or "
                        "--agg_shards > 0")
    p.add_argument("--secagg_t", type=int, default=0,
                   help="Shamir reveal threshold: survivors needed to "
                        "reconstruct an evicted rank's mask seeds "
                        "(0 = majority of the handshake roster)")
    p.add_argument("--compute_layout", type=str, default="none",
                   help="lane-fill compute layout for the client step: "
                        "none | auto (pad channel dims to MXU lane/"
                        "sublane multiples inside the jitted step; "
                        "logical shapes everywhere else — "
                        "docs/EXECUTION.md MFU playbook) | im2col "
                        "(rephrase the 5x5 stem conv as patches + a 1x1 "
                        "conv — conv lane shaping beyond s2d, "
                        "CNNOriginalFedAvg only)")
    p.add_argument("--client_step_dtype", type=str, default="fp32",
                   help="client-step COMPUTE dtype: fp32 (default) | "
                        "bf16 — layer compute in bfloat16 inside the "
                        "jitted client step; params, gradients, "
                        "optimizer, aggregation and server carry stay "
                        "fp32 (docs/EXECUTION.md MFU playbook)")
    p.add_argument("--group_reduce", action="store_true",
                   help="hierarchical sparse reduction on a client mesh "
                        "(cfg.group_reduce): group-composable "
                        "aggregators aggregate per shard — per HOST on "
                        "a --dcn_hosts pod mesh, ICI-only stage 1 — "
                        "then across the G group partials; "
                        "non-composable aggregators refuse loudly")
    p.add_argument("--dcn_hosts", type=int, default=0,
                   help="shard clients over a DCN×ICI pod mesh: "
                        "num_devices splits as dcn_hosts × "
                        "(num_devices/dcn_hosts) with client groups "
                        "pinned per host (hierarchical group reduction, "
                        "docs/PLATFORMS.md Multi-host; single-process "
                        "runs force the factorization). 0 = flat mesh")
    p.add_argument("--eval_on_clients", action="store_true",
                   help="per-client eval of the global model each eval "
                        "round (reference _local_test_on_all_clients "
                        "cadence; adds worst-client metrics)")
    p.add_argument("--ditto_lam", type=float, default=0.1,
                   help="Ditto proximal strength λ (personal ↔ global "
                        "trade-off; --algorithm Ditto)")
    p.add_argument("--feddyn_alpha", type=float, default=0.01,
                   help="FedDyn dynamic-regularization strength "
                        "(--algorithm FedDyn)")
    p.add_argument("--qffl_q", type=float, default=1.0,
                   help="q-FedAvg fairness exponent (0 = equal-weight "
                        "FedAvg; --algorithm QFedAvg)")
    p.add_argument("--fedac_gamma", type=float, default=2.0,
                   help="FedAc acceleration γ in units of the round's "
                        "local progress (1 = FedAvg; --algorithm FedAc)")
    p.add_argument("--server_avg_coef", type=float, default=0.5,
                   help="server-averaging mix β toward the running mean "
                        "of past globals (0 = FedAvg; --algorithm "
                        "ServerAvg)")
    p.add_argument("--adapter_rank", type=int, default=0,
                   help="frozen-base adapter finetuning (FedAdapter / "
                        "the async tiers' adapter-delta uploads): rank "
                        "of the LoRA pairs injected next to the "
                        "transformer's scoped dense projections; 0 "
                        "(default) trains the dense model. Drivers that "
                        "never read it refuse loudly "
                        "(reject_adapter_flags)")
    p.add_argument("--adapter_scope", type=str, default="attn",
                   choices=["attn", "mlp", "all"],
                   help="which projections get adapter pairs: attention "
                        "qkv+out, the MLP pair, or both")
    p.add_argument("--dp_clip", type=float, default=0.0,
                   help="example-level DP-SGD: per-example grad L2 clip "
                        "(0 disables DP)")
    p.add_argument("--dp_noise_multiplier", type=float, default=0.0,
                   help="DP-SGD Gaussian noise std = multiplier * dp_clip")
    p.add_argument("--sweep_pipe", type=str, default=None,
                   help="named pipe to post a completion line to when the "
                        "run finishes (sweep orchestrator handshake, "
                        "reference fedavg/utils.py:19-27)")
    p.add_argument("--synthetic_samples", type=int, default=0,
                   help="override the synthetic-fallback dataset size "
                        "(zero-egress runs); 0 = loader default")
    # MQTT bridge (reference mqtt_comm_manager.py connects to an external
    # broker; used only with --backend MQTT)
    p.add_argument("--mqtt_host", type=str, default="127.0.0.1")
    p.add_argument("--mqtt_port", type=int, default=1883)
    # Multi-tenant adapter serving plane (fedml_tpu.serve; docs/SERVING.md).
    # Only main_extra's FedBuff runner serves — every other driver refuses
    # these loudly (reject_serve_flags).
    p.add_argument("--serve", action="store_true",
                   help="stand up the multi-tenant adapter serving plane "
                        "next to the FedBuff training fleet: batched "
                        "per-request LoRA inference over one frozen-base "
                        "dispatch (requires --adapter_rank > 0 and the "
                        "transformer_lm model)")
    p.add_argument("--serve_port", type=int, default=0,
                   help="TCP port for the line-delimited-JSON serve front "
                        "end (0 = no socket; in-process traffic only)")
    p.add_argument("--serve_max_batch", type=int, default=32,
                   help="micro-batcher batch size: a batch closes when "
                        "this many requests arrived or the deadline "
                        "expired, whichever is first")
    p.add_argument("--serve_deadline_ms", type=float, default=5.0,
                   help="micro-batcher window: max milliseconds the first "
                        "request of a batch waits for co-batching traffic")
    p.add_argument("--serve_requests", type=int, default=0,
                   help="smoke traffic: issue this many in-process serve "
                        "requests DURING training and report latency "
                        "percentiles in the output (0 = none)")
    # Adaptive federation control (fedml_tpu.ctrl; docs/ROBUSTNESS.md
    # "Adaptive control"). Only main_extra's FedAsync/FedBuff runners
    # attach a controller — every other driver refuses these loudly
    # (reject_controller_flags).
    p.add_argument("--controller", type=str, default="none",
                   choices=["none", "adaptive"],
                   help="telemetry-driven federation controller: retunes "
                        "the server's knobs (buffer_k, admission cap, "
                        "timeouts) at safe boundaries from live staleness/"
                        "eviction/accuracy telemetry; 'none' leaves every "
                        "knob static")
    p.add_argument("--controller_interval", type=int, default=1,
                   help="control-step cadence in protocol progress units "
                        "(model versions / rounds) between controller "
                        "steps")
    p.add_argument("--controller_band_lo", type=float, default=2.0,
                   help="staleness-p95 guard band floor: below it the "
                        "admission policy relaxes back toward baseline")
    p.add_argument("--controller_band_hi", type=float, default=6.0,
                   help="staleness-p95 guard band ceiling: above it the "
                        "admission policy backs buffer_k off and arms the "
                        "staleness admission cap")
    return p


def reject_fedavg_family_flags(args, algorithm: str) -> None:
    """Refuse FedAvg-family-only flags for algorithms that never read
    them. ``FedAvgAPI.__init__`` guards its OWN subclasses against a
    silently-dropped ``--aggregator``/``--corrupt_mode``, but the
    specialty mains (FedGAN/GKT/NAS/SplitNN/VFL/decentralized/async…)
    construct classes outside that family — without this driver-level
    check the user would believe a Byzantine defense or attack drill is
    active while nothing reads the flag (docs/ROBUSTNESS.md)."""
    bad = []
    if getattr(args, "aggregator", "mean") != "mean":
        bad.append(f"--aggregator {args.aggregator}")
    if getattr(args, "corrupt_mode", "none") != "none":
        bad.append(f"--corrupt_mode {args.corrupt_mode}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: robust "
            "aggregation and the corruption drill ride the FedAvg "
            "family's shared rounds only (the flag would be silently "
            "inert here)")


def reject_async_tier_flags(args, algorithm: str, *,
                            allow_mixing: bool = False) -> None:
    """Refuse the async/buffered-tier knobs for runners that never read
    them (same convention as :func:`reject_fedavg_family_flags`): a
    churn drill whose ``--staleness_exp`` silently does nothing is worse
    than one that refuses. ``allow_mixing`` lets FedAsync — which shares
    ``--fedasync_alpha``/``--staleness_exp`` with FedBuff but has no
    buffer — still refuse a stray ``--buffer_k``."""
    bad = []
    if not allow_mixing:
        if getattr(args, "fedasync_alpha", -1.0) >= 0:
            bad.append(f"--fedasync_alpha {args.fedasync_alpha}")
        if getattr(args, "staleness_exp", 0.5) != 0.5:
            bad.append(f"--staleness_exp {args.staleness_exp}")
    if getattr(args, "buffer_k", 2) != 2:
        bad.append(f"--buffer_k {args.buffer_k}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: staleness "
            "weighting and the arrival buffer belong to the async/"
            "buffered message-passing tiers (FedAsync/FedBuff in "
            "main_extra) — the flag would be silently inert here")


def reject_pod_plane_flags(args, algorithm: str) -> None:
    """Refuse the pod-compute-plane knobs for runners that never read
    them (the PR 4 flag-rejection convention): the bf16 client step and
    the DCN×ICI group reduction ride the FedAvg family's shared round
    builders (exp/run.py); a specialty loop that silently trains fp32
    under ``--client_step_dtype bf16``, or flat under ``--group_reduce``,
    would report the baseline as the optimized arm."""
    bad = []
    if getattr(args, "client_step_dtype", "fp32") not in ("fp32", ""):
        bad.append(f"--client_step_dtype {args.client_step_dtype}")
    if getattr(args, "group_reduce", False):
        bad.append("--group_reduce")
    if getattr(args, "dcn_hosts", 0):
        bad.append(f"--dcn_hosts {args.dcn_hosts}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: the pod "
            "compute plane (bf16 client step, DCN×ICI group reduction) "
            "rides the FedAvg family's shared rounds only (the flag "
            "would be silently inert here)")


def reject_adapter_flags(args, algorithm: str) -> None:
    """Refuse the frozen-base adapter knobs for drivers that never read
    them (the PR 4/14 flag-rejection convention): ``--adapter_rank`` /
    ``--adapter_scope`` configure the LoRA finetune (``FedAdapter`` in
    exp/run.py; the FedAsync/FedBuff runners' adapter-delta uploads via
    ``cfg.adapter_rank``). A specialty driver that silently trained the
    DENSE arm under them would report the wrong experiment — the exact
    baseline-as-treated-arm drift this convention exists to refuse."""
    bad = []
    if getattr(args, "adapter_rank", 0):
        bad.append(f"--adapter_rank {args.adapter_rank}")
    if getattr(args, "adapter_scope", "attn") != "attn":
        bad.append(f"--adapter_scope {args.adapter_scope}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: frozen-base "
            "adapter finetuning rides FedAdapter (exp/run.py) and the "
            "FedAsync/FedBuff adapter-delta uploads only — the flag "
            "would silently train the dense arm here")


def reject_serve_flags(args, algorithm: str) -> None:
    """Refuse the serving-plane knobs for drivers that never stand up a
    plane (the PR 4/14 flag-rejection convention): only main_extra's
    FedBuff runner serves (``fedml_tpu.serve``; docs/SERVING.md). A run
    whose ``--serve_requests`` silently does nothing would report a
    training-only run as a serving benchmark — the flag must refuse,
    not no-op."""
    bad = []
    if getattr(args, "serve", False):
        bad.append("--serve")
    if getattr(args, "serve_port", 0):
        bad.append(f"--serve_port {args.serve_port}")
    if getattr(args, "serve_max_batch", 32) != 32:
        bad.append(f"--serve_max_batch {args.serve_max_batch}")
    if getattr(args, "serve_deadline_ms", 5.0) != 5.0:
        bad.append(f"--serve_deadline_ms {args.serve_deadline_ms}")
    if getattr(args, "serve_requests", 0):
        bad.append(f"--serve_requests {args.serve_requests}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: the "
            "multi-tenant adapter serving plane rides main_extra's "
            "FedBuff runner only (fedml_tpu.serve) — the flag would be "
            "silently inert here")


def reject_controller_flags(args, algorithm: str) -> None:
    """Refuse the adaptive-controller knobs for drivers with no actuation
    seam to attach a controller to (the PR 4 flag-rejection convention):
    only main_extra's FedAsync/FedBuff runners wire
    ``controller_from_args`` through to the server manager. A churn run
    whose ``--controller adaptive`` silently did nothing would report
    static behavior as the self-tuning arm — the flag must refuse, not
    no-op."""
    bad = []
    if getattr(args, "controller", "none") != "none":
        bad.append(f"--controller {args.controller}")
    if getattr(args, "controller_interval", 1) != 1:
        bad.append(f"--controller_interval {args.controller_interval}")
    if getattr(args, "controller_band_lo", 2.0) != 2.0:
        bad.append(f"--controller_band_lo {args.controller_band_lo}")
    if getattr(args, "controller_band_hi", 6.0) != 6.0:
        bad.append(f"--controller_band_hi {args.controller_band_hi}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: the adaptive "
            "federation controller (fedml_tpu.ctrl) attaches to the "
            "FedAsync/FedBuff server managers in main_extra only — the "
            "flag would be silently inert here")


def reject_ingest_pool_flag(args, algorithm: str) -> None:
    """Refuse ``--ingest_workers`` for runners with no message-passing
    server dispatch thread to parallelize (the PR 4/6 flag-rejection
    convention): a serving drill whose pool flag silently does nothing
    would report the baseline as the optimized arm. The cross-silo CLI
    and main_extra's FedAsync/FedBuff are the tiers that read it; the
    non-mean ``--aggregator`` combination is refused by the server
    managers themselves (the robust stack-then-reduce path is
    inherently serialized)."""
    if getattr(args, "ingest_workers", 0):
        raise SystemExit(
            f"{algorithm} does not support --ingest_workers "
            f"{args.ingest_workers}: the parallel ingest pool unblocks a "
            "message-passing server's dispatch thread (cross-silo / "
            "FedAsync / FedBuff, comm/ingest.py) — the flag would be "
            "silently inert here")


def reject_agg_shards_flag(args, algorithm: str) -> None:
    """Refuse ``--agg_shards`` wherever the sharded aggregation plane
    cannot run (same convention as :func:`reject_ingest_pool_flag`):
    the simulator tiers have no server processes to shard, and the
    async tiers' server managers additionally refuse ``cfg.agg_shards``
    themselves (their mix is order-dependent, algos/fedasync.py)."""
    if getattr(args, "agg_shards", 0):
        raise SystemExit(
            f"{algorithm} does not support --agg_shards "
            f"{args.agg_shards}: the sharded aggregation plane stands up "
            "M aggregator-shard processes for the synchronous message-"
            "passing federation (comm/shardplane.py) — the flag would "
            "be silently inert here")


def reject_secagg_flags(args, algorithm: str) -> None:
    """Refuse the secure-aggregation knobs wherever the masked protocol
    cannot run (the PR 4 flag-rejection convention): secagg needs the
    synchronous message-passing federation's roster-complete rounds and
    the fixed-point ingest pool (comm/secagg.py rides comm/ingest.py).
    A drill whose ``--secagg`` silently does nothing would report a
    CLEAR-upload run as a privacy experiment — the worst possible
    silent-inert flag; it must refuse. The async tiers' server managers
    additionally refuse ``cfg.secagg`` themselves (algos/fedasync.py:
    no roster-complete cohort sum for the masks to cancel in)."""
    bad = []
    if getattr(args, "secagg", False):
        bad.append("--secagg")
    if getattr(args, "secagg_t", 0):
        bad.append(f"--secagg_t {args.secagg_t}")
    if bad:
        raise SystemExit(
            f"{algorithm} does not support {', '.join(bad)}: secure "
            "aggregation rides the sync cross-silo tier's fixed-point "
            "ingest pool and roster-complete rounds (comm/secagg.py) — "
            "a silently-inert privacy flag would report clear uploads "
            "as a masked run")


def trace_dir_from(args) -> "str | None":
    """Resolve ``--trace`` into the runners' ``trace_dir``: the run
    directory when tracing is on (refusing loudly without one — trace
    artifacts need somewhere to land), else ``None`` (the strict no-op
    path)."""
    if not getattr(args, "trace", False):
        return None
    if not getattr(args, "run_dir", None):
        raise SystemExit(
            "--trace needs --run_dir: the Chrome trace JSON, span JSONL "
            "and flight-recorder dump land there (docs/OBSERVABILITY.md)")
    return args.run_dir


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="fedml_tpu experiment")
    add_args(parser)
    return parser.parse_args(argv)


def config_from_args(args: argparse.Namespace) -> FedConfig:
    return FedConfig(
        client_num_in_total=args.client_num_in_total,
        client_num_per_round=args.client_num_per_round,
        comm_round=args.comm_round,
        epochs=args.epochs,
        batch_size=args.batch_size,
        client_optimizer=args.client_optimizer,
        lr=args.lr,
        wd=args.wd,
        frequency_of_the_test=args.frequency_of_the_test,
        seed=args.seed,
        server_optimizer=args.server_optimizer,
        server_lr=args.server_lr,
        server_momentum=args.server_momentum,
        fedprox_mu=args.fedprox_mu,
        robust_norm_bound=args.norm_bound,
        robust_stddev=args.stddev,
        attack_freq=args.attack_freq,
        attack_num_adversaries=args.attack_num_adversaries,
        aggregator=args.aggregator,
        corrupt_mode=args.corrupt_mode,
        corrupt_scale=args.corrupt_scale,
        group_comm_round=args.group_comm_round,
        lr_schedule=args.lr_schedule,
        lr_decay_rate=args.lr_decay_rate,
        grad_clip=args.grad_clip,
        remat=args.remat,
        dp_clip=args.dp_clip,
        dp_noise_multiplier=args.dp_noise_multiplier,
        compute_layout=args.compute_layout,
        client_step_dtype=args.client_step_dtype,
        adapter_rank=int(getattr(args, "adapter_rank", 0) or 0),
        adapter_scope=getattr(args, "adapter_scope", "attn"),
        group_reduce=bool(getattr(args, "group_reduce", False)),
        client_selection=args.client_selection,
        pow_d_candidates=args.pow_d_candidates,
        oort_epsilon=args.oort_epsilon,
        oort_staleness_coef=args.oort_staleness_coef,
        compress=args.compress,
        wire_codec=args.wire_codec,
        checkpoint_every=args.checkpoint_frequency,
        round_timeout_s=args.round_timeout_s,
        heartbeat_interval_s=args.heartbeat_interval_s,
        ingest_workers=args.ingest_workers,
        agg_shards=int(getattr(args, "agg_shards", 0) or 0),
        secagg=bool(getattr(args, "secagg", False)),
        secagg_t=int(getattr(args, "secagg_t", 0) or 0),
        trace=args.trace,
    )
