"""Experiment mains for the non-FedAvg-family algorithms — the L4 entries the
reference keeps under ``fedml_experiments/distributed/{fedgan,fedgkt,fednas,
split_nn,classical_vertical_fl,base,decentralized_demo}`` and
``fedml_experiments/standalone/{decentralized,hierarchical_fl}``.

Each ``run_<algo>`` wires args → data → models → API with the reference's
defaults; the module is executable:

    python -m fedml_tpu.exp.main_extra --algorithm FedGAN --comm_round 5 ...
"""

from __future__ import annotations

import argparse
import json
import logging

import numpy as np

from fedml_tpu.exp.args import (add_args, config_from_args,
                                reject_adapter_flags,
                                reject_agg_shards_flag,
                                reject_async_tier_flags,
                                reject_controller_flags,
                                reject_fedavg_family_flags,
                                reject_ingest_pool_flag,
                                reject_pod_plane_flags,
                                reject_secagg_flags,
                                reject_serve_flags)
from fedml_tpu.exp.setup import global_test_batches, load_data
from fedml_tpu.data.loaders import to_federated_arrays


def _setup(args):
    fed = load_data(args)
    arrays = to_federated_arrays(fed, args.batch_size)
    test = global_test_batches(fed, args.batch_size)
    cfg = config_from_args(args)
    cfg.client_num_in_total = fed.client_num
    cfg.client_num_per_round = min(cfg.client_num_per_round, fed.client_num)
    return fed, arrays, test, cfg


def run_fedgan(args):
    """main_fedgan.py parity: federated GAN on image data."""
    from fedml_tpu.algos import FedGanAPI
    from fedml_tpu.models import create_model

    _, arrays, _, cfg = _setup(args)
    api = FedGanAPI(create_model("mnist_gan"), arrays, cfg)
    return _loop(api, cfg)


def run_fedgkt(args):
    """main_fedgkt.py parity: small client CNN + big server net distillation."""
    from fedml_tpu.algos import FedGKTAPI
    from fedml_tpu.models import create_model

    fed, arrays, test, cfg = _setup(args)
    # --ci shrinks the model pair (the reference's CI flag exists to cut
    # compute the same way, FedAVGAggregator.py:127-132).
    server_name = "resnet20_server" if args.ci else "resnet56_server"
    client = create_model("resnet5_56", num_classes=fed.class_num)
    server = create_model(server_name, num_classes=fed.class_num)
    api = FedGKTAPI(client, server, arrays, test, cfg)
    return _loop(api, cfg)


def run_fednas(args):
    """main_fednas.py parity: federated DARTS search."""
    from fedml_tpu.algos import FedNASAPI
    from fedml_tpu.models import create_model

    fed, arrays, test, cfg = _setup(args)
    model = create_model("darts", num_classes=fed.class_num, c=8, layers=4)
    api = FedNASAPI(model, arrays, test, cfg)
    hist = _loop(api, cfg)
    logging.info("searched genotype: %s", api.genotype())
    return hist


def run_split_nn(args):
    """main_split_nn.py parity: relay-ring split learning. SplitNN is
    epoch-structured (one relay cycle per epoch), so --epochs drives it."""
    from fedml_tpu.algos import SplitNNAPI
    from fedml_tpu.models import create_model

    fed, arrays, test, cfg = _setup(args)
    server_name = "resnet20_server" if args.ci else "resnet56_server"
    client = create_model("resnet_split_bottom")
    server = create_model(server_name, num_classes=fed.class_num)
    api = SplitNNAPI(client, server, arrays, test, cfg)
    history = []
    for e in range(cfg.epochs):
        metrics = api.train_one_epoch(e)
        if e == cfg.epochs - 1:
            metrics.update(api.evaluate())
        logging.info(json.dumps(metrics))
        history.append(metrics)
    return history


def run_vfl(args):
    """main_vfl.py parity: two-party vertical FL on NUS-WIDE-shaped data."""
    from fedml_tpu.algos import VflAPI
    from fedml_tpu.data.loaders import load_two_party_nus_wide

    (xa, xb, y), (xat, xbt, yt) = load_two_party_nus_wide(
        data_dir=args.data_dir, n_samples=max(args.batch_size * 20, 500))
    api = VflAPI([xa.shape[1], xb.shape[1]], lr=args.lr)
    history = []
    for epoch in range(args.comm_round):
        losses = api.fit([xa, xb], y, epochs=1, batch_size=args.batch_size)
        metrics = {"round": epoch, "train_loss": float(np.mean(losses))}
        if epoch == args.comm_round - 1:
            metrics.update(api.evaluate([xat, xbt], yt))
        logging.info(json.dumps(metrics))
        history.append(metrics)
    return history


def run_decentralized(args):
    """main_dol.py / decentralized_demo parity: gossip DSGD or PushSum."""
    from fedml_tpu.algos import DecentralizedAPI
    from fedml_tpu.core.topology import SymmetricTopologyManager
    from fedml_tpu.models import create_model

    fed, arrays, test, cfg = _setup(args)
    topo = SymmetricTopologyManager(fed.client_num, neighbor_num=2)
    x0 = fed.train_data_global[0][0]
    model = create_model(
        "lr", num_classes=fed.class_num,
        input_dim=int(np.prod(np.asarray(x0).shape[1:])))
    api = DecentralizedAPI(model, arrays, test, cfg, topo,
                           mode=getattr(args, "dol_mode", "dsgd"))
    return _loop(api, cfg)


def _async_loss_kwargs(args):
    """Sequence-dataset loss for the async runners (run.py's make_api
    wiring, which these CLI paths bypass): without it a transformer_lm +
    shakespeare worker dies on the classification CE's label shape and
    the federation deadlocks waiting for its uploads."""
    from fedml_tpu.exp.run import SEQ_DATASETS

    if args.dataset not in SEQ_DATASETS:
        return {}
    from functools import partial

    from fedml_tpu.trainer.local import seq_softmax_ce

    pad_id = -1 if args.dataset == "shakespeare" else 0
    return {"loss_fn": partial(seq_softmax_ce, pad_id=pad_id)}


def _async_obs_kwargs(args):
    """Shared --run_dir/--trace wiring for the async-tier runners: a
    metrics.jsonl ctrl/ stream per model version (the same schema the
    sync server logs per round) and the flight recorder / span tracer.
    Returns ``(kwargs, metrics_logger_or_None)`` — the caller closes the
    logger after the run."""
    from fedml_tpu.exp.args import trace_dir_from

    metrics = None
    if getattr(args, "run_dir", None):
        from fedml_tpu.obs import MetricsLogger

        metrics = MetricsLogger.for_run(run_dir=args.run_dir, stdout=False)
    return {"metrics": metrics, "trace_dir": trace_dir_from(args)}, metrics


def run_fedasync(args):
    """Asynchronous FL (no barrier; staleness-weighted mixing) over the
    loopback message-passing backend — new capability, fedasync.py."""
    from fedml_tpu.algos.fedasync import FedML_FedAsync_distributed
    from fedml_tpu.exp.setup import create_model_for

    fed, arrays, test, cfg = _setup(args)
    model = create_model_for(args, fed)
    obs_kw, metrics = _async_obs_kwargs(args)
    from fedml_tpu.ctrl import controller_from_args

    try:
        srv = FedML_FedAsync_distributed(
            model, arrays, test, cfg,
            alpha=(0.6 if args.fedasync_alpha < 0 else args.fedasync_alpha),
            staleness_exp=args.staleness_exp, wire_codec=args.wire_codec,
            controller=controller_from_args(args),
            **_async_loss_kwargs(args), **obs_kw)
    finally:
        if metrics is not None:
            metrics.close()
    logging.info("fedasync staleness history: %s", srv.staleness_history)
    return srv.test_history or [{"version": srv.version}]


def run_fedbuff(args):
    """Buffered semi-sync FL (aggregate every ``--buffer_k`` arrivals
    with polynomial staleness discounting) — fedbuff.py. Composes with
    ``--aggregator`` (robust buffer reduction) and ``--corrupt_mode``
    (the first ``--attack_num_adversaries`` worker ranks turn
    Byzantine), so churn and Byzantine drills run from one CLI."""
    from fedml_tpu.algos.fedbuff import FedML_FedBuff_distributed
    from fedml_tpu.core.faults import UpdateCorruptor
    from fedml_tpu.exp.setup import create_model_for

    fed, arrays, test, cfg = _setup(args)
    model = create_model_for(args, fed)
    corruptor = None
    corrupt_ranks = ()
    if args.corrupt_mode != "none":
        corruptor = UpdateCorruptor(args.corrupt_mode, args.corrupt_scale,
                                    seed=cfg.seed)
        corrupt_ranks = tuple(range(1, 1 + args.attack_num_adversaries))
    obs_kw, metrics = _async_obs_kwargs(args)
    from fedml_tpu.ctrl import controller_from_args

    try:
        srv = FedML_FedBuff_distributed(
            model, arrays, test, cfg,
            alpha=(1.0 if args.fedasync_alpha < 0 else args.fedasync_alpha),
            staleness_exp=args.staleness_exp, buffer_k=args.buffer_k,
            aggregator=args.aggregator, wire_codec=args.wire_codec,
            corrupt_ranks=corrupt_ranks, corruptor=corruptor,
            controller=controller_from_args(args),
            **_async_loss_kwargs(args), **obs_kw)
    finally:
        if metrics is not None:
            metrics.close()
    logging.info("fedbuff staleness history: %s (guard_drops=%d)",
                 srv.staleness_history, srv.guard_drops)
    history = srv.test_history or [{"version": srv.version}]
    if getattr(args, "serve", False):
        history[-1] = dict(history[-1], **_serve_fedbuff_global(args, model,
                                                               srv))
    return history


def _serve_fedbuff_global(args, model, srv):
    """Stand up the multi-tenant serving plane (fedml_tpu.serve;
    docs/SERVING.md) on the trained FedBuff global: batched LoRA
    inference over the run's frozen base, ``--serve_requests`` smoke
    traffic through the micro-batcher, optional ``--serve_port`` JSON
    socket. Returns flat serve_* scalars for the output line."""
    from fedml_tpu.models.adapter import adapter_model_fns
    from fedml_tpu.serve import (AdapterDecoder, ServeForward, ServeManager,
                                 ServeSocketServer)

    holder = getattr(srv, "adapter_holder", None)
    if not holder or "base" not in holder:
        raise SystemExit(
            "--serve needs the frozen-base adapter run: pass "
            "--adapter_rank > 0 with --model transformer_lm (the serving "
            "plane batches per-request LoRA deltas over one frozen base)")
    fns = adapter_model_fns(model, holder=holder)
    glob = srv.net.params
    fwd = ServeForward(fns, glob)
    dec = AdapterDecoder(model, fns, glob)
    seq_len = min(int(getattr(model, "max_len", 32)), 32)
    vocab = int(getattr(model, "vocab_size", 64))
    mgr = ServeManager(fwd, None, glob, seq_len=seq_len,
                       max_batch=args.serve_max_batch,
                       deadline_s=args.serve_deadline_ms / 1e3,
                       decoder=dec).start()
    sock = None
    try:
        if args.serve_port:
            sock = ServeSocketServer(mgr, args.serve_port).start()
            logging.info("serve socket listening on 127.0.0.1:%d", sock.port)
        rng = np.random.default_rng(0)
        pending = []
        for i in range(int(args.serve_requests)):
            toks = rng.integers(0, vocab,
                                size=int(rng.integers(1, seq_len + 1)))
            pending.append(mgr.submit(i, toks.astype(np.int32),
                                      max_new_tokens=2))
            if len(pending) >= 64:
                for r in pending:
                    r.result(120)
                pending.clear()
        for r in pending:
            r.result(120)
        stats = mgr.stats()
    finally:
        if sock is not None:
            sock.close()
        mgr.close()
    return {k.replace("/", "_"): v for k, v in stats.items()
            if isinstance(v, (int, float))}


def run_base_framework(args):
    """main_base.py parity: the didactic scalar-sum message-passing demo over
    the loopback backend (local result = rank + round)."""
    from fedml_tpu.algos.base_framework import FedML_Base_distributed

    worker_num = max(2, args.client_num_per_round)
    results = FedML_Base_distributed(
        worker_num, args.comm_round,
        local_fn=lambda round_idx, _global: float(round_idx + 1))
    logging.info("base framework per-round aggregates: %s", results)
    return [{"round": i, "aggregate": float(r)} for i, r in enumerate(results)]


def _loop(api, cfg):
    history = []
    for r in range(cfg.comm_round):
        metrics = api.train_one_round(r)
        if hasattr(api, "evaluate") and (
            r % cfg.frequency_of_the_test == 0 or r == cfg.comm_round - 1
        ):
            metrics.update(api.evaluate())
        logging.info(json.dumps({k: v for k, v in metrics.items()
                                 if isinstance(v, (int, float))}))
        history.append(metrics)
    return history


RUNNERS = {
    "FedAsync": run_fedasync,
    "FedBuff": run_fedbuff,
    "FedGAN": run_fedgan,
    "FedGKT": run_fedgkt,
    "FedNAS": run_fednas,
    "SplitNN": run_split_nn,
    "VFL": run_vfl,
    "Decentralized": run_decentralized,
    "BaseFramework": run_base_framework,
}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--algorithm", type=str, required=True,
                        choices=sorted(RUNNERS))
    parser.add_argument("--dol_mode", type=str, default="dsgd",
                        help="Decentralized only: dsgd | pushsum")
    add_args(parser)
    args = parser.parse_args(argv)
    # FedBuff composes with the robust aggregator + corruption drill
    # (buffered ingest reduces through core/robust_agg); every other
    # specialty algorithm must refuse those flags, not no-op. The
    # async-tier knobs are read by FedAsync/FedBuff only.
    if args.algorithm != "FedBuff":
        reject_fedavg_family_flags(args, args.algorithm)
        reject_async_tier_flags(args, args.algorithm,
                                allow_mixing=args.algorithm == "FedAsync")
        # Only the FedBuff runner stands up the serving plane
        # (fedml_tpu.serve) — every other specialty loop refuses the
        # serve knobs rather than silently training without serving.
        reject_serve_flags(args, args.algorithm)
    elif not getattr(args, "serve", False):
        # FedBuff without --serve: the tuning/traffic knobs would be
        # silently inert — same refuse-don't-noop convention.
        reject_serve_flags(args, f"{args.algorithm} without --serve")
    elif not getattr(args, "adapter_rank", 0):
        raise SystemExit(
            "--serve needs --adapter_rank > 0 (and --model "
            "transformer_lm): the serving plane batches per-request "
            "LoRA deltas over one frozen base (fedml_tpu.serve)")
    if (args.algorithm not in ("FedAsync", "FedBuff")
            and getattr(args, "wire_codec", "none") != "none"):
        raise SystemExit(
            f"{args.algorithm} does not support --wire_codec "
            f"{args.wire_codec}: the negotiated wire codec rides the "
            "message-passing upload path (FedAsync/FedBuff here, or the "
            "cross-silo CLI) — the flag would be silently inert")
    if args.algorithm not in ("FedAsync", "FedBuff"):
        # The parallel ingest pool likewise rides only the message-
        # passing server tiers (FedAsync/FedBuff here; cross-silo CLI).
        reject_ingest_pool_flag(args, args.algorithm)
        # ...as does the adaptive controller (fedml_tpu.ctrl): only the
        # FedAsync/FedBuff runners thread controller_from_args through
        # to the server's actuation seam — anywhere else the flags
        # would label a static run self-tuning.
        reject_controller_flags(args, args.algorithm)
    # The sharded aggregation plane is a synchronous-FedAvg capability
    # (comm/shardplane.py): FedAsync/FedBuff refuse cfg.agg_shards in
    # their server constructors (the sequential mix / global-arrival
    # buffer cannot be partitioned), and every other specialty loop
    # never stands up a message-passing server at all.
    reject_agg_shards_flag(args, args.algorithm)
    # Secure aggregation is likewise sync-FedAvg-only (comm/secagg.py):
    # FedAsync/FedBuff refuse cfg.secagg in their server constructors
    # (no roster-complete cohort sum for the masks to cancel in), and
    # no other specialty loop stands up the masked upload path — refuse
    # at the driver so a "privacy" run can never silently ship clear
    # uploads.
    reject_secagg_flags(args, args.algorithm)
    # The pod compute plane (bf16 client step, DCN group reduction)
    # rides the FedAvg family's shared rounds; every specialty loop
    # refuses here. FedAsync/FedBuff refuse client_step_dtype /
    # group_reduce via the shared distributed-setup CFG guard, but
    # --dcn_hosts never reaches a cfg field (it is consumed by the
    # mesh-building setup these runners skip — the same hole
    # main_cross_silo special-cases), so it must refuse at the driver.
    if args.algorithm in ("FedAsync", "FedBuff"):
        if getattr(args, "dcn_hosts", 0):
            raise SystemExit(
                f"{args.algorithm} does not support --dcn_hosts "
                f"{args.dcn_hosts}: the async tiers shard by rank, not "
                "over a device mesh (the flag would be silently inert)")
        # The async tiers DO run the frozen-base adapter finetune
        # (cfg.adapter_rank via build_federation_setup), but only a
        # transformer model has injection sites — any other model would
        # refuse deep inside adapter_model_fns; name the fix here.
        if (getattr(args, "adapter_rank", 0)
                and args.model != "transformer_lm"):
            raise SystemExit(
                f"--adapter_rank {args.adapter_rank} needs --model "
                f"transformer_lm (got {args.model!r}): adapter "
                "injection lives in models/transformer.py")
    else:
        reject_pod_plane_flags(args, args.algorithm)
        # Non-async specialty loops never read the adapter knobs — the
        # PR 4/14 convention: refuse, don't silently train dense.
        reject_adapter_flags(args, args.algorithm)
    logging.basicConfig(level=logging.INFO,
                        format=f"[{args.algorithm} %(asctime)s] %(message)s")
    history = RUNNERS[args.algorithm](args)
    print(json.dumps({k: v for k, v in history[-1].items()
                      if isinstance(v, (int, float))}))
    return history


if __name__ == "__main__":
    main()
