"""FedOpt entry (fedml_experiments/distributed/fedopt/main_fedopt.py):
FedAvg + server optimizer on the pseudo-gradient; choose with
``--server_optimizer {sgd,adam,yogi,adagrad} --server_lr ...``."""

from fedml_tpu.exp.run import main

if __name__ == "__main__":
    main(algorithm="FedOpt")
