"""Robust FedAvg entry (fedml_experiments/distributed/fedavg_robust/
main_fedavg_robust.py): norm-clipping defense ``--norm_bound`` and weak-DP
noise ``--stddev``."""

from fedml_tpu.exp.run import main

if __name__ == "__main__":
    main(algorithm="FedAvgRobust")
