"""Experiments / CLI layer (the reference's ``fedml_experiments``).

North-star entry (launches with the reference's flags unchanged):

    python -m fedml_tpu.exp.main_fedavg --model resnet56 --dataset cifar10 \
        --partition_method hetero --partition_alpha 0.5 \
        --client_num_in_total 10 --client_num_per_round 10 \
        --batch_size 64 --lr 0.03 --epochs 5 --comm_round 100

Generalized launcher with an ``--algorithm`` switch (fed_launch parity):

    python -m fedml_tpu.exp.run --algorithm FedOpt --server_optimizer adam ...
"""

from fedml_tpu.exp.args import add_args, config_from_args, parse_args
from fedml_tpu.exp.run import run, round_lr
from fedml_tpu.exp.setup import (
    create_model_for,
    global_test_batches,
    load_data,
    setup_standard,
)

__all__ = [
    "add_args",
    "config_from_args",
    "parse_args",
    "run",
    "round_lr",
    "create_model_for",
    "global_test_batches",
    "load_data",
    "setup_standard",
]
