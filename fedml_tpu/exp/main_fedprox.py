"""FedProx entry — with the proximal μ term the reference's fedprox
snapshot silently dropped (SURVEY.md §2.3); set ``--fedprox_mu``."""

from fedml_tpu.exp.run import main

if __name__ == "__main__":
    main(algorithm="FedProx")
