"""Experiment wiring: args → data → model → algorithm API.

The reference's mains each re-implement ``load_data``/``create_model``
switches (main_fedavg.py:133-390); here they are shared functions. The
(model, dataset) → constructor-kwargs mapping reproduces
main_fedavg.py:354-390.
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.data.batching import batch_global
from fedml_tpu.data.loaders import FederatedDataset, load_data as _load_data, to_federated_arrays
from fedml_tpu.models import create_model


def load_data(args) -> FederatedDataset:
    from fedml_tpu.data.loaders import _CIFAR_FAMILY

    kw = {}
    n_synth = getattr(args, "synthetic_samples", 0)
    if n_synth:
        if args.dataset not in _CIFAR_FAMILY:
            import logging

            logging.getLogger(__name__).warning(
                "--synthetic_samples is only honored by the CIFAR-family "
                "loaders; ignored for %s", args.dataset)
        else:
            kw["synthetic_samples"] = n_synth
    return _load_data(
        args.dataset,
        data_dir=args.data_dir,
        partition_method=args.partition_method,
        partition_alpha=args.partition_alpha,
        client_num_in_total=args.client_num_in_total,
        batch_size=args.batch_size,
        **kw,
    )


def create_model_for(args, fed: FederatedDataset):
    """main_fedavg.py:354-390's (model, dataset) switch: lr for
    mnist/stackoverflow_lr, cnn for femnist, resnet18_gn for fed_cifar100,
    rnn for the shakespeares, rnn_stackoverflow for nwp, resnet56/mobilenet
    for the cross-silo CV datasets."""
    name, ds, ncls = args.model, args.dataset, fed.class_num
    x0 = fed.train_data_global[0][0]
    if name == "lr":
        in_dim = int(np.prod(x0.shape[1:]))
        return create_model("lr", num_classes=ncls, input_dim=in_dim)
    if name == "rnn":
        return create_model("rnn", vocab_size=ncls)
    if name == "cnn":
        return create_model("cnn", num_classes=ncls, only_digits=(ds == "mnist"))
    if name == "transformer_lm":
        # The adapter-finetune model (PR 15): vocab from the dataset,
        # max_len from the loaded sequences, LoRA pairs injected when
        # --adapter_rank is on (rank 0 leaves the param tree identical
        # to the dense transformer).
        return create_model(
            "transformer_lm", vocab_size=ncls,
            max_len=max(int(np.asarray(x0).shape[-1]), 32),
            adapter_rank=int(getattr(args, "adapter_rank", 0) or 0),
            adapter_scope=getattr(args, "adapter_scope", "attn"))
    return create_model(name, num_classes=ncls)


def _pooled_batches(batches, batch_size: int):
    if not batches:
        return None
    xs = np.concatenate([b[0] for b in batches])
    ys = np.concatenate([b[1] for b in batches])
    return batch_global(xs, ys, batch_size)


def global_test_batches(fed: FederatedDataset, batch_size: int):
    """Concatenate the global test batches into the on-device
    ``(x, y, mask)`` eval layout."""
    return _pooled_batches(fed.test_data_global, batch_size)


def global_train_batches(fed: FederatedDataset, batch_size: int):
    """Pooled TRAIN set in the same layout — what the centralized baseline
    trains on (the reference pools the non-IID dataset the same way,
    fedml_api/centralized/centralized_trainer.py)."""
    return _pooled_batches(fed.train_data_global, batch_size)


def build_mesh(num_devices: int, dcn_hosts: int = 0):
    if not num_devices:
        if dcn_hosts:
            raise ValueError(
                "--dcn_hosts needs --num_devices (the pod mesh factors "
                "num_devices as dcn_hosts x chips-per-host)")
        return None
    if dcn_hosts:
        if num_devices % dcn_hosts:
            raise ValueError(
                f"--num_devices {num_devices} does not factor over "
                f"--dcn_hosts {dcn_hosts}")
        from fedml_tpu.parallel.multihost import dcn_client_mesh

        return dcn_client_mesh(dcn_hosts, num_devices // dcn_hosts)
    from fedml_tpu.parallel.mesh import client_mesh

    return client_mesh(num_devices)


def setup_standard(args, need_test: bool = True, need_mesh: bool = True):
    """(arrays, test_global, model, cfg, mesh) for the FedAvg-family mains.

    ``need_test=False`` skips concatenating the global test set (client
    ranks of a cross-silo run never evaluate — only rank 0 should pay the
    test-set memory); ``need_mesh=False`` skips device-mesh construction."""
    from fedml_tpu.exp.args import config_from_args

    fed = load_data(args)
    arrays = to_federated_arrays(fed, args.batch_size)
    test = global_test_batches(fed, args.batch_size) if need_test else None
    model = create_model_for(args, fed)
    cfg = config_from_args(args)
    # Clamp sampling like the reference (client_sampling takes min,
    # FedAVGAggregator.py:92).
    cfg.client_num_per_round = min(cfg.client_num_per_round, fed.client_num)
    cfg.client_num_in_total = fed.client_num
    mesh = (build_mesh(args.num_devices, getattr(args, "dcn_hosts", 0))
            if need_mesh else None)
    return fed, arrays, test, model, cfg, mesh
