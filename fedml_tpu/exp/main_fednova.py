"""FedNova entry (fedml_experiments/standalone/fednova/main.py):
normalized averaging over heterogeneous local step counts."""

from fedml_tpu.exp.run import main

if __name__ == "__main__":
    main(algorithm="FedNova")
