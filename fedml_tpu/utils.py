"""Shared utilities (reference fedml_api/utils parity).

- ``raise_error``: contextmanager logging the traceback before re-raising
  (context.py:9-18 ``raise_MPI_error`` — but without the Abort: callers
  decide lifecycle; use HeartbeatMonitor / nan_guard for containment);
- ``get_lock``: contextmanager around a ``threading.Lock`` (context.py:30);
- ``logging_config``: per-rank logging format (utils/logger.py:7,
  main_fedavg.py:411-415);
- ``post_complete_message_to_sweep_process``: fifo signal used by sweep
  drivers (fedavg/utils.py:19-27).
"""

from __future__ import annotations

import contextlib
import logging
import os
import traceback


@contextlib.contextmanager
def raise_error(logger: logging.Logger | None = None):
    try:
        yield
    except Exception:
        (logger or logging.getLogger(__name__)).error(traceback.format_exc())
        raise


@contextlib.contextmanager
def get_lock(lock):
    lock.acquire()
    try:
        yield lock
    finally:
        lock.release()


def logging_config(process_id: int = 0, level=logging.INFO):
    """Per-rank prefixed logging (reference main_fedavg.py:411-415)."""
    logging.basicConfig(
        level=level,
        format=(
            f"[rank {process_id}] %(asctime)s %(levelname)s "
            "%(filename)s:%(lineno)d %(message)s"
        ),
        force=True,
    )


def rss_mb() -> float:
    """CURRENT host RSS in MB (/proc/self/statm — Linux; falls back to
    the getrusage peak elsewhere). Current, not ru_maxrss: the process
    peak is monotone, so point-in-time memory claims (the sharded
    store's flat-RSS story, a sim drill's host-memory axis) need live
    samples. Single-sourced here for bench.py's per-section trajectory
    AND ``sim.FleetResult.summary()``'s host-RSS axis."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE") / 1e6
    except Exception:
        # Non-Linux fallback: ru_maxrss is the MONOTONE process peak
        # (point-in-time claims degenerate toward ratio 1.0 here —
        # Linux is the measured platform), and macOS reports bytes
        # where Linux uses KB.
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak / (1024.0 ** 2 if sys.platform == "darwin" else 1024.0)


def post_complete_message_to_sweep_process(args, pipe_path: str = "./tmp/fedml"):
    """Write a completion line to a fifo so a sweep driver can advance
    (reference fedavg/utils.py:19-27). No-op if the fifo cannot be created."""
    try:
        os.makedirs(os.path.dirname(pipe_path), exist_ok=True)
        if not os.path.exists(pipe_path):
            os.mkfifo(pipe_path)
        fd = os.open(pipe_path, os.O_WRONLY | os.O_NONBLOCK)
        try:
            os.write(fd, f"training is finished! \n{args}\n".encode())
        finally:
            os.close(fd)
    except OSError:
        logging.getLogger(__name__).debug("no sweep fifo reader; skipping")
