"""Fault injection + failure detection.

The reference's entire failure story is ``raise_MPI_error`` → traceback →
``MPI.COMM_WORLD.Abort()`` (fedml_api/utils/context.py:9-18), plus
Turbo-Aggregate's client dropout flag (TA_client.py:25) and the robustness
harness's adversarial clients (main_fedavg_robust.py:82-83). Here those
become framework subsystems:

- ``DropoutInjector`` — per-round Bernoulli client dropout (the TA dropout
  generalized to every algorithm: returns a weight mask);
- ``UpdateCorruptor`` — adversarial/fault update injection for robustness
  testing (sign-flip, gradient-scaling, NaN faults); its
  :meth:`~UpdateCorruptor.device_fn` form is mask-driven and pure, so
  attack-vs-defense drills run INSIDE the jitted rounds on every
  execution tier, windowed scan included (cfg.corrupt_mode via
  FedAvgRobustAPI; docs/ROBUSTNESS.md);
- ``HeartbeatMonitor`` — wall-clock failure detector for the message-passing
  path: ranks check in, anything silent past ``timeout_s`` is reported
  failed instead of hanging the federation;
- the aggregation-side NaN guard lives in fedml_tpu.parallel.shard
  (``nan_guard=True``): a diverged client is zero-weighted, not averaged.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class DropoutInjector:
    """Bernoulli(p) per-round client dropout; seeded and round-keyed so runs
    reproduce (reference TA dropout is a manual list; this simulates churn)."""

    def __init__(self, p: float, seed: int = 0):
        if not 0.0 <= p < 1.0:
            raise ValueError(f"dropout p must be in [0, 1), got {p}")
        self.p = p
        self.seed = seed

    def round_mask(self, round_idx: int, n_clients: int) -> np.ndarray:
        """[n] float mask — 0.0 = dropped this round. Guarantees at least
        one survivor: an all-dropped round would be a silent no-op, so one
        client is revived — drawn UNIFORMLY from the same round-keyed RNG
        (still deterministic per (seed, round)). Always reviving client 0
        would be a systematic participation bias at high dropout rates —
        the same bias class FedAvgRobustAPI's eviction fix addressed
        (algos/robust.py): client 0 would train in every all-dropped
        round while its peers never do."""
        rng = np.random.RandomState((self.seed * 1_000_003 + round_idx) % (2**31))
        mask = (rng.rand(n_clients) >= self.p).astype(np.float32)
        if mask.sum() == 0:
            mask[rng.randint(n_clients)] = 1.0
        return mask


class UpdateCorruptor:
    """Inject faults into a trained client update (NetState pytree) —
    the attack/fault models the robust aggregator defends against."""

    MODES = ("sign_flip", "scale", "nan", "random")

    def __init__(self, mode: str = "sign_flip", scale: float = 10.0, seed: int = 0):
        if mode not in self.MODES:
            raise ValueError(f"unknown corruption mode {mode!r}; known {self.MODES}")
        self.mode = mode
        self.scale = scale
        self.rng = jax.random.PRNGKey(seed)

    def corrupt(self, net, global_net=None):
        """Returns the corrupted pytree (params leaf-wise)."""
        params = net.params if hasattr(net, "params") else net

        if self.mode == "sign_flip":
            # Model replacement: w_g - scale*(w - w_g) if global given, else -w.
            if global_net is not None:
                gp = global_net.params if hasattr(global_net, "params") else global_net
                new = jax.tree.map(lambda w, g: g - self.scale * (w - g), params, gp)
            else:
                new = jax.tree.map(lambda w: -w, params)
        elif self.mode == "scale":
            new = jax.tree.map(lambda w: w * self.scale, params)
        elif self.mode == "nan":
            new = jax.tree.map(
                lambda w: w.at[(0,) * w.ndim].set(jnp.nan) if w.ndim else jnp.nan * w,
                params,
            )
        else:  # random
            self.rng, sub = jax.random.split(self.rng)
            leaves, treedef = jax.tree.flatten(params)
            keys = jax.random.split(sub, len(leaves))
            new = jax.tree.unflatten(
                treedef,
                [self.scale * jax.random.normal(k, l.shape, l.dtype)
                 for k, l in zip(keys, leaves)],
            )
        if hasattr(net, "params"):
            return type(net)(new, net.model_state)
        return new

    def device_fn(self):
        """The device-side, MASK-DRIVEN variant of :meth:`corrupt` for
        the jitted rounds: a pure ``(global_net, client_nets, adv, rngs)
        -> client_nets`` over the CLIENT-STACKED trained models, where
        ``adv [C] > 0`` flags the adversary slots and ``rngs [C]`` are
        per-client streams (consumed by the "random" mode — forked by
        the round builder with a corruptor-reserved fold_in constant,
        ``parallel.shard.run_clients_guarded``).

        Branchless by construction — corruption is computed for every
        client and selected per-slot with ``tree_select`` — so it traces
        into vmap/shard_map and, critically, into the windowed
        ``lax.scan`` body: attack-vs-defense drills run in the windowed
        tier itself instead of flooring at host-loop RTT. No host state
        is read or mutated (unlike :meth:`corrupt`'s ``self.rng`` split
        chain), so repeated traces are stable and the scan never
        recompiles for it."""
        mode, scale = self.mode, self.scale
        from fedml_tpu.core.tree import tree_select

        def corrupted(gp, cp, rng):
            if mode == "sign_flip":
                # Model replacement: g - scale * (w - g).
                return jax.tree.map(lambda w, g: g - scale * (w - g), cp, gp)
            if mode == "scale":
                return jax.tree.map(lambda w: w * scale, cp)
            if mode == "nan":
                return jax.tree.map(
                    lambda w: (w.at[(0,) * w.ndim].set(jnp.nan)
                               if w.ndim else jnp.nan * w), cp)
            leaves, treedef = jax.tree.flatten(cp)  # random
            keys = jax.random.split(rng, len(leaves))
            return jax.tree.unflatten(
                treedef,
                [scale * jax.random.normal(k, l.shape, l.dtype)
                 for k, l in zip(keys, leaves)])

        def apply(global_net, client_nets, adv, rngs):
            gp = (global_net.params if hasattr(global_net, "params")
                  else global_net)

            def per_client(cnet, a, rng):
                cp = cnet.params if hasattr(cnet, "params") else cnet
                new = tree_select(a > 0, corrupted(gp, cp, rng), cp)
                if hasattr(cnet, "params"):
                    return type(cnet)(new, cnet.model_state)
                return new

            return jax.vmap(per_client, in_axes=(0, 0, 0))(
                client_nets, adv, rngs)

        return apply


class HeartbeatMonitor:
    """Failure detector for the host-side federation: ranks ``beat()``;
    ``failed()`` lists ranks silent for > timeout_s. The reference has no
    equivalent — a dead client hangs its server forever
    (FedAVGAggregator.check_whether_all_receive waits unconditionally).

    Thread-safe: the distributed server manager beats from its receive
    thread while its watchdog thread polls ``failed()`` /
    ``wait_all_or_failed`` (algos/fedavg_distributed.py)."""

    def __init__(self, ranks: Sequence[int], timeout_s: float = 30.0,
                 clock=time.monotonic):
        import threading

        self.timeout_s = timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        self._last: Dict[int, float] = {r: now for r in ranks}

    def beat(self, rank: int):
        """Unknown ranks are registered on first beat."""
        with self._lock:
            self._last[rank] = self._clock()

    def forget(self, rank: int):
        """Drop a rank from monitoring (evicted from membership); a later
        ``beat`` re-registers it."""
        with self._lock:
            self._last.pop(rank, None)

    def failed(self) -> List[int]:
        now = self._clock()
        with self._lock:
            return sorted(
                r for r, t in self._last.items() if now - t > self.timeout_s
            )

    def alive(self) -> List[int]:
        with self._lock:
            known = set(self._last)
        return sorted(known - set(self.failed()))

    def wait_all_or_failed(self, expected: Sequence[int], have,
                           poll_s: float = 0.05,
                           deadline_s: Optional[float] = None) -> List[int]:
        """Block until ``have()`` covers ``expected`` minus failed ranks;
        returns the failed set. Replaces the reference's unconditional
        check_whether_all_receive spin. Ranks in ``expected`` the monitor
        has never seen count as failed once the timeout elapses (they are
        registered at entry). ``deadline_s`` (default 2x timeout) bounds the
        total wait: anything still missing then is declared failed."""
        expected = set(expected)
        start = self._clock()
        with self._lock:
            for r in expected - set(self._last):
                self._last[r] = start  # start their timeout clocks now
        deadline = deadline_s if deadline_s is not None else 2 * self.timeout_s
        while True:
            failed = set(self.failed())
            present = set(have())
            if present >= (expected - failed):
                return sorted(failed & expected)
            if self._clock() - start > deadline:
                # Deadline: report heartbeat-failed ranks PLUS whatever is
                # still missing (even if its heartbeat looks alive, its
                # result never arrived — the caller must not keep waiting).
                return sorted((failed | (expected - present)) & expected)
            time.sleep(poll_s)


def fault_injected_round(api, round_idx: int,
                         dropout: Optional[DropoutInjector] = None):
    """Harness: run one round of an API that supports host-side dropout
    (TurboAggregate's ``set_dropout``, reference TA_client.py:25) with
    injected per-round client churn. Update corruption is a BUILD-time
    concern — install ``UpdateCorruptor.corrupt`` through the algorithm's
    ``client_transform`` hook (see FedAvgRobustAPI for the pattern) and pair
    it with ``nan_guard`` / robust clipping to test the defenses."""
    if dropout is not None and hasattr(api, "set_dropout"):
        n = api.cfg.client_num_per_round
        mask = dropout.round_mask(round_idx, n)
        api.set_dropout(np.where(mask == 0.0)[0])
    return api.train_one_round(round_idx)
