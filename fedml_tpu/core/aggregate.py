"""Server-side aggregation primitives.

The reference's server holds a dict of client state_dicts and loops over keys
(fedml_api/distributed/fedavg/FedAVGAggregator.py:59-88). Here the "server" is
a functional reduction over a client-stacked pytree — on one chip a
``tree_weighted_mean``, across a mesh a ``lax.psum`` of per-shard partial sums
(see fedml_tpu/parallel/shard.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from fedml_tpu.core.tree import tree_sub, tree_weighted_mean


def weighted_average(stacked_params, sample_counts):
    """FedAvg: average client params weighted by true local sample counts
    (reference weights by ``local_sample_number``, FedAVGAggregator.py:78-82)."""
    return tree_weighted_mean(stacked_params, jnp.asarray(sample_counts))


def pseudo_gradient(old_params, avg_params):
    """Server pseudo-gradient ``old - avg`` used by the FedOpt family
    (fedml_api/distributed/fedopt/FedOptAggregator.py:95-109)."""
    return tree_sub(old_params, avg_params)
