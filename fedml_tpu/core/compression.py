"""Update compression for cross-silo communication.

New capability relative to the reference, which ships full pickled
state_dicts over MPI/gRPC every round (mpi_send_thread.py:27,
grpc_comm_manager.py:54 — and raises the gRPC cap to 1000 MB to make the
full payloads fit). Two standard schemes, both jit-able on device so the
TPU compresses before anything crosses the PCIe/DCN boundary:

- **Top-k sparsification with error feedback** (Deep Gradient Compression /
  EF-SGD): send only the k largest-|.|-entries of the flattened update,
  carry the residual forward in a client-local accumulator so the error is
  corrected on later rounds rather than lost.
- **Stochastic uniform quantization** (QSGD-style): map each entry to
  ``2^bits`` levels with stochastic rounding, so the quantizer is
  unbiased: ``E[deq(q(x))] = x``. The codec quantizes **per leaf** (one
  scale per tensor) — a single global scale would flush small-magnitude
  layers to zero at low bit widths with no error feedback to recover them.

Top-k operates on the flattened update vector (``tree_to_vector`` /
``vector_to_tree``); the wire payload is one values ndarray + int32
indices — ``k * (4 + 4)`` bytes instead of ``4 * n``.
"""

from __future__ import annotations

import re
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.tree import tree_cast, tree_vectorize


class TreeSpec(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]


def tree_spec(tree) -> TreeSpec:
    leaves, treedef = jax.tree.flatten(tree)
    return TreeSpec(
        treedef,
        tuple(l.shape for l in leaves),
        tuple(l.dtype for l in leaves),
        tuple(int(np.prod(l.shape)) if l.shape else 1 for l in leaves),
    )


def tree_to_vector(tree) -> jnp.ndarray:
    """Flatten to one fp32 vector (``core.tree.tree_vectorize`` plus the
    cast the compression math needs)."""
    vec = tree_vectorize(tree_cast(tree, jnp.float32))
    return vec if vec.size else jnp.zeros((0,), jnp.float32)


def vector_to_tree(vec, spec: TreeSpec):
    out, off = [], 0
    for shape, dtype, size in zip(spec.shapes, spec.dtypes, spec.sizes):
        out.append(jnp.reshape(vec[off:off + size], shape).astype(dtype))
        off += size
    return jax.tree.unflatten(spec.treedef, out)


# --------------------------------------------------------------------------
# Top-k sparsification with error feedback


def topk_compress(vec, k: int):
    """Keep the k largest-magnitude entries: returns (values[k], idx[k],
    residual) where residual = vec - scatter(values) is the error-feedback
    carry for the next round."""
    k = max(1, min(int(k), vec.shape[0]))
    _, idx = jax.lax.top_k(jnp.abs(vec), k)
    values = vec[idx]
    residual = vec.at[idx].set(0.0)
    return values, idx, residual


def topk_decompress(values, idx, n: int):
    return jnp.zeros((n,), values.dtype).at[idx].set(values)


# --------------------------------------------------------------------------
# Stochastic uniform quantization (unbiased)


def _check_bits(bits: int) -> None:
    if not 2 <= bits <= 16:
        raise ValueError(f"bits must be in [2, 16], got {bits}")


def quantize_stochastic(vec, bits: int, rng):
    """Symmetric uniform quantizer over one tensor with stochastic
    rounding. Returns (int levels in [-L, L] as int8/int16, fp32 scale)."""
    _check_bits(bits)
    levels = (1 << (bits - 1)) - 1  # e.g. 127 for 8 bits
    scale = jnp.maximum(jnp.max(jnp.abs(vec)), 1e-12) / levels
    scaled = vec / scale
    low = jnp.floor(scaled)
    p_up = scaled - low  # P(round up) = fractional part → unbiased
    up = jax.random.bernoulli(rng, p_up).astype(jnp.float32)
    q = jnp.clip(low + up, -levels, levels)
    dtype = jnp.int8 if bits <= 8 else jnp.int16
    return q.astype(dtype), scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


# jit wrappers hoisted to module level: constructing jax.jit inside
# encode() would discard the trace cache and re-trace every round.
_topk_jit = jax.jit(topk_compress, static_argnums=1)
_quantize_jit = jax.jit(quantize_stochastic, static_argnums=1)


# --------------------------------------------------------------------------
# Codec objects the cross-silo managers plug in (host-side frame shaping;
# the math above runs jitted on device).


class NoCompression:
    name = "none"

    def encode(self, update_tree, state, rng):
        return update_tree, state

    def decode(self, payload, spec: TreeSpec):
        return payload


class TopKCompression:
    """``ratio`` = fraction of entries kept (e.g. 0.01 → 100x sparser).
    ``state`` is the client's error-feedback residual vector (or None)."""

    def __init__(self, ratio: float):
        if not 0 < ratio <= 1:
            raise ValueError(f"ratio must be in (0, 1], got {ratio}")
        self.ratio = ratio
        self.name = f"topk{ratio}"

    def encode(self, update_tree, state, rng):
        vec = tree_to_vector(update_tree)
        if state is not None:
            vec = vec + state
        k = max(1, int(round(self.ratio * vec.shape[0])))
        values, idx, residual = _topk_jit(vec, k)
        payload = {
            "kind": "topk",
            "n": int(vec.shape[0]),
            "values": np.asarray(values),
            "idx": np.asarray(idx),
        }
        return payload, residual

    def decode(self, payload, spec: TreeSpec):
        vec = topk_decompress(
            jnp.asarray(payload["values"]), jnp.asarray(payload["idx"]),
            payload["n"])
        return vector_to_tree(vec, spec)


class QuantizeCompression:
    """QSGD-style ``bits``-bit stochastic quantization, one scale per leaf
    tensor (stateless)."""

    def __init__(self, bits: int):
        _check_bits(int(bits))  # fail at construction, not first upload
        self.bits = int(bits)
        self.name = f"q{bits}"

    def encode(self, update_tree, state, rng):
        leaves = jax.tree.leaves(update_tree)
        qs, scales = [], []
        for leaf, key in zip(leaves, jax.random.split(rng, max(len(leaves), 1))):
            q, scale = _quantize_jit(
                jnp.ravel(leaf).astype(jnp.float32), self.bits, key)
            qs.append(q)
            scales.append(scale)
        # ONE device→host sync for the whole update; per-leaf float()/
        # np.asarray() would serialize hundreds of blocking transfers on
        # the hot communication path.
        qs = jax.device_get(qs)
        scales = [float(s) for s in jax.device_get(scales)]
        payload = {"kind": "quant", "qs": qs, "scales": scales}
        return payload, state

    def decode(self, payload, spec: TreeSpec):
        vec = jnp.concatenate([
            dequantize(jnp.asarray(q), s)
            for q, s in zip(payload["qs"], payload["scales"])
        ]) if payload["qs"] else jnp.zeros((0,), jnp.float32)
        return vector_to_tree(vec, spec)


def make_compressor(name: str):
    """``none`` | ``topk<ratio>`` (e.g. topk0.05) | ``q<bits>`` (e.g. q8).

    Must accept every name a compressor can generate for itself (frames
    carry ``self.name`` and the server rebuilds the codec from it), so the
    ratio is parsed with ``float`` — including scientific notation like
    ``topk1e-05`` — rather than a decimal-only regex."""
    if name in (None, "", "none"):
        return NoCompression()
    guidance = (
        f"unknown compressor {name!r}; use none | topk<ratio> | q<bits>")
    if name.startswith("topk"):
        try:
            ratio = float(name[4:])
        except ValueError:
            raise ValueError(guidance) from None
        return TopKCompression(ratio)
    if re.fullmatch(r"q\d+", name):
        return QuantizeCompression(int(name[1:]))
    raise ValueError(guidance)
