"""Byzantine-robust pluggable server aggregation — the Aggregator protocol.

The reference's entire defense surface is norm-diff clipping + weak-DP
noise (fedml_core/robustness/robust_aggregation.py, mirrored in
``core/robustness.py``) — a SINGLE colluding client defeats both, because
the aggregation itself is still a weighted mean: one update scaled by the
cohort size drags the average anywhere. The canonical Byzantine-FL
defenses replace the mean with order statistics or medians over the
client-stacked update:

- ``coord_median`` / ``trimmed_mean`` — coordinate-wise median / trimmed
  mean (Yin et al., ICML'18): dimension-wise order statistics tolerate
  any minority of arbitrarily-corrupted clients.
- ``krum`` / ``multi_krum`` — Krum (Blanchard et al., NeurIPS'17): score
  each update by its summed squared distance to its n−f−2 nearest
  neighbors and keep the best-supported one (m best, averaged, for
  Multi-Krum).
- ``geometric_median`` — the smoothed geometric median via a FIXED
  number of Weiszfeld iterations (RFA, Pillutla et al. 2019); fixed
  iteration count so the whole aggregator stays a static-shape jittable
  block that rides ``lax.scan`` (the windowed tier).

**Protocol.** An aggregator is a pure, jittable callable

    ``agg(stacked, weights) -> tree``

where ``stacked`` is a client-stacked pytree (every leaf ``[C, ...]`` —
the round builders pass the full ``NetState`` stack) and ``weights`` is
the ``[C]`` float aggregation-weight vector the mean path already uses
(sample counts × pad mask × ``nan_guard``'s finite mask). Attributes
``name`` and ``is_mean`` ride on the callable; the round builders treat
``is_mean`` aggregators as the existing partial-sum fast path (bit-equal
to ``tree_weighted_mean``), and route every other aggregator through the
full client-stacked update — on a client mesh that means an
``all_gather`` of the cohort (``parallel/shard.make_sharded_round``).

**Weight semantics.** ``mean`` and ``geometric_median`` use the weight
VALUES (sample-count weighting, exactly like the reference). The order-
statistic aggregators (``coord_median``/``trimmed_mean``/``krum``) use
weights as a PARTICIPATION GATE only — ``weight > 0`` means the client's
update enters the order statistics, ``weight <= 0`` means it is EXCLUDED
(not averaged-at-zero: a zero-weighted entry would still shift a median).
That is the unification with ``nan_guard``: a diverged client's weight is
zeroed by the finite mask, so it vanishes from the order statistics
entirely. The all-excluded round (every weight zero) is the ROUND
BUILDER's problem — it keeps the previous global model, because order
statistics over an empty participant set are meaningless.

Every aggregator composes with the norm-clip client transform
(``core/robustness.norm_diff_clipping`` via the ``client_transform``
hook) — clipping bounds what a Byzantine client can inject, the robust
aggregator removes what clipping lets through. See docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.core.tree import tree_weighted_mean


def _mark(fn, name: str, is_mean: bool = False,
          group_composable: bool = False):
    fn.name = name
    fn.is_mean = is_mean
    # Hierarchical sparse reduction (arXiv:1903.05133 shape;
    # parallel/shard.py ``group_reduce``, algos/hierarchical.py): a
    # GROUP-COMPOSABLE aggregator may be applied in two stages — within
    # each group over that group's clients, then across the group
    # partials (each surviving group one vote, weight>0 = participation)
    # — shrinking the mesh collective from C client updates to G group
    # partials. Mean composes EXACTLY (partial weighted sums + psum is
    # already the deployed fast path); the coordinate-wise order
    # statistics compose as median-of-medians / trim-of-trims — the
    # standard hierarchical robust construction, deliberately NOT
    # numerically identical to the flat statistic (Byzantine tolerance
    # now holds per group). Krum (pairwise client distances) and the
    # geometric median (joint Weiszfeld fixpoint) do NOT decompose; they
    # keep the exact full client-stacked ``all_gather`` path, and the
    # round builders refuse ``group_reduce`` for them loudly.
    fn.group_composable = group_composable
    return fn


def _colshape(leaf):
    """Reshape a [C] vector to broadcast against a [C, ...] leaf."""
    return (-1,) + (1,) * (leaf.ndim - 1)


def mean():
    """Today's sample-count-weighted average — the fast path. The round
    builders special-case ``is_mean`` and keep their existing reduction
    (per-shard partial sums + ``psum`` on a mesh), so ``aggregator="mean"``
    is BIT-EQUAL to the pre-protocol rounds on every tier."""

    def agg(stacked, weights):
        return tree_weighted_mean(stacked, weights)

    return _mark(agg, "mean", is_mean=True, group_composable=True)


def coord_median():
    """Coordinate-wise median over participating clients (Yin et al.
    ICML'18). Excluded (weight<=0) clients are masked to +inf before the
    sort, so the median indexes only the m participating values; even m
    averages the two middle order statistics."""

    def agg(stacked, weights):
        valid = weights > 0
        m = jnp.sum(valid.astype(jnp.int32))
        lo_i = jnp.maximum((m - 1) // 2, 0)
        hi_i = jnp.maximum(m // 2, 0)

        def med(p):
            v = jnp.where(valid.reshape(_colshape(p)), p.astype(jnp.float32),
                          jnp.inf)
            s = jnp.sort(v, axis=0)
            lo = jnp.take(s, lo_i, axis=0)
            hi = jnp.take(s, hi_i, axis=0)
            return ((lo + hi) * 0.5).astype(p.dtype)

        return jax.tree.map(med, stacked)

    return _mark(agg, "coord_median", group_composable=True)


def trimmed_mean(beta: float = 0.1):
    """Coordinate-wise ``beta``-trimmed mean (Yin et al. ICML'18): drop
    the ``floor(beta*m)`` smallest and largest values per coordinate
    among the m participating clients, average the rest. ``beta`` must be
    in [0, 0.5); the trim count is clamped so at least one value always
    survives (tiny cohorts)."""
    if not 0.0 <= beta < 0.5:
        raise ValueError(f"trimmed_mean beta must be in [0, 0.5), got {beta}")

    def agg(stacked, weights):
        valid = weights > 0
        c = weights.shape[0]
        m = jnp.sum(valid.astype(jnp.int32))
        k = jnp.minimum(jnp.floor(beta * m).astype(jnp.int32),
                        jnp.maximum((m - 1) // 2, 0))
        pos = jnp.arange(c)
        keep = (pos >= k) & (pos < m - k)  # sorted positions kept
        denom = jnp.maximum(m - 2 * k, 1).astype(jnp.float32)

        def tm(p):
            v = jnp.where(valid.reshape(_colshape(p)), p.astype(jnp.float32),
                          jnp.inf)
            s = jnp.sort(v, axis=0)
            s = jnp.where(keep.reshape(_colshape(p)), s, 0.0)
            return (jnp.sum(s, axis=0) / denom).astype(p.dtype)

        return jax.tree.map(tm, stacked)

    return _mark(agg, f"trimmed_mean{beta}", group_composable=True)


def multi_krum(f: int = 1, m: int = 1):
    """Multi-Krum (Blanchard et al. NeurIPS'17): flatten each
    participating client's update to a vector, score each by the sum of
    its squared distances to its ``n_valid − f − 2`` nearest participating
    neighbors, and average the ``m`` best-scoring clients (equal weights —
    Krum's selection is the defense; re-weighting by sample count would
    let a heavy Byzantine client back in). ``f`` is the assumed Byzantine
    count; guarantees need ``n_valid >= 2f + 3``. Excluded clients get
    +inf distances and +inf scores, so they are neither neighbors nor
    selectable."""
    if f < 0 or m < 1:
        raise ValueError(f"multi_krum needs f >= 0 and m >= 1, got ({f}, {m})")

    def agg(stacked, weights):
        valid = weights > 0
        c = weights.shape[0]
        nv = jnp.sum(valid.astype(jnp.int32))
        x = jnp.concatenate(
            [l.reshape(l.shape[0], -1).astype(jnp.float32)
             for l in jax.tree.leaves(stacked)], axis=1)
        # Gram-form pairwise distances: O(C·D + C²) instead of the
        # [C, C, D] broadcast-difference tensor, which at bench scale
        # (C=32, D~1.2M params) is ~5 GB of intermediate the backend is
        # not guaranteed to fuse away. Cancellation can leave tiny
        # negatives for near-identical vectors — clamp; the selection
        # only compares distances, so the clamp is inert.
        sq = jnp.sum(jnp.square(x), axis=1)
        d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (x @ x.T), 0.0)
        pair_ok = (valid[:, None] & valid[None, :]
                   & ~jnp.eye(c, dtype=bool))
        d2 = jnp.where(pair_ok, d2, jnp.inf)
        s = jnp.sort(d2, axis=1)  # ascending; excluded pairs last
        nn = jnp.clip(nv - f - 2, 1, c - 1)  # neighbors per Blanchard
        take = jnp.arange(c)[None, :] < nn
        score = jnp.sum(jnp.where(take, s, 0.0), axis=1)
        # Sort key: excluded clients must rank strictly AFTER every valid
        # one. A valid client's score can itself be +inf (a lone survivor
        # has no finite-distance neighbor), and inf == inf would let
        # argsort's stable order pick an EXCLUDED slot 0 — so valid
        # scores are clamped to a large finite before the invalid slots'
        # +inf (ties among clamped extremes resolve by index, which only
        # reorders clients that were all off-scale anyway).
        sort_key = jnp.where(valid, jnp.minimum(score, jnp.float32(3e38)),
                             jnp.inf)
        mm = jnp.minimum(m, jnp.maximum(nv, 1))
        order = jnp.argsort(sort_key)  # best-supported first
        sel = (jnp.arange(c) < mm).astype(jnp.float32)
        sel_w = jnp.zeros_like(score).at[order].set(sel)
        return tree_weighted_mean(stacked, sel_w)

    name = f"krum{f}" if m == 1 else f"multi_krum{f}-{m}"
    return _mark(agg, name)


def krum(f: int = 1):
    """Krum proper: Multi-Krum with m = 1 (keep the single best-supported
    client's update)."""
    return multi_krum(f, 1)


def geometric_median(iters: int = 8, eps: float = 1e-8):
    """Smoothed geometric median by ``iters`` FIXED Weiszfeld iterations
    (RFA, Pillutla et al. 2019): z ← Σ w_i x_i / (‖x_i − z‖ + ε) ÷ Σ ... ,
    initialized at the weighted mean. Uses the weight VALUES (weighted
    geometric median — zero-weight clients contribute nothing to either
    the init or any iterate). A fixed iteration count keeps the block
    static-shape, so it inlines into ``lax.scan`` bodies (the windowed
    tier) without recompiles."""
    if iters < 1:
        raise ValueError(f"geometric_median needs iters >= 1, got {iters}")

    def agg(stacked, weights):
        w = jnp.maximum(weights.astype(jnp.float32), 0.0)
        z = tree_weighted_mean(stacked, w)
        for _ in range(iters):  # static unroll: jit/scan-friendly
            diffs = jax.tree.map(
                lambda p, zz: p.astype(jnp.float32)
                - zz.astype(jnp.float32)[None], stacked, z)
            d2 = sum(jnp.sum(jnp.square(l.reshape(l.shape[0], -1)), axis=1)
                     for l in jax.tree.leaves(diffs))
            z = tree_weighted_mean(stacked, w / jnp.sqrt(d2 + eps))
        return z

    return _mark(agg, f"geometric_median{iters}")


def make_aggregator(spec):
    """Resolve ``cfg.aggregator`` to an Aggregator callable.

    Accepts a callable (returned as-is, ``name``/``is_mean`` defaulted)
    or a string spec, following ``cfg.compress``'s suffix-number idiom:

    - ``"mean"``
    - ``"coord_median"``
    - ``"trimmed_mean"`` / ``"trimmed_mean0.2"`` (beta, default 0.1)
    - ``"krum"`` / ``"krum2"`` (f, default 1)
    - ``"multi_krum"`` / ``"multi_krum2"`` / ``"multi_krum2-4"``
      (f[-m], defaults f=1, m=2)
    - ``"geometric_median"`` / ``"geometric_median16"`` (Weiszfeld
      iterations, default 8)
    """
    if callable(spec):
        if not hasattr(spec, "is_mean"):
            _mark(spec, getattr(spec, "name", getattr(
                spec, "__name__", "custom")))
        return spec
    s = str(spec).strip()

    def _suffix(prefix):
        return s[len(prefix):]

    try:
        if s == "mean":
            return mean()
        if s == "coord_median":
            return coord_median()
        if s.startswith("trimmed_mean"):
            rest = _suffix("trimmed_mean")
            return trimmed_mean(float(rest) if rest else 0.1)
        if s.startswith("multi_krum"):
            rest = _suffix("multi_krum")
            if not rest:
                return multi_krum(1, 2)
            f, _, m = rest.partition("-")
            return multi_krum(int(f), int(m) if m else 2)
        if s.startswith("krum"):
            rest = _suffix("krum")
            return krum(int(rest) if rest else 1)
        if s.startswith("geometric_median"):
            rest = _suffix("geometric_median")
            return geometric_median(int(rest) if rest else 8)
    except ValueError as e:
        if "aggregator" in str(e) or "must be" in str(e) or "needs" in str(e):
            raise
        raise ValueError(
            f"cfg.aggregator={spec!r}: could not parse the parameter "
            f"suffix ({e})") from None
    raise ValueError(
        f"unknown aggregator {spec!r}; known: mean, coord_median, "
        "trimmed_mean<beta>, krum<f>, multi_krum<f>-<m>, "
        "geometric_median<iters>")
