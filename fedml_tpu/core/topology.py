"""Topology managers for decentralized FL.

Parity: fedml_core/distributed/topology/ — ring (Watts–Strogatz k=2 p=0)
plus random symmetric/asymmetric extra links, row-normalized into a mixing
matrix (symmetric_topology_manager.py:22-52, asymmetric variant).

The TPU twist: the topology is materialised as a dense ``[n, n]`` mixing
matrix ``W`` so one round of neighbor gossip over ALL clients is a single
``einsum('ij,j...->i...', W, stacked_params)`` — the MXU does the message
passing (vs. the reference's per-neighbor MPI sends,
decentralized_worker_manager.py:29-39).
"""

from __future__ import annotations

import numpy as np


class BaseTopologyManager:
    """ABC parity: base_topology_manager.py:4-28."""

    topology: np.ndarray  # [n, n] row-stochastic mixing weights

    def get_in_neighbor_idx_list(self, node_index: int):
        return [
            j for j in range(self.n) if self.topology[j][node_index] > 0 and j != node_index
        ]

    def get_out_neighbor_idx_list(self, node_index: int):
        return [
            j for j in range(self.n) if self.topology[node_index][j] > 0 and j != node_index
        ]

    def get_in_neighbor_weights(self, node_index: int):
        return [self.topology[j][node_index] for j in range(self.n)]

    def get_out_neighbor_weights(self, node_index: int):
        return [self.topology[node_index][j] for j in range(self.n)]

    def mixing_matrix(self) -> np.ndarray:
        return self.topology


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring + undirected random links, row-normalized
    (symmetric_topology_manager.py:22-52)."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, max(n - 1, 1))
        self.seed = seed
        self.generate_topology()

    def generate_topology(self):
        n, k = self.n, self.neighbor_num
        # Explicit ring (±1 mod n) so connectivity never silently degrades
        # (watts_strogatz with odd/clamped k can drop links — e.g. n=2
        # would otherwise yield an edgeless graph and gossip would be a
        # no-op with no warning).
        topo = np.eye(n)
        for i in range(n):
            topo[i, (i + 1) % n] = 1.0
            topo[i, (i - 1) % n] = 1.0
        # sprinkle undirected random links like the reference's
        # "np.random.seed + random positions" loop
        rng = np.random.RandomState(self.seed)
        k_extra = max(k - 2, 0)
        for i in range(n):
            if k_extra == 0:
                break
            js = rng.choice(n, k_extra, replace=False)
            topo[i, js] = 1.0
            topo[js, i] = 1.0
        row_sums = topo.sum(axis=1, keepdims=True)
        self.topology = topo / row_sums


class AsymmetricTopologyManager(BaseTopologyManager):
    """Directed ring + random out-links, row-normalized (asymmetric variant,
    fedml_core/distributed/topology/asymmetric_topology_manager.py)."""

    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.neighbor_num = min(neighbor_num, max(n - 1, 1))
        self.seed = seed
        self.generate_topology()

    def generate_topology(self):
        n = self.n
        topo = np.eye(n)
        for i in range(n):
            topo[i, (i + 1) % n] = 1.0  # directed ring
        rng = np.random.RandomState(self.seed)
        for i in range(n):
            extra = rng.choice(n, self.neighbor_num, replace=False)
            topo[i, extra] = 1.0
        self.topology = topo / topo.sum(axis=1, keepdims=True)


def column_stochastic(topology: np.ndarray) -> np.ndarray:
    """Column-normalized variant (PushSum needs column-stochastic weights)."""
    return topology / topology.sum(axis=0, keepdims=True)
