"""Seeded client sampling, reproducing the reference's semantics exactly.

Reference: ``FedAVGAggregator.client_sampling``
(fedml_api/distributed/fedavg/FedAVGAggregator.py:90-99) does
``np.random.seed(round_idx)`` then ``np.random.choice(range(total), num,
replace=False)``; with full participation it returns ``range(total)``.
Matching this bit-for-bit keeps training curves comparable with published
reference runs.
"""

from __future__ import annotations

import numpy as np


def sample_clients(
    round_idx: int, client_num_in_total: int, client_num_per_round: int
) -> np.ndarray:
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int32)
    num_clients = min(client_num_per_round, client_num_in_total)
    # Legacy RandomState(seed) generates the same stream as np.random.seed(seed).
    rng = np.random.RandomState(round_idx)
    return rng.choice(client_num_in_total, num_clients, replace=False).astype(np.int32)


def sample_clients_weighted(
    round_idx: int,
    client_num_in_total: int,
    num: int,
    counts,
) -> np.ndarray:
    """Data-fraction-proportional candidate draw (without replacement).

    Power-of-Choice (Cho et al. 2020, §2 "Power-of-Choice Selection
    Strategy") draws its d candidates with probability proportional to
    each client's data fraction — on power-law partitions (LEAF MNIST)
    this differs materially from a uniform draw. Seeded by ``round_idx``
    like :func:`sample_clients`; falls back to the uniform reference
    stream when fewer than ``num`` clients hold data (the weighted draw
    would be infeasible without replacement).
    """
    if client_num_in_total == num:
        return np.arange(client_num_in_total, dtype=np.int32)
    num = min(num, client_num_in_total)
    counts = np.asarray(counts, dtype=np.float64)
    if counts.shape != (client_num_in_total,):
        raise ValueError(
            f"counts shape {counts.shape} != ({client_num_in_total},); "
            "client_num_in_total must match the federated dataset")
    if np.count_nonzero(counts > 0) < num:
        return sample_clients(round_idx, client_num_in_total, num)
    p = counts / counts.sum()
    rng = np.random.RandomState(round_idx)
    return rng.choice(client_num_in_total, num, replace=False, p=p).astype(np.int32)


def pad_to_multiple(indices: np.ndarray, multiple: int):
    """Pad a sampled-client index list to a device-count multiple.

    Padded slots repeat index 0 but carry weight 0 (see ``weight_mask``), so
    the weighted average is unchanged while every shard stays rectangular.
    Returns ``(padded_indices, weight_mask)``.
    """
    n = len(indices)
    if multiple <= 1 or n % multiple == 0:
        return indices, np.ones((n,), dtype=np.float32)
    pad = multiple - (n % multiple)
    padded = np.concatenate([indices, np.full((pad,), indices[0], dtype=indices.dtype)])
    mask = np.concatenate([np.ones((n,), np.float32), np.zeros((pad,), np.float32)])
    return padded, mask
