"""Seeded client sampling, reproducing the reference's semantics exactly.

Reference: ``FedAVGAggregator.client_sampling``
(fedml_api/distributed/fedavg/FedAVGAggregator.py:90-99) does
``np.random.seed(round_idx)`` then ``np.random.choice(range(total), num,
replace=False)``; with full participation it returns ``range(total)``.
Matching this bit-for-bit keeps training curves comparable with published
reference runs.
"""

from __future__ import annotations

import numpy as np


def sample_clients(
    round_idx: int, client_num_in_total: int, client_num_per_round: int
) -> np.ndarray:
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int32)
    num_clients = min(client_num_per_round, client_num_in_total)
    # Legacy RandomState(seed) generates the same stream as np.random.seed(seed).
    rng = np.random.RandomState(round_idx)
    return rng.choice(client_num_in_total, num_clients, replace=False).astype(np.int32)


def pad_to_multiple(indices: np.ndarray, multiple: int):
    """Pad a sampled-client index list to a device-count multiple.

    Padded slots repeat index 0 but carry weight 0 (see ``weight_mask``), so
    the weighted average is unchanged while every shard stays rectangular.
    Returns ``(padded_indices, weight_mask)``.
    """
    n = len(indices)
    if multiple <= 1 or n % multiple == 0:
        return indices, np.ones((n,), dtype=np.float32)
    pad = multiple - (n % multiple)
    padded = np.concatenate([indices, np.full((pad,), indices[0], dtype=indices.dtype)])
    mask = np.concatenate([np.ones((n,), np.float32), np.zeros((pad,), np.float32)])
    return padded, mask
