"""MPC primitives for secure aggregation (Turbo-Aggregate).

Parity target: reference fedml_api/distributed/turboaggregate/mpc_function.py
(identical library in fedml_api/standalone/turboaggregate/) —
- Shamir/BGW secret sharing (BGW_encoding:62 / BGW_decoding:90),
- Lagrange Coded Computing (LCC_encoding:111 / LCC_decoding:195 and the
  _with_points variants :227,:249),
- additive secret sharing (Gen_Additive_SS:214),
- Diffie-Hellman key agreement (my_pk_gen:263 / my_key_agreement:271).

Redesign notes (same math, safer numerics): the reference evaluates
``alpha ** t`` before reducing mod p — silent int64 overflow for larger
degrees. Here every multiply is reduced mod p immediately (p < 2^31 keeps
products < 2^62), modular inverses use Fermat via ``pow(a, p-2, p)``, and
share generation is a Vandermonde-style matmul built with running powers.
These are host-side (numpy) by design: secure aggregation is a *protocol*
between trust domains, not a TPU kernel; the field arithmetic is cheap
relative to the masked-model transfers it protects.
"""

from __future__ import annotations

import numpy as np

# 2^31 - 1 (Mersenne prime) — keeps all products within int64.
DEFAULT_PRIME = 2147483647


def modular_inv(a, p: int = DEFAULT_PRIME):
    """Inverse mod prime p (Fermat little theorem; reference :4-18 uses
    extended Euclid — same result)."""
    a = np.mod(np.asarray(a, dtype=np.int64), p)
    return np.vectorize(lambda v: pow(int(v), p - 2, p))(a).astype(np.int64)


def field_div(num, den, p: int = DEFAULT_PRIME):
    """num / den mod p (reference divmod :21-27)."""
    num = np.mod(np.asarray(num, np.int64), p)
    return np.mod(num * modular_inv(den, p), p)


def _powers(points: np.ndarray, deg: int, p: int) -> np.ndarray:
    """[len(points), deg+1] matrix of points**t mod p with running products
    (no un-reduced exponentials, unlike reference :74)."""
    points = np.mod(np.asarray(points, np.int64), p)
    out = np.ones((len(points), deg + 1), np.int64)
    for t in range(1, deg + 1):
        out[:, t] = np.mod(out[:, t - 1] * points, p)
    return out


def lagrange_coeffs(alpha_s, beta_s, p: int = DEFAULT_PRIME) -> np.ndarray:
    """U[j, i] = ∏_{k≠i} (alpha_j − beta_k) / (beta_i − beta_k) mod p
    (reference gen_Lagrange_coeffs :39-59)."""
    alpha_s = np.mod(np.asarray(alpha_s, np.int64), p)
    beta_s = np.mod(np.asarray(beta_s, np.int64), p)
    U = np.zeros((len(alpha_s), len(beta_s)), np.int64)
    for i in range(len(beta_s)):
        den = np.int64(1)
        for k in range(len(beta_s)):
            if k != i:
                den = np.mod(den * np.mod(beta_s[i] - beta_s[k], p), p)
        for j in range(len(alpha_s)):
            num = np.int64(1)
            for k in range(len(beta_s)):
                if k != i:
                    num = np.mod(num * np.mod(alpha_s[j] - beta_s[k], p), p)
            U[j, i] = field_div(num, den, p)
    return U


def _mod_matmul(U: np.ndarray, flat: np.ndarray, p: int) -> np.ndarray:
    """U @ flat with every term reduced mod p — a plain int64 matmul of
    field elements overflows at ≥3 accumulated products ((p−1)² ≈ 4.6e18)."""
    out = np.zeros((U.shape[0], flat.shape[1]), np.int64)
    for i in range(U.shape[1]):
        out = np.mod(out + U[:, i, None] * flat[i][None], p)
    return out


# ---------------------------------------------------------------------------
# BGW / Shamir
# ---------------------------------------------------------------------------

def bgw_encode(X, N: int, T: int, p: int = DEFAULT_PRIME,
               rng: np.random.RandomState = None) -> np.ndarray:
    """Degree-T Shamir shares of ``X [m, d]`` for N workers, evaluation
    points alpha = 1..N (reference BGW_encoding :62-75). Returns [N, m, d]."""
    rng = rng or np.random.RandomState()
    X = np.mod(np.asarray(X, np.int64), p)
    m, d = X.shape
    coeffs = rng.randint(0, p, size=(T + 1, m, d)).astype(np.int64)
    coeffs[0] = X
    V = _powers(np.arange(1, N + 1), T, p)  # [N, T+1]
    shares = _mod_matmul(V, coeffs.reshape(T + 1, -1), p)
    return shares.reshape(N, m, d)


def bgw_decode(shares: np.ndarray, worker_idx, p: int = DEFAULT_PRIME,
               T: int | None = None):
    """Reconstruct the secret from ≥T+1 shares; ``worker_idx`` are the
    0-based worker indices the shares came from (reference BGW_decoding
    :90-108, evaluation point of worker i is i+1). Pass ``T`` to validate
    the share count — with < T+1 shares Lagrange interpolation returns a
    plausible-looking but WRONG reconstruction, so the check must be loud."""
    worker_idx = np.asarray(worker_idx, np.int64)
    if T is not None and len(worker_idx) < T + 1:
        raise ValueError(
            f"bgw_decode needs >= T+1 = {T + 1} shares, got {len(worker_idx)}"
        )
    alpha_eval = np.mod(worker_idx + 1, p)
    lam = lagrange_coeffs(np.zeros(1, np.int64), alpha_eval, p)[0]  # at x=0
    flat = shares.reshape(len(worker_idx), -1)
    rec = np.zeros(flat.shape[1], np.int64)
    for i in range(len(worker_idx)):
        rec = np.mod(rec + lam[i] * flat[i], p)
    return rec.reshape(shares.shape[1:])


# ---------------------------------------------------------------------------
# Lagrange Coded Computing
# ---------------------------------------------------------------------------

def _lcc_points(N: int, K: int, T: int, p: int):
    """Interpolation points beta (data+noise chunks) and evaluation points
    alpha (workers). The sets MUST be disjoint: a worker whose alpha equals
    some beta_k (k < K) would receive that plaintext chunk as its "share",
    voiding the T-noise privacy guarantee. beta = 0..K+T-1,
    alpha = K+T..K+T+N-1 (requires K+T+N < p, trivially true here)."""
    n_beta = K + T
    if n_beta + N >= p:
        # Privacy-critical (a collision hands a worker a plaintext chunk);
        # must survive python -O, so not an assert.
        raise ValueError(
            f"field p={p} too small for disjoint LCC point sets "
            f"(need K+T+N={n_beta + N} < p)")
    beta_s = np.arange(n_beta, dtype=np.int64)
    alpha_s = np.arange(n_beta, n_beta + N, dtype=np.int64)
    return alpha_s, beta_s


def lcc_encode(X, N: int, K: int, T: int, p: int = DEFAULT_PRIME,
               rng: np.random.RandomState = None) -> np.ndarray:
    """LCC shares: split ``X [m, d]`` into K chunks + T random chunks,
    Lagrange-interpolate through beta points, evaluate at N alpha points
    (reference LCC_encoding :111-134). Returns [N, m//K, d]."""
    rng = rng or np.random.RandomState()
    X = np.mod(np.asarray(X, np.int64), p)
    m, d = X.shape
    assert m % K == 0, "rows must divide K"
    chunks = X.reshape(K, m // K, d)
    if T > 0:
        noise = rng.randint(0, p, size=(T, m // K, d)).astype(np.int64)
        chunks = np.concatenate([chunks, noise], axis=0)
    alpha_s, beta_s = _lcc_points(N, K, T, p)
    U = lagrange_coeffs(alpha_s, beta_s, p)  # [N, K+T]
    out = _mod_matmul(U, chunks.reshape(K + T, -1), p)
    return out.reshape(N, m // K, d)


def lcc_decode(f_eval: np.ndarray, worker_idx, N: int, K: int, T: int,
               p: int = DEFAULT_PRIME) -> np.ndarray:
    """Recover the K data chunks from ≥K+T share evaluations
    (reference LCC_decoding :195-211). Returns [K, rows, d]."""
    alpha_s, beta_s = _lcc_points(N, K, T, p)
    worker_idx = np.asarray(worker_idx)
    if len(worker_idx) < K + T:
        raise ValueError(
            f"lcc_decode needs >= K+T = {K + T} shares, got {len(worker_idx)}"
        )
    U = lagrange_coeffs(beta_s[:K], alpha_s[worker_idx], p)  # [K, W]
    flat = f_eval.reshape(len(worker_idx), -1)
    rec = _mod_matmul(U, flat, p)
    return rec.reshape((K,) + f_eval.shape[1:])


def lcc_encode_with_points(X, alpha_s, beta_s, p: int = DEFAULT_PRIME):
    """Evaluate the interpolant of (beta_i → X_i) at alpha points
    (reference LCC_encoding_with_points :227-246)."""
    X = np.mod(np.asarray(X, np.int64), p)
    U = lagrange_coeffs(alpha_s, beta_s, p)
    flat = X.reshape(len(beta_s), -1)
    return _mod_matmul(U, flat, p).reshape((len(alpha_s),) + X.shape[1:])


def lcc_decode_with_points(f_eval, eval_points, target_points,
                           p: int = DEFAULT_PRIME):
    """Inverse of the above (reference LCC_decoding_with_points :249-260)."""
    return lcc_encode_with_points(f_eval, target_points, eval_points, p)


# ---------------------------------------------------------------------------
# Additive secret sharing + key agreement
# ---------------------------------------------------------------------------

def additive_shares(x, n_out: int, p: int = DEFAULT_PRIME,
                    rng: np.random.RandomState = None) -> np.ndarray:
    """n_out shares summing to x mod p (reference Gen_Additive_SS :214-224)."""
    rng = rng or np.random.RandomState()
    x = np.mod(np.asarray(x, np.int64), p)
    shares = rng.randint(0, p, size=(n_out,) + x.shape).astype(np.int64)
    shares[-1] = np.mod(x - np.mod(shares[:-1].sum(axis=0), p), p)
    return shares


def pk_gen(sk: int, p: int = DEFAULT_PRIME, g: int = 3) -> int:
    """g^sk mod p (reference my_pk_gen :263-268)."""
    return pow(g, int(sk), p)


def key_agreement(my_sk: int, other_pk: int, p: int = DEFAULT_PRIME) -> int:
    """Diffie-Hellman shared key pk^sk mod p (reference my_key_agreement
    :271-276) — symmetric in the two parties."""
    return pow(int(other_pk), int(my_sk), p)


# ---------------------------------------------------------------------------
# Fixed-point quantization (model weights ↔ field elements)
# ---------------------------------------------------------------------------

def quantize(x: np.ndarray, scale: int = 2 ** 16,
             p: int = DEFAULT_PRIME) -> np.ndarray:
    """Real → field: round(x·scale) mod p, negatives wrap to [p/2, p)."""
    return np.mod(np.round(np.asarray(x, np.float64) * scale).astype(np.int64), p)


def dequantize(q: np.ndarray, scale: int = 2 ** 16,
               p: int = DEFAULT_PRIME) -> np.ndarray:
    """Field → real, mapping [p/2, p) back to negatives."""
    q = np.asarray(q, np.int64)
    signed = np.where(q > p // 2, q - p, q)
    return signed.astype(np.float64) / scale
