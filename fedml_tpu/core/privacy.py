"""Differential-privacy accounting (zCDP) for the DP mechanisms.

New capability relative to the reference, whose only DP surface is
uncalibrated server-side Gaussian noise ("weak DP",
fedml_core/robustness/robust_aggregation.py:49-53) with no accounting.
Two mechanisms in this framework release noised quantities:

- example-level DP-SGD on clients (``make_local_train_fn(dp_clip=...,
  dp_noise_multiplier=z)``, trainer/local.py): each optimizer step releases
  ``sum(clipped per-example grads) + N(0, (z*C)^2)`` — L2 sensitivity to
  one example is ``C``, so each step is a Gaussian mechanism with noise
  multiplier ``z``;
- client-level DP-FedAvg at the server (norm-clipped client deltas +
  Gaussian noise, ``core/robustness.py``): sensitivity to one client is
  the clip bound, noise multiplier = ``stddev / norm_bound``.

Accounting uses zero-concentrated DP (Bun & Steinke 2016): the Gaussian
mechanism with noise multiplier ``z`` satisfies ``rho = 1/(2 z^2)``-zCDP,
zCDP composes additively, and ``rho``-zCDP implies
``(rho + 2*sqrt(rho * ln(1/delta)), delta)``-DP. These bounds are tight
enough for reporting and entirely closed-form (no numerical RDP-order
search); they do NOT include subsampling amplification, so the reported
epsilon is a conservative upper bound when clients/batches are sampled.
"""

from __future__ import annotations

import math


def zcdp_of_gaussian(noise_multiplier: float) -> float:
    """rho of one Gaussian-mechanism release with std = z * sensitivity."""
    if noise_multiplier <= 0:
        return math.inf
    return 0.5 / (noise_multiplier ** 2)


def zcdp_to_eps(rho: float, delta: float) -> float:
    """Convert rho-zCDP to (eps, delta)-DP."""
    if not 0 < delta < 1:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    if math.isinf(rho):
        return math.inf
    return rho + 2.0 * math.sqrt(rho * math.log(1.0 / delta))


class PrivacyAccountant:
    """Additive zCDP composition over a run.

    >>> acct = PrivacyAccountant()
    >>> acct.step(noise_multiplier=1.1, steps=rounds * steps_per_round)
    >>> acct.epsilon(delta=1e-5)
    """

    def __init__(self):
        self.rho = 0.0

    def step(self, noise_multiplier: float, steps: int = 1) -> "PrivacyAccountant":
        self.rho += steps * zcdp_of_gaussian(noise_multiplier)
        return self

    def epsilon(self, delta: float) -> float:
        return zcdp_to_eps(self.rho, delta)


def dp_sgd_epsilon(noise_multiplier: float, epochs: int, steps_per_epoch: int,
                   rounds: int, delta: float) -> float:
    """Closed-form epsilon for a full DP-SGD federated run: every local
    optimizer step on a client is one Gaussian release against that
    client's data (``rounds * epochs * steps_per_epoch`` compositions)."""
    acct = PrivacyAccountant()
    acct.step(noise_multiplier, steps=rounds * epochs * steps_per_epoch)
    return acct.epsilon(delta)
