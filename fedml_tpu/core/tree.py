"""Pytree arithmetic for federated aggregation.

The reference aggregates per-key over Python dict state_dicts on the CPU
(fedml_api/distributed/fedavg/FedAVGAggregator.py:74-82). Here model
parameters are JAX pytrees and every aggregation is a fused on-device
elementwise op, so XLA tiles the whole weighted average into a handful of
HBM-bandwidth-bound kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_add(a, b):
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a, b):
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree, s):
    return jax.tree.map(lambda x: x * s, tree)


def tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_dot(a, b):
    """Sum of elementwise products over two pytrees (an inner product)."""
    leaves = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return jnp.sum(jnp.stack(leaves)) if leaves else jnp.float32(0.0)


def tree_global_norm(tree):
    """L2 norm over all leaves.

    Mirrors the reference's ``vectorize_weight(...).norm()``
    (fedml_core/robustness/robust_aggregation.py:4-10) without materialising
    the concatenated vector.
    """
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree))
    )


def tree_vectorize(tree):
    """Flatten a pytree into one 1-D vector (host/debug utility)."""
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([jnp.ravel(x) for x in leaves]) if leaves else jnp.zeros((0,))


def tree_weighted_mean(stacked, weights):
    """Weighted mean over the leading (client) axis of a stacked pytree.

    ``stacked`` leaves have shape ``[C, ...]``; ``weights`` is ``[C]`` and is
    normalised internally, reproducing the reference's sample-count-weighted
    average (fedml_api/distributed/fedavg/FedAVGAggregator.py:74-82) with the
    per-key Python loop replaced by one einsum per leaf.
    """
    w = weights.astype(jnp.float32)
    w = w / jnp.maximum(jnp.sum(w), 1e-12)
    return jax.tree.map(
        lambda p: jnp.einsum("c,c...->...", w, p.astype(jnp.float32)).astype(p.dtype),
        stacked,
    )


def tree_select(pred, on_true, on_false):
    """Elementwise pytree select on a scalar predicate (used to gate optimizer
    updates on padded/empty batches so padding never perturbs state)."""
    return jax.tree.map(lambda t, f: jnp.where(pred, t, f), on_true, on_false)


def gather_stacked(stacked, idx):
    """Gather sampled-client slots from a client-stacked pytree
    (``[N, ...]`` leaves → ``[k, ...]``). The per-client-state companion
    of ``data.batching.gather_clients`` — Ditto's personal models,
    SCAFFOLD's control variates."""
    return jax.tree.map(lambda p: jnp.take(p, idx, axis=0), stacked)


def scatter_stacked(stacked, idx, values, wmask):
    """Write back sampled-client slots of a client-stacked pytree. Shard
    padding repeats idx[0] with wmask 0; routing padded slots to an
    out-of-bounds index with ``mode='drop'`` discards those writes
    entirely — a gated merge would leave duplicate indices in the
    scatter, whose write order XLA leaves undefined, letting a padded
    slot's stale state clobber the real one."""

    def put(old, new):
        dustbin = old.shape[0]  # out of bounds → dropped
        idx_eff = jnp.where(wmask > 0, idx, dustbin)
        return old.at[idx_eff].set(new, mode="drop")

    return jax.tree.map(put, stacked, values)
