from fedml_tpu.core.tree import (
    tree_add,
    tree_sub,
    tree_scale,
    tree_dot,
    tree_global_norm,
    tree_vectorize,
    tree_weighted_mean,
    tree_select,
    tree_zeros_like,
    tree_cast,
)
from fedml_tpu.core.sampling import sample_clients
from fedml_tpu.core.aggregate import weighted_average, pseudo_gradient
from fedml_tpu.core.robust_agg import make_aggregator

__all__ = [
    "tree_add",
    "tree_sub",
    "tree_scale",
    "tree_dot",
    "tree_global_norm",
    "tree_vectorize",
    "tree_weighted_mean",
    "tree_select",
    "tree_zeros_like",
    "tree_cast",
    "sample_clients",
    "weighted_average",
    "pseudo_gradient",
    "make_aggregator",
]
