"""Robust-aggregation defense primitives.

Parity: fedml_core/robustness/robust_aggregation.py —
``norm_diff_clipping`` (:36-47) projects each client update ``w_i − w_g``
onto an L2 ball of radius ``norm_bound`` before averaging, and ``add_noise``
(:49-53) adds weak-DP Gaussian noise. The reference skips BatchNorm running
stats via an ``is_weight_param`` name filter (:27-29); here those live in
``NetState.model_state`` and are excluded structurally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.core.tree import tree_global_norm


def norm_diff_clipping(client_params, global_params, norm_bound: float):
    """Return ``w_g + clip(w_i − w_g)`` with the diff scaled to at most
    ``norm_bound`` in global L2 norm (exactly the reference's
    ``weight_diff / max(1, ||diff||/bound)``)."""
    diff = jax.tree.map(jnp.subtract, client_params, global_params)
    norm = tree_global_norm(diff)
    scale = 1.0 / jnp.maximum(1.0, norm / norm_bound)
    return jax.tree.map(lambda g, d: g + d * scale, global_params, diff)


def add_gaussian_noise(params, rng, stddev: float):
    """Weak-DP Gaussian mechanism on the aggregated model
    (robust_aggregation.py:49-53)."""
    leaves, treedef = jax.tree.flatten(params)
    rngs = jax.random.split(rng, len(leaves))
    noised = [
        p + stddev * jax.random.normal(r, p.shape, p.dtype)
        for p, r in zip(leaves, rngs)
    ]
    return jax.tree.unflatten(treedef, noised)
