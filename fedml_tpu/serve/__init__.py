"""Multi-tenant adapter serving — the inference half of the north star.

The training side personalizes millions of adapter-only models
(models/adapter.PersonalAdapterStore, algos/fedadapter); this package
serves them: thousands of *different* personalized models share one
batched frozen-base forward (serve.forward), a micro-batching request
plane admits/sheds/batches live traffic (serve.plane), and a versioned
rollout loop publishes new globals from the training fleet behind a
shadow-eval regression gate with one-step rollback (serve.rollout).
docs/SERVING.md is the operator story.
"""

from fedml_tpu.serve.forward import (FLASH_CROSSOVER_T, AdapterDecoder,
                                     ServeForward, pick_attention,
                                     stacked_tree_of)
from fedml_tpu.serve.plane import (ServeManager, ServeOverload, ServeRefused,
                                   ServeRequest, ServeSocketServer)
from fedml_tpu.serve.rollout import RolloutCoordinator, StaleEpochError

__all__ = [
    "FLASH_CROSSOVER_T",
    "AdapterDecoder",
    "RolloutCoordinator",
    "ServeForward",
    "ServeManager",
    "ServeOverload",
    "ServeRefused",
    "ServeRequest",
    "ServeSocketServer",
    "StaleEpochError",
    "pick_attention",
    "stacked_tree_of",
]
