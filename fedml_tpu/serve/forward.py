"""Batched multi-adapter inference over ONE frozen-base dispatch.

The serving problem at FedML scale: every request belongs to a
*different* personalized model (a per-client LoRA adapter row in
:class:`~fedml_tpu.models.adapter.PersonalAdapterStore`), but the frozen
transformer base — 99%+ of the FLOPs — is shared by all of them. Serving
per request would pay one dispatch + one unbatched forward per user;
here ``B`` requests ride a single jitted program: the base enters as
jit-captured device constants (the ``adapter_model_fns`` holder), the
``B`` adapter rows enter as stacked ``[B, ...]`` leaves, and ``vmap``
lifts the shared-base matmuls to batched matmuls against one weight
while the per-row LoRA pairs contract per row
(:func:`~fedml_tpu.models.transformer.lora_delta_batched`).

Bitwise contracts (test-pinned, the PR 15 identity invariant moved onto
the read path):

- the batched forward at ``B=1`` equals the per-request jitted forward
  bit-for-bit;
- a row whose adapter vector is all-zero (rank-0 / never-personalized
  under a zero global) reproduces the DENSE model byte-identically;
- right-padding the token row and zero-padding the batch change no real
  row's logits (causal attention + row-independent vmap), so the plane
  can pad every micro-batch to one compiled ``[max_batch, seq_len]``
  shape.

For tokens/s the module also carries :class:`AdapterDecoder`, a
KV-cached prefill + per-step decode over the SAME merged params —
single-token steps never recompute the prompt. Attention for the
full-sequence path follows the flash-attention sweep: causal flash above
the measured crossover (``T >= 2048``, bench ``flash_attention_sweep``),
dense below it (:func:`pick_attention`).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models.transformer import lora_delta_batched
from fedml_tpu.trainer.local import NetState

#: Measured flash-vs-dense crossover on the bench sweep
#: (bench.py flash_attention_sweep; docs/EXECUTION.md): the pallas fused
#: kernel wins from T≈2048 with bf16 activations, dense wins below.
FLASH_CROSSOVER_T = 2048


def pick_attention(seq_len: int, crossover: int = FLASH_CROSSOVER_T) -> str:
    """``attn=`` spec for a serving model at this sequence length: causal
    flash (fedml_tpu.ops.flash_attention) where the sweep says it wins,
    dense fallback below the crossover."""
    return "flash" if int(seq_len) >= int(crossover) else "dense"


def stacked_tree_of(vecs, spec):
    """``[B, D]`` flat adapter vectors → adapter tree with ``[B, ...]``
    leaves (the batched twin of ``comm.codec.vector_to_tree_np``): per
    leaf one reshape of the row slice, no per-row Python loop."""
    vecs = np.asarray(vecs, np.float32)
    if vecs.ndim != 2:
        raise ValueError(f"expected [B, D] adapter vectors, got {vecs.shape}")
    b = vecs.shape[0]
    total = int(sum(spec.sizes))
    if vecs.shape[1] != total:
        raise ValueError(
            f"adapter vectors have dim {vecs.shape[1]} but the spec "
            f"declares {total}")
    leaves, off = [], 0
    for shape, dtype, size in zip(spec.shapes, spec.dtypes, spec.sizes):
        leaves.append(vecs[:, off:off + size]
                      .reshape((b,) + tuple(shape)).astype(np.dtype(dtype)))
        off += size
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


class ServeForward:
    """The jitted batched multi-adapter forward over one frozen base.

    ``fns`` is the :class:`~fedml_tpu.models.adapter.AdapterFns` whose
    holder already carries the frozen base; ``template_adapters`` fixes
    the adapter tree structure (and hence the flat dim the store rows
    must match). ``batched(stacked, tokens)`` is the serving path;
    ``sequential(adapters, tokens_row)`` is the per-request baseline the
    B=1 bitwise pin (and the bench A/B) runs against.
    """

    def __init__(self, fns, template_adapters):
        from fedml_tpu.core.compression import tree_spec

        self.fns = fns
        self.spec = tree_spec(template_adapters)
        self.dim = int(sum(self.spec.sizes))

        def row(adapters, toks):
            logits, _ = fns.apply(NetState(adapters, {}), toks[None],
                                  train=False)
            return logits[0]

        #: [B,...]-stacked adapters + [B, T] tokens -> [B, T, V] logits;
        #: ONE dispatch for B personalized models.
        self.batched = jax.jit(jax.vmap(row))
        #: one adapter tree + [T] tokens -> [T, V]; the per-request path.
        self.sequential = jax.jit(row)

    def stacked_tree(self, vecs):
        """``[B, D]`` store rows → the batched forward's adapter input."""
        return stacked_tree_of(vecs, self.spec)

    def prefill(self, vecs, tokens):
        """Serve ``B`` requests in one dispatch: gathered ``[B, D]`` rows
        + ``[B, T]`` int32 tokens → ``[B, T, V]`` float32 logits."""
        return self.batched(self.stacked_tree(vecs),
                            jnp.asarray(tokens, jnp.int32))

    def prefill_sequential(self, vecs, tokens):
        """The one-adapter-at-a-time baseline: same inputs, one dispatch
        PER ROW (what serving without this plane would pay). Bench A/B
        arm and bitwise oracle for the B=1 pin."""
        tokens = np.asarray(tokens, np.int32)
        out = []
        for i in range(tokens.shape[0]):
            tree = self._row_tree(vecs, i)
            out.append(self.sequential(tree, jnp.asarray(tokens[i])))
        return jnp.stack(out)

    def _row_tree(self, vecs, i):
        from fedml_tpu.comm.codec import vector_to_tree_np

        return vector_to_tree_np(np.asarray(vecs[i], np.float32), self.spec)


class _DecodeCache(NamedTuple):
    """Per-layer KV cache: ``k``/``v`` are ``[L, B, T_max, H, Dh]``;
    ``pos`` is the PER-ROW ``[B]`` count of filled positions — rows with
    different true prompt lengths decode from their own last token, so
    the plane's right-padding stays inert through decode."""

    k: Any
    v: Any
    pos: Any


def _layer_norm(x, p):
    """flax ``nn.LayerNorm`` twin (eps 1e-6, scale + bias)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * p["scale"] + p["bias"]


class AdapterDecoder:
    """KV-cached greedy decode over the merged (base + per-row adapter)
    params — the tokens/s path: ``prefill`` runs the prompt once and
    fills the cache; each ``step`` pays a single-position forward whose
    attention reads the cache instead of recomputing the prompt.

    The stack is evaluated functionally from the param tree the flax
    module owns (same names, same math: pre-LN blocks, causal attention
    at ``1/sqrt(d_head)``, gelu MLP, f32 logits head), with the per-row
    LoRA residuals applied through the SAME
    :func:`~fedml_tpu.models.transformer.lora_delta_batched` expression
    as the batched forward. Decode logits are pinned against the full
    forward (tests/test_serve.py) — the cache is an optimization, not a
    different model.
    """

    def __init__(self, model, fns, template_adapters, *,
                 max_len: Optional[int] = None):
        from fedml_tpu.core.compression import tree_spec

        self.model = model
        self.fns = fns
        self.spec = tree_spec(template_adapters)
        self.n_heads = int(model.n_heads)
        self.n_layers = int(model.n_layers)
        self.d_model = int(model.d_model)
        self.alpha = float(model.adapter_alpha)
        self.max_len = int(max_len or model.max_len)
        # One jitted program per static step count: the prompt length(s)
        # and steps=1 for decode — the cache shape keys the rest.
        self._jit_run = jax.jit(self._run, static_argnames=("steps",))

    # -- merged functional stack ---------------------------------------

    def _delta(self, ad, site, x):
        a = ad.get(f"lora_{site}_a")
        if a is None:
            return None
        b = ad[f"lora_{site}_b"]
        return lora_delta_batched(a, b, x, alpha=self.alpha,
                                  rank=int(a.shape[-1]))

    def _block(self, base, ad, x, ck, cv, pos):
        """One pre-LN block over ``x [B, S, d]`` with the KV cache
        (``pos [B]`` per-row write offsets); returns updated
        ``(x, ck, cv)`` (``ck``/``cv`` ``[B, T, H, Dh]``)."""
        h = _layer_norm(x, base["LayerNorm_0"])
        mha, mad = base["MHA_0"], (ad or {}).get("MHA_0", {})
        qkv = h @ mha["Dense_0"]["kernel"]
        d = self._delta(mad, "qkv", h)
        if d is not None:
            qkv = qkv + d
        q, k, v = jnp.split(qkv, 3, axis=-1)
        bsz, s, _ = q.shape
        hd = self.d_model // self.n_heads
        shp = (bsz, s, self.n_heads, hd)
        q, k, v = q.reshape(shp), k.reshape(shp), v.reshape(shp)
        upd = jax.vmap(lambda c, new, p: jax.lax.dynamic_update_slice(
            c, new, (p, 0, 0)))
        ck = upd(ck, k, pos)
        cv = upd(cv, v, pos)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck) / jnp.sqrt(
            jnp.asarray(hd, q.dtype))
        # Causal over ABSOLUTE per-row positions: row b's query i sits at
        # pos[b]+i, key j is valid iff j <= pos[b]+i (unfilled cache
        # slots — and a short row's stale prompt-pad slots — live beyond
        # pos[b]+S-1, so the same inequality masks them).
        qpos = pos[:, None] + jnp.arange(s)[None, :]
        keep = jnp.arange(ck.shape[1])[None, None, :] <= qpos[:, :, None]
        scores = jnp.where(keep[:, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, cv).reshape(bsz, s,
                                                            self.d_model)
        out = o @ mha["Dense_1"]["kernel"]
        d = self._delta(mad, "out", o)
        if d is not None:
            out = out + d
        x = x + out
        h = _layer_norm(x, base["LayerNorm_1"])
        up = h @ base["Dense_0"]["kernel"] + base["Dense_0"]["bias"]
        d = self._delta(ad or {}, "mlp_in", h)
        if d is not None:
            up = up + d
        up = jax.nn.gelu(up)
        down = up @ base["Dense_1"]["kernel"] + base["Dense_1"]["bias"]
        d = self._delta(ad or {}, "mlp_out", up)
        if d is not None:
            down = down + d
        return x + down, ck, cv

    def _run(self, stacked, tokens, cache, *, steps: int):
        """``steps`` positions starting at the per-row ``cache.pos``:
        prompt prefill (``steps = T0``, empty cache) and single-token
        decode (``steps = 1``) are the same traced program at different
        static shapes. Returns ``(logits [B, steps, V], cache')``."""
        base = self.fns.holder["base"]
        pos = cache.pos
        x = (base["Embed_0"]["embedding"][tokens]
             + base["Embed_1"]["embedding"][pos[:, None]
                                            + jnp.arange(steps)[None]])
        ks, vs = [], []
        for li in range(self.n_layers):
            name = f"Block_{li}"
            x, ck, cv = self._block(base[name], stacked.get(name), x,
                                    cache.k[li], cache.v[li], pos)
            ks.append(ck)
            vs.append(cv)
        x = _layer_norm(x, base["LayerNorm_0"])
        logits = (x @ base["Dense_0"]["kernel"]).astype(jnp.float32)
        return logits, _DecodeCache(jnp.stack(ks), jnp.stack(vs),
                                    pos + steps)

    # -- public surface -------------------------------------------------

    def empty_cache(self, batch: int, max_len: Optional[int] = None):
        t = int(max_len or self.max_len)
        hd = self.d_model // self.n_heads
        shape = (self.n_layers, batch, t, self.n_heads, hd)
        return _DecodeCache(jnp.zeros(shape, jnp.float32),
                            jnp.zeros(shape, jnp.float32),
                            jnp.zeros(batch, jnp.int32))

    def prefill(self, stacked, tokens, lens=None,
                max_len: Optional[int] = None):
        """Prompt pass: ``[B, T0]`` tokens → TRUE-last-position logits
        ``[B, V]`` + the filled cache. ``lens [B]`` gives per-row true
        prompt lengths for right-padded batches: the returned logits are
        gathered at ``lens-1`` (never a pad position) and the cache's
        per-row write offsets rewind to ``lens``, so decode overwrites a
        short row's pad slots before its causal mask can reach them.
        ``lens=None`` means every row is full length."""
        tokens = jnp.asarray(tokens, jnp.int32)
        cache = self.empty_cache(tokens.shape[0], max_len)
        logits, cache = self._jit_run(stacked, tokens, cache,
                                      steps=int(tokens.shape[1]))
        if lens is None:
            return logits[:, -1], cache
        lens = jnp.asarray(lens, jnp.int32)
        last = jnp.take_along_axis(logits, (lens - 1)[:, None, None],
                                   axis=1)[:, 0]
        return last, cache._replace(pos=lens)

    def step(self, stacked, token, cache):
        """One decode position: ``[B]`` tokens → ``[B, V]`` logits."""
        logits, cache = self._jit_run(stacked, token[:, None], cache,
                                      steps=1)
        return logits[:, 0], cache

    def generate(self, stacked, tokens, n_new: int, lens=None):
        """Greedy decode ``n_new`` tokens per row (``lens`` as in
        :meth:`prefill` — right-padded rows continue from their true
        last token). Returns ``[B, n_new]`` int32 — the tokens/s
        workload (one cached step per token)."""
        logits, cache = self.prefill(stacked, tokens, lens=lens)
        out = []
        for _ in range(int(n_new)):
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out.append(nxt)
            logits, cache = self.step(stacked, nxt, cache)
        return jnp.stack(out, axis=1)
