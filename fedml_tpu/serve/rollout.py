"""Versioned adapter rollout: publish → shadow → promote | rollback.

The training fleet keeps producing new global adapters; the serving
plane must pick them up WITHOUT trusting them — an aggregation that
diverged (poisoned cohort, codec bug, NaN blow-up) must never become
what live traffic is answered with. ``RolloutCoordinator`` is that
gate:

- :meth:`publish` stages a candidate version behind an EPOCH FENCE
  (the PR 5 server-epoch discipline): a snapshot published under an
  epoch at or below the last accepted one is a zombie — a pre-restart
  coordinator's in-flight publish — and raises :class:`StaleEpochError`
  instead of racing the new incarnation.
- While staged, the plane mirrors live traffic through BOTH the live
  global and the candidate (serve/plane.py ``serve.shadow`` spans) and
  accumulates next-token CE per arm.
- :meth:`try_promote` reads the mirrored scores and promotes ONLY when
  the candidate saw enough shadow tokens, its CE is finite, and it does
  not regress the live CE beyond ``regression_tol``. Promotion keeps
  the displaced version as the one-step rollback target.
- :meth:`rollback` restores that displaced version BIT-EQUAL (the
  adapter vector round-trips through the checkpoint as raw float32 —
  test-pinned).

Every transition persists a fixed-shape payload through the PR 5
:class:`~fedml_tpu.obs.checkpoint.CheckpointManager` before it takes
effect on the plane, so a coordinator restart mid-promotion resumes on
the fenced epoch with the same live/candidate/rollback state (orbax
restore is structure-checked; fixed shapes make every snapshot
restorable by every incarnation).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class StaleEpochError(RuntimeError):
    """Publish fenced off: the snapshot's epoch is not newer than the
    last accepted one — a previous coordinator incarnation's in-flight
    publish, refused so it cannot race the current one."""


class RolloutCoordinator:
    """Shadow-gated version control for the serving plane's live global.

    ``manager`` is the :class:`~fedml_tpu.serve.plane.ServeManager`
    whose live/shadow versions this coordinator owns. ``directory``
    (optional) persists every transition via
    :class:`~fedml_tpu.obs.checkpoint.CheckpointManager`; on
    construction an existing state is restored INTO the manager —
    restart-resume is the constructor, not a separate code path.

    ``regression_tol`` is relative: candidate CE may exceed live CE by
    at most ``live_ce * regression_tol``. ``min_shadow_tokens`` keeps a
    lucky two-token mirror from promoting anything.
    """

    def __init__(self, manager, *, directory: Optional[str] = None,
                 regression_tol: float = 0.02,
                 min_shadow_tokens: int = 32):
        self.manager = manager
        self.regression_tol = float(regression_tol)
        self.min_shadow_tokens = int(min_shadow_tokens)
        self.dim = int(manager.fwd.dim)
        self._mgr = None
        self._seq = 0  # checkpoint step allocator (monotonic)
        self.fence_epoch = -1
        self.live_version = int(manager.live_version)
        self._live_vec = manager._vec(manager.live_adapters())
        self.prev_version: Optional[int] = None
        self._prev_vec = np.zeros(self.dim, np.float32)
        self.cand_version: Optional[int] = None
        self._cand_vec = np.zeros(self.dim, np.float32)
        if directory is not None:
            from fedml_tpu.obs.checkpoint import CheckpointManager

            self._mgr = CheckpointManager(directory, max_to_keep=3)
            self._restore()

    # -- persistence -----------------------------------------------------

    def _payload(self) -> dict:
        """Fixed-shape snapshot: absent versions ride as ``-1`` + zero
        vectors so every incarnation can ``restore(like=)`` every step."""
        return {
            "seq": np.asarray(self._seq, np.int64),
            "fence_epoch": np.asarray(self.fence_epoch, np.int64),
            "live_version": np.asarray(self.live_version, np.int64),
            "live_vec": np.asarray(self._live_vec, np.float32),
            "prev_version": np.asarray(
                -1 if self.prev_version is None else self.prev_version,
                np.int64),
            "prev_vec": np.asarray(self._prev_vec, np.float32),
            "cand_version": np.asarray(
                -1 if self.cand_version is None else self.cand_version,
                np.int64),
            "cand_vec": np.asarray(self._cand_vec, np.float32),
        }

    def _persist(self) -> None:
        """Durable-then-visible: the snapshot commits BEFORE the
        transition lands on the plane, so a crash between the two
        resumes on the new state, never a half-applied one."""
        if self._mgr is None:
            return
        self._seq += 1
        self._mgr.save(self._seq, self._payload())

    def _restore(self) -> None:
        restored = self._mgr.restore(like=self._payload())
        if restored is None:
            return
        self._seq = int(restored["seq"])
        self.fence_epoch = int(restored["fence_epoch"])
        self.live_version = int(restored["live_version"])
        self._live_vec = np.asarray(restored["live_vec"], np.float32)
        pv = int(restored["prev_version"])
        self.prev_version = None if pv < 0 else pv
        self._prev_vec = np.asarray(restored["prev_vec"], np.float32)
        cv = int(restored["cand_version"])
        self.cand_version = None if cv < 0 else cv
        self._cand_vec = np.asarray(restored["cand_vec"], np.float32)
        self.manager.set_live(self.live_version, self._tree(self._live_vec))
        if self.cand_version is not None:
            # Resume mid-promotion: re-stage the candidate shadow. CE
            # accumulators restart from zero — mirrored evidence from the
            # dead incarnation is not trusted across a restart.
            self.manager.set_shadow(self.cand_version,
                                    self._tree(self._cand_vec))
        else:
            self.manager.set_shadow(None)

    def _tree(self, vec: np.ndarray):
        from fedml_tpu.comm.codec import vector_to_tree_np

        return vector_to_tree_np(np.asarray(vec, np.float32),
                                 self.manager.fwd.spec)

    # -- transitions -----------------------------------------------------

    def publish(self, adapters, *, epoch: int) -> int:
        """Stage ``adapters`` (a training-fleet snapshot taken under
        server ``epoch``) as the shadow candidate. Returns the candidate
        version id. Replaces any currently staged candidate — the fleet
        moved on, so should the gate."""
        epoch = int(epoch)
        if epoch <= self.fence_epoch:
            raise StaleEpochError(
                f"publish under epoch {epoch} refused: fence is at "
                f"{self.fence_epoch} — a newer coordinator incarnation "
                "already accepted a snapshot from this epoch or later")
        self.fence_epoch = epoch
        version = max(self.live_version,
                      self.cand_version if self.cand_version is not None
                      else -1) + 1
        self.cand_version = version
        self._cand_vec = self.manager._vec(adapters)
        self._persist()
        self.manager.set_shadow(version, self._tree(self._cand_vec))
        return version

    def try_promote(self) -> dict:
        """Promote the staged candidate iff the shadow gate passes.
        Returns the verdict dict (``promoted`` bool + the scores it was
        judged on); no candidate staged → ``{"promoted": False,
        "reason": "no_candidate"}``. A blocked candidate STAYS staged —
        more mirrored traffic may still clear (or confirm) it; call
        :meth:`discard` to drop it."""
        if self.cand_version is None:
            return {"promoted": False, "reason": "no_candidate"}
        scores = self.manager.shadow_scores()
        verdict = dict(scores, promoted=False,
                       candidate_version=self.cand_version)
        if scores["tokens"] < self.min_shadow_tokens:
            verdict["reason"] = (
                f"insufficient_shadow_traffic ({scores['tokens']} < "
                f"{self.min_shadow_tokens} tokens)")
            return verdict
        if not np.isfinite(scores["cand_ce"]):
            verdict["reason"] = "candidate_ce_not_finite"
            return verdict
        limit = scores["live_ce"] * (1.0 + self.regression_tol)
        if np.isfinite(scores["live_ce"]) and scores["cand_ce"] > limit:
            verdict["reason"] = (
                f"regression (cand_ce {scores['cand_ce']:.4f} > "
                f"{limit:.4f})")
            return verdict
        # Gate passed: displaced live becomes the one-step rollback
        # target; persist, then flip the plane.
        self.prev_version = self.live_version
        self._prev_vec = self._live_vec
        self.live_version = self.cand_version
        self._live_vec = self._cand_vec
        self.cand_version = None
        self._cand_vec = np.zeros(self.dim, np.float32)
        self._persist()
        self.manager.set_shadow(None)
        self.manager.set_live(self.live_version, self._tree(self._live_vec))
        verdict.update(promoted=True, reason="ok",
                       live_version=self.live_version)
        return verdict

    def discard(self) -> None:
        """Drop the staged candidate without promoting."""
        if self.cand_version is None:
            return
        self.cand_version = None
        self._cand_vec = np.zeros(self.dim, np.float32)
        self._persist()
        self.manager.set_shadow(None)

    def rollback(self) -> int:
        """One-step rollback: the previously displaced version becomes
        live again, BIT-EQUAL to what it was (raw float32 vector round-
        trip — test-pinned). The rolled-back-from version becomes the
        new rollback target, so a mistaken rollback is itself one step
        reversible. No displaced version recorded → RuntimeError."""
        if self.prev_version is None:
            raise RuntimeError(
                "no previous version to roll back to: nothing was ever "
                "promoted over")
        self.prev_version, self.live_version = (self.live_version,
                                                self.prev_version)
        self._prev_vec, self._live_vec = self._live_vec, self._prev_vec
        self._persist()
        self.manager.set_live(self.live_version, self._tree(self._live_vec))
        return self.live_version

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
