"""The request plane: bounded admission, micro-batching, shadow mirror.

``ServeManager`` turns single-request traffic into the batched forward's
unit of work: a bounded queue admits or SHEDS (a full queue refuses
loudly — queueing without bound just moves the overload into latency),
and a micro-batcher thread forms batches on a deadline-or-batch-full
policy — the first request opens a window of ``deadline_s``; the batch
closes when ``max_batch`` requests arrived or the window expired,
whichever is first. Every batch is padded to one compiled
``[max_batch, seq_len]`` shape (padding rows/positions are bitwise
inert — serve/forward.py), so steady-state serving never re-jits.

Request lifecycle is span-traced on the PR 11 tracer
(``serve.gather`` / ``serve.prefill`` / ``serve.decode`` /
``serve.shadow``) and metered in the metrics registry:
``serve/admitted``, ``serve/shed``, ``serve/refused``, ``serve/served``
counters plus ``serve/latency_ms`` and ``serve/batch_fill`` histograms
(p50/p95 via the registry snapshot). docs/SERVING.md carries the table.

The plane also owns the LIVE global adapter version (what
never-personalized rows fall back to) and an optional SHADOW candidate:
while a candidate is staged (serve/rollout.py), every batch's token
stream is mirrored through BOTH globals and their next-token CE
accumulates — the regression signal the rollout gate reads. Mirroring
costs two extra batched forwards and never touches what live traffic is
answered with.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.obs import trace as obs_trace


class ServeOverload(RuntimeError):
    """Admission refused: the bounded request queue is full (counted in
    ``serve/shed``). Callers retry with backoff or spill to another
    replica — the plane never queues unboundedly."""


class ServeRefused(RuntimeError):
    """Request malformed for this plane (counted in ``serve/refused``):
    wrong token length, unknown client id, or plane shut down."""


_STOP = object()


class ServeRequest:
    """One admitted request: resolves to the per-request logits slice
    (``[true_len, V]``) and, when ``max_new_tokens > 0``, the greedy
    continuation. ``result()`` blocks the caller until the micro-batch
    that carried it completes."""

    __slots__ = ("client_id", "tokens", "max_new_tokens", "t_submit",
                 "_done", "_logits", "_generated", "_error")

    def __init__(self, client_id: int, tokens, max_new_tokens: int,
                 t_submit: float):
        self.client_id = int(client_id)
        self.tokens = np.asarray(tokens, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.t_submit = float(t_submit)
        self._done = threading.Event()
        self._logits = None
        self._generated = None
        self._error = None

    def result(self, timeout: Optional[float] = None):
        """``(logits [true_len, V], generated [max_new_tokens] | None)``."""
        if not self._done.wait(timeout):
            raise TimeoutError("serve request not completed in time")
        if self._error is not None:
            raise self._error
        return self._logits, self._generated


class ServeManager:
    """Micro-batching front end over one :class:`~fedml_tpu.serve.
    forward.ServeForward` (+ optional :class:`~fedml_tpu.serve.forward.
    AdapterDecoder` for decode traffic).

    ``store`` is the :class:`~fedml_tpu.models.adapter.
    PersonalAdapterStore` request rows gather from (``None`` = every row
    serves the live global — the FedBuff-global serving mode);
    ``live_adapters`` seeds version 0. ``start()`` spawns the batcher
    thread; tests may instead drive :meth:`serve_batch` synchronously.
    """

    def __init__(self, forward, store, live_adapters, *,
                 seq_len: int = 16, max_batch: int = 32,
                 deadline_s: float = 0.005, queue_cap: int = 256,
                 decoder=None, registry=None, clock=None,
                 live_version: int = 0):
        import time

        from fedml_tpu.obs.registry import MetricsRegistry

        self.fwd = forward
        self.store = store
        self.decoder = decoder
        self.seq_len = int(seq_len)
        self.max_batch = int(max_batch)
        self.deadline_s = float(deadline_s)
        self.registry = registry if registry is not None else MetricsRegistry()
        self._clock = clock if clock is not None else time.monotonic
        self._q: "queue.Queue" = queue.Queue(maxsize=int(queue_cap))
        self._lock = threading.Lock()
        self._live_version = int(live_version)
        self._live = jax.tree.map(np.asarray, live_adapters)
        self._live_vec = self._vec(self._live)
        self._shadow = None  # (version, adapters, vec) while staged
        # mirrored-traffic CE sums: [live_ce, live_tok, cand_ce, cand_tok]
        self._shadow_sums = np.zeros(4, np.float64)
        self._thread = None
        self._running = False
        self._ce = jax.jit(self._ce_fn)

    # -- version surface (rollout loop) --------------------------------

    def _vec(self, adapters) -> np.ndarray:
        from fedml_tpu.comm.codec import tree_to_vector_np

        return tree_to_vector_np(adapters)

    @property
    def live_version(self) -> int:
        with self._lock:
            return self._live_version

    def live_adapters(self):
        with self._lock:
            return self._live

    def set_live(self, version: int, adapters) -> None:
        """Swap the global adapter version live traffic falls back to.
        Takes effect at the next batch boundary — in-flight batches
        finish on the version they gathered."""
        adapters = jax.tree.map(np.asarray, adapters)
        vec = self._vec(adapters)
        with self._lock:
            self._live_version = int(version)
            self._live = adapters
            self._live_vec = vec

    def set_shadow(self, version: Optional[int], adapters=None) -> None:
        """Stage (or clear, with ``version=None``) the shadow candidate;
        resets the mirrored-traffic CE accumulators."""
        staged = None
        if version is not None:
            adapters = jax.tree.map(np.asarray, adapters)
            staged = (int(version), adapters, self._vec(adapters))
        with self._lock:
            self._shadow = staged
            self._shadow_sums = np.zeros(4, np.float64)

    def shadow_scores(self) -> dict:
        """Mirrored-traffic next-token CE per arm: ``live_ce`` /
        ``cand_ce`` means and the token count both accumulated over."""
        with self._lock:
            s = self._shadow_sums.copy()
            version = self._shadow[0] if self._shadow is not None else None
        return {
            "candidate_version": version,
            "tokens": int(s[1]),
            "live_ce": float(s[0] / s[1]) if s[1] else float("nan"),
            "cand_ce": float(s[2] / s[3]) if s[3] else float("nan"),
        }

    # -- admission ------------------------------------------------------

    def submit(self, client_id: int, tokens,
               max_new_tokens: int = 0) -> ServeRequest:
        """Admit one request (non-blocking). Sheds on a full queue,
        refuses malformed input; both are counted, never silent."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or not 0 < tokens.shape[0] <= self.seq_len:
            self.registry.counter("serve/refused").inc()
            raise ServeRefused(
                f"request tokens must be [1..{self.seq_len}] ints, got "
                f"shape {tokens.shape}")
        n_new = int(max_new_tokens)
        if n_new < 0:
            self.registry.counter("serve/refused").inc()
            raise ServeRefused(f"max_new_tokens must be >= 0, got {n_new}")
        if n_new and self.decoder is not None \
                and self.seq_len + n_new > self.decoder.max_len:
            # Past max_len the decoder's positional gather / cache writes
            # would be silently clamped by JAX OOB semantics — refuse
            # loudly instead of serving garbage tokens.
            self.registry.counter("serve/refused").inc()
            raise ServeRefused(
                f"max_new_tokens {n_new} exceeds the decoder budget "
                f"(seq_len {self.seq_len} + {n_new} > max_len "
                f"{self.decoder.max_len})")
        req = ServeRequest(client_id, tokens, n_new, self._clock())
        # Admission and shutdown race on _running: flag + enqueue under
        # the lock so no request slips in after close() starts draining.
        with self._lock:
            if not self._running and self._thread is not None:
                self.registry.counter("serve/refused").inc()
                raise ServeRefused("serve plane is shut down")
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.registry.counter("serve/shed").inc()
                raise ServeOverload(
                    f"request queue full ({self._q.maxsize}): shedding — "
                    "scale replicas or raise queue_cap") from None
        self.registry.counter("serve/admitted").inc()
        return req

    def request(self, client_id: int, tokens, max_new_tokens: int = 0,
                timeout: float = 30.0):
        """Blocking convenience: submit + wait for the batch."""
        return self.submit(client_id, tokens,
                           max_new_tokens).result(timeout)

    # -- micro-batcher ---------------------------------------------------

    def start(self) -> "ServeManager":
        if self._thread is None:
            self._running = True
            self._thread = threading.Thread(target=self._serve_loop,
                                            daemon=True,
                                            name="serve-batcher")
            self._thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            self._running = False
        if self._thread is not None:
            self._q.put(_STOP)
            self._thread.join(timeout=30.0)
        # Drain stragglers (admitted concurrently with shutdown, or
        # queued behind _STOP when the batcher stopped mid-collection):
        # complete them with a refusal so no waiter blocks to timeout.
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is _STOP:
                continue
            self.registry.counter("serve/refused").inc()
            req._error = ServeRefused("serve plane shut down")
            req._done.set()

    def __enter__(self) -> "ServeManager":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _serve_loop(self) -> None:
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if not self._running:
                    return
                continue
            if first is _STOP:
                return
            batch = [first]
            stop = False
            deadline = self._clock() + self.deadline_s
            while len(batch) < self.max_batch:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=max(remaining, 1e-4))
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            self.serve_batch(batch)
            if stop:
                return

    # -- batch execution -------------------------------------------------

    def serve_batch(self, batch) -> None:
        """Serve one micro-batch end to end (also the synchronous test
        entry). Never raises: a batch failure completes every request
        with the error instead of wedging its waiters."""
        try:
            self._serve_batch(batch)
        except Exception as err:  # noqa: BLE001 - fanned out to waiters
            for req in batch:
                req._error = ServeRefused(f"batch failed: {err!r}")
                req._done.set()

    def _serve_batch(self, batch) -> None:
        tracer = obs_trace.active()
        n = len(batch)
        with self._lock:
            live = self._live
            live_vec = self._live_vec
            shadow = self._shadow
        tokens = np.zeros((self.max_batch, self.seq_len), np.int32)
        # Pad rows get length 1 (a lone token 0): keeps the decoder's
        # per-row lens-1 indexing in range while the mirror mask still
        # counts them as zero next-token targets. Real rows overwrite.
        lens = np.ones(self.max_batch, np.int32)
        for i, req in enumerate(batch):
            lens[i] = req.tokens.shape[0]
            tokens[i, :lens[i]] = req.tokens
        with tracer.span("serve.gather", cat="serve", batch=n):
            vecs = np.zeros((self.max_batch, self.fwd.dim), np.float32)
            if self.store is not None:
                ids = np.asarray([r.client_id for r in batch], np.int64)
                vecs[:n] = self.store.gather(ids, live)
            else:
                vecs[:n] = live_vec[None]
            stacked = self.fwd.stacked_tree(vecs)
        with tracer.span("serve.prefill", cat="serve", batch=n):
            logits = self.fwd.batched(stacked, jnp.asarray(tokens))
            logits = np.asarray(logits)
        generated = None
        n_new = max((r.max_new_tokens for r in batch), default=0)
        if n_new and self.decoder is not None:
            with tracer.span("serve.decode", cat="serve", batch=n,
                             new_tokens=n_new):
                generated = np.asarray(
                    self.decoder.generate(stacked, tokens, n_new,
                                          lens=lens))
        if shadow is not None:
            with tracer.span("serve.shadow", cat="serve", batch=n,
                             candidate=shadow[0]):
                self._mirror(tokens, lens, live_vec, shadow[2])
        now = self._clock()
        fill = self.registry.histogram("serve/batch_fill", lo=1.0)
        lat = self.registry.histogram("serve/latency_ms")
        fill.record(n)
        for i, req in enumerate(batch):
            req._logits = logits[i, :lens[i]]
            if generated is not None and req.max_new_tokens:
                req._generated = generated[i, :req.max_new_tokens]
            req._done.set()
            lat.record(max((now - req.t_submit) * 1e3, 1e-6))
            self.registry.counter("serve/served").inc()

    def _ce_fn(self, stacked, tokens, mask):
        """Summed next-token CE + token count over a mirrored batch."""
        logits = self.fwd.batched(stacked, tokens)
        lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = tokens[:, 1:]
        nll = -jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
        m = mask[:, 1:].astype(jnp.float32)
        return jnp.sum(nll * m), jnp.sum(m)

    def _mirror(self, tokens, lens, live_vec, cand_vec) -> None:
        """Run the batch's token stream through BOTH globals and
        accumulate next-token CE — the shadow gate's regression signal.
        Mirrored traffic only ever affects the accumulators. Runs on the
        already-padded ``[max_batch, seq_len]`` tokens with the length
        mask zeroing pad rows/positions, so the jitted CE compiles ONCE
        at the plane's fixed shape — a half-full batch while a candidate
        is staged never triggers a fresh XLA compile on the serving
        thread."""
        b = tokens.shape[0]
        mask = (np.arange(self.seq_len)[None, :] < lens[:, None])
        toks = jnp.asarray(tokens)
        m = jnp.asarray(mask)
        sums = np.zeros(4, np.float64)
        live_tree = self.fwd.stacked_tree(np.tile(live_vec, (b, 1)))
        ce, cnt = self._ce(live_tree, toks, m)
        sums[0], sums[1] = float(ce), float(cnt)
        cand_tree = self.fwd.stacked_tree(np.tile(cand_vec, (b, 1)))
        ce, cnt = self._ce(cand_tree, toks, m)
        sums[2], sums[3] = float(ce), float(cnt)
        with self._lock:
            self._shadow_sums += sums

    # -- health ----------------------------------------------------------

    def stats(self) -> dict:
        """Counter/latency snapshot (flat scalars, bench/ci-friendly)."""
        snap = self.registry.snapshot()
        return {k: v for k, v in snap.items() if k.startswith("serve/")}


class ServeSocketServer:
    """Line-delimited-JSON TCP front end over a :class:`ServeManager`
    (the ``--serve_port`` surface): one ``{"client": id, "tokens":
    [...], "max_new_tokens": n}`` request per line, one ``{"next_token":
    ..., "generated": [...]}`` reply per line. Single accept thread,
    one connection at a time — the smoke/drill front door, not a load
    balancer (docs/SERVING.md)."""

    def __init__(self, manager: ServeManager, port: int = 0,
                 host: str = "127.0.0.1"):
        import socket

        self.manager = manager
        self._sock = socket.create_server((host, int(port)))
        self._sock.settimeout(0.1)
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._thread = None

    def start(self) -> "ServeSocketServer":
        if self._thread is None:
            self._running = True
            self._thread = threading.Thread(target=self._accept_loop,
                                            daemon=True,
                                            name="serve-socket")
            self._thread.start()
        return self

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._sock.close()

    def __enter__(self) -> "ServeSocketServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _accept_loop(self) -> None:
        import socket

        while self._running:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                self._handle_conn(conn)

    def _handle_conn(self, conn) -> None:
        import json

        buf = b""
        conn.settimeout(5.0)
        while self._running:
            try:
                chunk = conn.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    msg = json.loads(line)
                    logits, gen = self.manager.request(
                        int(msg["client"]), msg["tokens"],
                        int(msg.get("max_new_tokens", 0)))
                    reply = {
                        "next_token": int(np.argmax(logits[-1])),
                        "generated": ([] if gen is None
                                      else [int(t) for t in gen]),
                    }
                except (ServeOverload, ServeRefused, KeyError,
                        ValueError) as err:
                    reply = {"error": f"{type(err).__name__}: {err}"}
                conn.sendall((json.dumps(reply) + "\n").encode())
