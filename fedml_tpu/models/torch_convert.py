"""Convert the reference's torch ``.pth`` checkpoints to flax NetStates.

The reference ships pretrained CIFAR ResNet weights as torch state_dicts
(``model/cv/pretrained/{CIFAR10,CIFAR100,CINIC10}/resnet56``, loaded by
``resnet56(pretrained=True, path=...)`` — model/cv/resnet.py:209-220,
including the DataParallel ``module.`` prefix strip). Zero egress means
those files cannot be fetched here, but torch (CPU) is available, so the
PORT is implemented and proven: :func:`convert_torch_cifar_resnet` maps a
torch ``ResNet(Bottleneck/BasicBlock, [n,n,n])`` state_dict onto
``CifarResNet(norm="bn")`` — weights, biases AND BatchNorm running stats
— and the test suite verifies converted models reproduce the torch
model's forward outputs exactly (tests/test_torch_convert.py). Point
:func:`load_torch_checkpoint` at a real reference ``.pth`` and it loads.

Layout conversions: torch conv ``(O, I, kh, kw)`` → flax HWIO
``(kh, kw, I, O)``; linear ``(O, I)`` → ``(I, O)``; BatchNorm
``weight/bias`` → ``scale/bias``; ``running_mean/var`` →
``batch_stats .../mean,var``. ``num_batches_tracked`` is dropped (flax
keeps no equivalent).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import numpy as np

from fedml_tpu.trainer.local import NetState


def _torch_key(flax_path, layers: Sequence[int]) -> str:
    """Reference torch parameter name for one CifarResNet flax path."""
    keys = [str(getattr(k, "key", k)) for k in flax_path]
    if keys[0] == "batch_stats":
        keys = keys[1:]
    head, leaf = keys[0], keys[-1]
    suffix = {"kernel": "weight", "scale": "weight", "bias": "bias",
              "mean": "running_mean", "var": "running_var"}[leaf]

    if head == "Conv_0":  # stem conv
        return "conv1.weight"
    if head == "Norm_0":  # stem norm
        return f"bn1.{suffix}"
    if head == "Dense_0":  # classifier
        return f"fc.{suffix}"
    if head.startswith(("BottleneckBlock_", "BasicBlock_")):
        blk = int(head.split("_")[1])
        stage, offset = 0, 0
        while blk - offset >= layers[stage]:
            offset += layers[stage]
            stage += 1
        prefix = f"layer{stage + 1}.{blk - offset}"
        part = keys[1]
        if part == "downsample":
            return f"{prefix}.downsample.0.weight"
        if part.startswith("Conv_"):
            return f"{prefix}.conv{int(part.split('_')[1]) + 1}.{suffix}"
        if part.startswith("Norm_"):
            j = int(part.split("_")[1])
            n_main = 3 if head.startswith("Bottleneck") else 2
            if j == n_main:  # the downsample branch's norm
                return f"{prefix}.downsample.1.{suffix}"
            return f"{prefix}.bn{j + 1}.{suffix}"
    raise KeyError(f"no torch mapping for flax path {'/'.join(keys)}")


def _convert_leaf(torch_arr: np.ndarray, flax_leaf) -> np.ndarray:
    arr = np.asarray(torch_arr)
    if arr.ndim == 4:  # conv (O, I, kh, kw) -> (kh, kw, I, O)
        arr = arr.transpose(2, 3, 1, 0)
    elif arr.ndim == 2:  # linear (O, I) -> (I, O)
        arr = arr.T
    if arr.shape != flax_leaf.shape:
        raise ValueError(
            f"converted shape {arr.shape} != model shape {flax_leaf.shape}")
    return arr.astype(np.asarray(flax_leaf).dtype)


def convert_torch_cifar_resnet(state_dict: Dict, net: NetState,
                               layers: Sequence[int] = (6, 6, 6)) -> NetState:
    """Map a reference torch CIFAR-ResNet state_dict onto ``net`` (a
    ``CifarResNet(norm="bn")`` NetState). Strict both ways: every model
    leaf must find its torch tensor, and every torch tensor (except
    ``num_batches_tracked``) must be consumed — a partially-matching
    checkpoint (wrong depth/width) raises instead of silently loading
    the common prefix."""
    sd = {k[len("module."):] if k.startswith("module.") else k: v
          for k, v in state_dict.items()
          if not k.endswith("num_batches_tracked")}
    # Refuse non-reference model geometry UP FRONT with a diagnosis,
    # not a mid-tree shape error: the s2d stem (2x2 space-to-depth, 12
    # input channels, doubled widths) and lane-padded physical twins
    # (parallel/layout.py) have no reference ``.pth`` mapping by
    # construction — the reference trained the conv stem at 3 input
    # channels and 16/32/64 stage widths. (Lane-fill layouts never need
    # conversion anyway: checkpoints live at LOGICAL shapes and the pad
    # happens inside the client step.)
    stem_kernel = net.params.get("Conv_0", {}).get("kernel")
    ref_stem = sd.get("conv1.weight")
    if stem_kernel is not None and ref_stem is not None:
        in_ch, out_ch = stem_kernel.shape[2], stem_kernel.shape[3]
        ref_out, ref_in = np.asarray(ref_stem).shape[:2]
        if (in_ch, out_ch) != (ref_in, ref_out):
            raise ValueError(
                f"model stem conv is {in_ch}->{out_ch} channels but the "
                f"torch checkpoint's conv1 is {ref_in}->{ref_out}: this "
                "net's geometry cannot map onto the reference weights "
                "(stem='s2d' variants and lane-padded physical twins "
                "have no reference checkpoint — use the reference stem, "
                "or load logical-shape checkpoints via obs/checkpoint)")
    used = set()

    def rebuild(tree):
        def visit(path, leaf):
            tk = _torch_key(path, layers)
            if tk not in sd:
                raise KeyError(
                    f"torch checkpoint is missing {tk!r} (wanted by flax "
                    f"path {'/'.join(str(getattr(k, 'key', k)) for k in path)})")
            used.add(tk)
            return _convert_leaf(sd[tk], leaf)

        return jax.tree_util.tree_map_with_path(visit, tree)

    out = NetState(rebuild(net.params), rebuild(net.model_state))
    leftover = set(sd) - used
    if leftover:
        raise ValueError(
            f"torch checkpoint has {len(leftover)} unused tensors "
            f"(first: {sorted(leftover)[:3]}) — architecture mismatch?")
    return out


def convert_torch_gkt_client(state_dict: Dict, net: NetState,
                             n_blocks: int = 1) -> NetState:
    """Map a reference GKT client-stump state_dict onto a
    ``ResNetClientStump(norm="bn")`` NetState.

    The reference's ``resnet5_56``/``resnet8_56``
    (model/cv/resnet56_gkt/resnet_client.py:206,:230) are single-stage
    nets — conv1/bn1 stem, ``layer1`` only, fc on 16·expansion features —
    loaded from the same ``{'state_dict': ...}`` + ``module.`` format as
    the full ResNets (:215-226). The stump shares the flax module naming
    of :class:`~fedml_tpu.models.resnet.CifarResNet`, so the key map is
    :func:`_torch_key` with a one-stage layers tuple."""
    return convert_torch_cifar_resnet(state_dict, net, layers=(n_blocks,))


def convert_torch_gkt_server(state_dict: Dict, net: NetState,
                             layers: Sequence[int] = (6, 6, 6)) -> NetState:
    """Map a reference GKT server-tail state_dict onto a
    ``ResNetServerTail(norm="bn")`` NetState.

    The reference server net (resnet_server.py:113-199) CONSTRUCTS a
    conv1/bn1 stem but its forward never runs it (:188-191 — the client
    supplies the 16-channel features), so its checkpoints carry stem
    tensors with no flax counterpart: they are dropped here, and the
    strict leftover check applies to everything else."""
    sd = {k[len("module."):] if k.startswith("module.") else k: v
          for k, v in state_dict.items()}
    stem = ("conv1.weight", "bn1.weight", "bn1.bias", "bn1.running_mean",
            "bn1.running_var", "bn1.num_batches_tracked")
    return convert_torch_cifar_resnet(
        {k: v for k, v in sd.items() if k not in stem}, net, layers)


def _load_state_dict(path: str) -> Dict:
    import torch

    # weights_only: the supported format is a dict of tensors — never
    # opt back into pickle code execution for externally-obtained files.
    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    sd = ckpt.get("state_dict", ckpt) if isinstance(ckpt, dict) else ckpt
    return {k: v.numpy() if hasattr(v, "numpy") else v for k, v in sd.items()}


def load_torch_checkpoint(path: str, net: NetState,
                          layers: Sequence[int] = (6, 6, 6)) -> NetState:
    """Load a reference ``.pth`` (``{'state_dict': ...}`` wrapper or a
    bare state_dict, DataParallel prefixes included) into ``net`` — the
    flax analogue of ``resnet56(pretrained=True, path=...)``."""
    return convert_torch_cifar_resnet(_load_state_dict(path), net, layers)


def load_torch_gkt_checkpoint(path: str, net: NetState, *,
                              role: str, n_blocks: int = 1,
                              layers: Sequence[int] = (6, 6, 6)) -> NetState:
    """Load a reference GKT split-ResNet ``.pth`` into the matching half:
    ``role="client"`` → :func:`convert_torch_gkt_client` (stump),
    ``role="server"`` → :func:`convert_torch_gkt_server` (tail) — the
    flax analogue of ``resnet5_56/resnet8_56/resnet56_server(pretrained=
    True, path=...)``."""
    if role not in ("client", "server"):
        raise ValueError(f"role must be 'client' or 'server', got {role!r}")
    sd = _load_state_dict(path)
    if role == "client":
        return convert_torch_gkt_client(sd, net, n_blocks=n_blocks)
    return convert_torch_gkt_server(sd, net, layers=layers)
