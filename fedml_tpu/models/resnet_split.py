"""Split ResNet-56 pair for FedGKT / split learning.

Parity targets (reference fedml_api/model/cv/resnet56_gkt/):
- Client stump (resnet_client.py:112-247): conv1(3→16,3x3,s1)+norm+relu
  emits the **extracted features** [B,32,32,16]; then layer1 (16-planes
  blocks) → global avgpool → fc gives the client's own logits. Returns
  ``(logits, features)`` — features go to the server, logits feed the KL
  distillation loss. Variants ``resnet5_56`` (BasicBlock [1]) and
  ``resnet8_56`` (Bottleneck [2]) mirror :206,:230 (the reference's layers
  lists have extra entries its forward never uses — only layer1 runs).
- Server tail (resnet_server.py:113-199): takes the 16-channel features,
  runs layer1(16)/layer2(32,s2)/layer3(64,s2) → avgpool → fc.
  ``resnet56_server`` = Bottleneck [6,6,6] (:200); ``resnet110_server`` =
  Bottleneck [12,12,12].

TPU-first: NHWC, GroupNorm default (``norm='bn'`` for parity), shared
block implementations from fedml_tpu.models.resnet.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import BasicBlock, BottleneckBlock, Norm


class ResNetClientStump(nn.Module):
    """Bottom-of-the-split client net: features + local logits."""

    n_blocks: int = 1
    block: str = "basic"
    num_classes: int = 10
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = nn.relu(x)
        features = x  # B x 32 x 32 x 16 — crosses the split boundary
        blk = BasicBlock if self.block == "basic" else BottleneckBlock
        for _ in range(self.n_blocks):
            x = blk(16, 1, self.norm)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(x)
        return logits, features


class ResNetServerTail(nn.Module):
    """Top-of-the-split server net: features → logits."""

    layers: Sequence[int] = (6, 6, 6)
    block: str = "bottleneck"
    num_classes: int = 10
    norm: str = "gn"

    @nn.compact
    def __call__(self, feats, train: bool = False):
        x = feats
        blk = BasicBlock if self.block == "basic" else BottleneckBlock
        for stage, (planes, n_blocks) in enumerate(zip((16, 32, 64), self.layers)):
            for i in range(n_blocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = blk(planes, strides, self.norm)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@register_model("resnet5_56")
def resnet5_56(num_classes: int = 10, norm: str = "gn", **_):
    return ResNetClientStump(n_blocks=1, block="basic",
                             num_classes=num_classes, norm=norm)


@register_model("resnet8_56")
def resnet8_56(num_classes: int = 10, norm: str = "gn", **_):
    return ResNetClientStump(n_blocks=2, block="bottleneck",
                             num_classes=num_classes, norm=norm)


@register_model("resnet56_server")
def resnet56_server(num_classes: int = 10, norm: str = "gn", **_):
    return ResNetServerTail(layers=(6, 6, 6), block="bottleneck",
                            num_classes=num_classes, norm=norm)


@register_model("resnet20_server")
def resnet20_server(num_classes: int = 10, norm: str = "gn", **_):
    """Small server tail (2-2-2) — CI/smoke-size counterpart of
    resnet56_server."""
    return ResNetServerTail(layers=(2, 2, 2), block="bottleneck",
                            num_classes=num_classes, norm=norm)


class ResNetSplitBottom(nn.Module):
    """SplitNN client bottom: the model's early layers only, features out
    (the reference's split cuts one net at a layer — split_nn/client.py runs
    just the bottom; no local logits, unlike the GKT stump)."""

    n_blocks: int = 1
    block: str = "basic"
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = nn.relu(x)
        blk = BasicBlock if self.block == "basic" else BottleneckBlock
        for _ in range(self.n_blocks):
            x = blk(16, 1, self.norm)(x, train)
        return x


@register_model("resnet_split_bottom")
def resnet_split_bottom(n_blocks: int = 1, norm: str = "gn", **_):
    return ResNetSplitBottom(n_blocks=n_blocks, norm=norm)


@register_model("resnet110_server")
def resnet110_server(num_classes: int = 10, norm: str = "gn", **_):
    return ResNetServerTail(layers=(12, 12, 12), block="bottleneck",
                            num_classes=num_classes, norm=norm)
