"""FedAvg-era CNNs (reference: fedml_api/model/cv/cnn.py).

- ``CNNOriginalFedAvg`` (cnn.py:5-70): McMahan'17 2-conv (32, 64 ch, 5x5) +
  FC-512 net for MNIST/FEMNIST.
- ``CNNDropOut`` (cnn.py:74-142): Reddi'20 "Adaptive Federated Optimization"
  variant with 3x3 convs, max-pool, dropout 0.25/0.5, FC-128.

NHWC layout (TPU-native; the reference is NCHW torch).

Lane-fill hooks (docs/ROOFLINE.md, parallel/layout.py): both nets take
``stem="s2d"`` — a 2x2 space-to-depth input transform (1→4 channels at
half spatial), the same MXU lane-fill lever the CIFAR ResNets carry
first-class — and ``widths=(c1, c2)`` conv-width overrides, which is how
the compute-layout transform builds lane-padded physical twins.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn

from fedml_tpu.models.registry import register_model


def _stem(x, stem: str):
    if x.ndim == 3:
        x = x[..., None]
    if stem == "s2d":
        from fedml_tpu.models.resnet import space_to_depth

        return space_to_depth(x, 2)
    if stem != "conv":
        raise ValueError(f"unknown stem {stem!r}: expected conv|s2d")
    return x


class CNNOriginalFedAvg(nn.Module):
    num_classes: int = 62
    only_digits: bool = False
    stem: str = "conv"  # "conv" (reference) | "s2d" (lane-fill variant)
    widths: Any = None  # Optional[(c1, c2)] conv-width override
    hidden: int = 512
    dtype: Any = None  # compute dtype (params stay float32)
    #: im2col-rephrased stem (parallel/layout.im2col_layout builds this
    #: physical twin): the 5x5 stem conv becomes patch extraction + a
    #: 1x1 conv whose contraction dim is k²·Cin (25 on the reference
    #: stem) — the MXU sees one dense GEMM instead of a 1-channel conv.
    #: Algebraically the SAME dot per output position; the Conv_0 kernel
    #: is the (c, kh, kw)-flattened reshape of the logical 5x5 kernel.
    im2col: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _stem(x, self.stem)
        c1, c2 = self.widths or (32, 64)
        if self.im2col:
            from jax import lax

            x = lax.conv_general_dilated_patches(
                x.astype(self.dtype or x.dtype), (5, 5), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = nn.Conv(c1, (1, 1), dtype=self.dtype)(x)
        else:
            x = nn.Conv(c1, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(c2, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        return nn.Dense(10 if self.only_digits else self.num_classes,
                        dtype=self.dtype)(x)


class CNNDropOut(nn.Module):
    num_classes: int = 62
    only_digits: bool = False
    stem: str = "conv"
    widths: Any = None  # Optional[(c1, c2)]
    dtype: Any = None  # compute dtype (params stay float32)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _stem(x, self.stem)
        c1, c2 = self.widths or (32, 64)
        x = nn.relu(nn.Conv(c1, (3, 3), padding="VALID",
                            dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(c2, (3, 3), padding="VALID",
                            dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes,
                        dtype=self.dtype)(x)


@register_model("cnn")
def _cnn(num_classes: int = 62, only_digits: bool = False,
         dropout: bool = True, stem: str = "conv", **_):
    cls = CNNDropOut if dropout else CNNOriginalFedAvg
    return cls(num_classes=num_classes, only_digits=only_digits, stem=stem)
