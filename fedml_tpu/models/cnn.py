"""FedAvg-era CNNs (reference: fedml_api/model/cv/cnn.py).

- ``CNNOriginalFedAvg`` (cnn.py:5-70): McMahan'17 2-conv (32, 64 ch, 5x5) +
  FC-512 net for MNIST/FEMNIST.
- ``CNNDropOut`` (cnn.py:74-142): Reddi'20 "Adaptive Federated Optimization"
  variant with 3x3 convs, max-pool, dropout 0.25/0.5, FC-128.

NHWC layout (TPU-native; the reference is NCHW torch).
"""

from __future__ import annotations

import flax.linen as nn

from fedml_tpu.models.registry import register_model


class CNNOriginalFedAvg(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)


class CNNDropOut(nn.Module):
    num_classes: int = 62
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes)(x)


@register_model("cnn")
def _cnn(num_classes: int = 62, only_digits: bool = False, dropout: bool = True, **_):
    cls = CNNDropOut if dropout else CNNOriginalFedAvg
    return cls(num_classes=num_classes, only_digits=only_digits)
