"""VGG 11/13/16/19 (± normalization) for federated CV workloads.

Parity target: reference fedml_api/model/cv/vgg.py:13-158 (torchvision-style
VGG with per-depth conv configs and optional BatchNorm).

TPU-first deviations (documented, deliberate):
- NHWC layout, GroupNorm default (``norm='bn'`` available for strict parity;
  see fedml_tpu/models/resnet.py for the FL-BatchNorm rationale).
- The reference flattens a 7x7 adaptive pool into a 512*7*7 -> 4096 dense
  stack (vgg.py:24-33) — 102M params that exist only for 224x224 ImageNet
  inputs. Here we global-average-pool then Dense(4096)x2, which keeps the
  classifier capacity structure while staying shape-polymorphic over input
  resolution (CIFAR 32x32 federated workloads reach the pool at 1x1 anyway).
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm

# Per-depth conv plans, 'M' = 2x2 max-pool (reference vgg.py:69-79 cfgs A/B/D/E).
_CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
         "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    num_classes: int = 10
    norm: str = ""  # "" (plain, = reference vgg1x), "gn", or "bn" (vgg1x_bn)
    classifier_width: int = 4096
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding="SAME")(x)
                if self.norm:
                    x = Norm(self.norm)(x, train)
                x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        for _ in range(2):
            x = nn.Dense(self.classifier_width)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


def _make(depth: int, default_norm: str):
    def ctor(num_classes: int = 10, classifier_width: int = 4096,
             norm: str = None, dropout_rate: float = 0.5, **_):
        return VGG(cfg=_CFGS[depth], num_classes=num_classes,
                   norm=default_norm if norm is None else norm,
                   classifier_width=classifier_width,
                   dropout_rate=dropout_rate)
    return ctor


for _d in (11, 13, 16, 19):
    register_model(f"vgg{_d}")(_make(_d, ""))
    register_model(f"vgg{_d}_bn")(_make(_d, "bn"))
    register_model(f"vgg{_d}_gn")(_make(_d, "gn"))
