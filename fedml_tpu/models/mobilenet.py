"""MobileNet v1 (α-width) and MobileNetV3 for cross-silo CV.

Parity: fedml_api/model/cv/mobilenet.py:60-209 (depthwise-separable stacks,
width multiplier) and mobilenet_v3.py:137 (LARGE/SMALL). NHWC + GroupNorm
default (see resnet.py for the BN note); depthwise convs use
``feature_group_count`` so XLA lowers them to efficient TPU convolutions.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm


class DepthwiseSeparable(nn.Module):
    out_ch: int
    strides: int = 1
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(
            in_ch, (3, 3), (self.strides, self.strides), padding="SAME",
            feature_group_count=in_ch, use_bias=False,
        )(x)
        x = Norm(self.norm)(x, train)
        x = nn.relu(x)
        x = nn.Conv(self.out_ch, (1, 1), use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    """Reference layer plan (mobilenet.py:69-205): 32-stem then
    64,128s2,128,256s2,256,512s2,512×5,1024s2,1024."""

    num_classes: int = 10
    alpha: float = 1.0
    norm: str = "gn"
    plan: Sequence[Tuple[int, int]] = (
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            return max(int(ch * self.alpha), 8)

        x = nn.Conv(c(32), (3, 3), (2, 2) if x.shape[1] > 64 else (1, 1),
                    padding="SAME", use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = nn.relu(x)
        for ch, s in self.plan:
            x = DepthwiseSeparable(c(ch), s, self.norm)(x, train)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@register_model("mobilenet")
def mobilenet(num_classes: int = 10, alpha: float = 1.0, norm: str = "gn", **_):
    return MobileNetV1(num_classes=num_classes, alpha=alpha, norm=norm)
