"""MNIST GAN (MLP generator + discriminator) for FedGAN.

Parity target: reference fedml_api/model/cv/mnist_gan.py:6-65 —
Generator 100→128→256→512→1024→784 with LeakyReLU(0.2)+BatchNorm1d and tanh
output reshaped to [B,1,28,28]; Discriminator 784→512→256→1 with
LeakyReLU(0.2); MNIST_gan wrapper holding both nets (the FedGAN aggregator
averages the two state_dicts jointly, fedgan/FedGANAggregator.py:73-81).

TPU-first deviations:
- NHWC: generator emits [B,28,28,1].
- The discriminator returns **logits** (no terminal sigmoid, reference
  mnist_gan.py:46) — pair with ``optax.sigmoid_binary_cross_entropy`` for
  numerically stable training; callers wanting probabilities apply
  ``jax.nn.sigmoid`` themselves.
- LayerNorm instead of BatchNorm1d by default: per-client generator batch
  stats are an FL pathology (same rationale as resnet.py) and LayerNorm is
  the standard JAX GAN choice. ``norm='bn'`` restores strict parity.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model


def _norm1d(kind: str, x, train: bool):
    if kind == "bn":
        return nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
    return nn.LayerNorm()(x)


class Generator(nn.Module):
    input_size: int = 100
    out_pixels: int = 784
    norm: str = "ln"

    @nn.compact
    def __call__(self, z, train: bool = False):
        x = nn.leaky_relu(nn.Dense(128)(z), 0.2)
        for width in (256, 512, 1024):
            x = nn.Dense(width)(x)
            x = _norm1d(self.norm, x, train)
            x = nn.leaky_relu(x, 0.2)
        x = jnp.tanh(nn.Dense(self.out_pixels)(x))
        side = int(self.out_pixels ** 0.5)
        return x.reshape(z.shape[0], side, side, 1)


class Discriminator(nn.Module):
    input_size: int = 784

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape(x.shape[0], -1)
        x = nn.leaky_relu(nn.Dense(512)(x), 0.2)
        x = nn.leaky_relu(nn.Dense(256)(x), 0.2)
        return nn.Dense(1)(x)  # logits


class MNISTGan(nn.Module):
    """Two-net wrapper (reference MNIST_gan :55-65). Calling it runs the
    full G→D pass so one ``init`` yields the joint params pytree with
    ``netg``/``netd`` submodule keys — the unit FedGAN aggregates."""

    latent_dim: int = 100
    norm: str = "ln"

    def setup(self):
        self.netg = Generator(input_size=self.latent_dim, norm=self.norm)
        self.netd = Discriminator()

    def __call__(self, z, train: bool = False):
        fake = self.netg(z, train)
        return self.netd(fake, train)

    def generate(self, z, train: bool = False):
        return self.netg(z, train)

    def discriminate(self, x, train: bool = False):
        return self.netd(x, train)


@register_model("mnist_gan")
def mnist_gan(latent_dim: int = 100, norm: str = "ln", **_):
    return MNISTGan(latent_dim=latent_dim, norm=norm)
