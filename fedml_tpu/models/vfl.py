"""Vertical-FL party models.

Parity targets (reference fedml_api/model/finance/):
- ``LocalModel`` (vfl_models_standalone.py:36-70): Dense → LeakyReLU
  feature extractor each party runs on its own feature slice.
- ``DenseModel`` (vfl_models_standalone.py:6-33): a single Linear producing
  the party's logit contribution (guest: with bias; hosts: without —
  party_models.py builds them that way so the summed logit has one bias).
- ``VFLFeatureExtractor`` / ``VFLClassifier`` (vfl_classifier.py,
  vfl_feature_extractor.py) follow the same two shapes.

The reference gives each model a hand-rolled ``backward(x, grads)`` doing
manual VJP + SGD (momentum 0.9, wd 0.01). Here the models are plain flax
modules; the protocol-level VJP lives in fedml_tpu.algos.vertical_fl via
``jax.vjp`` — same math, no hand-written backward.
"""

from __future__ import annotations

import flax.linen as nn

from fedml_tpu.models.registry import register_model


class VFLLocalModel(nn.Module):
    """Per-party feature extractor: Dense → LeakyReLU."""

    output_dim: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.leaky_relu(nn.Dense(self.output_dim)(x), 0.01)


class VFLDenseModel(nn.Module):
    """Party logit head: one Linear (guest keeps the bias)."""

    output_dim: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.output_dim, use_bias=self.use_bias)(x)


@register_model("vfl_local")
def vfl_local(output_dim: int = 32, **_):
    return VFLLocalModel(output_dim=output_dim)


@register_model("vfl_dense")
def vfl_dense(output_dim: int = 1, use_bias: bool = True, **_):
    return VFLDenseModel(output_dim=output_dim, use_bias=use_bias)
