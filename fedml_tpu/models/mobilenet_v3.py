"""MobileNetV3 LARGE/SMALL (Howard et al. 2019) in flax.

Parity target: reference fedml_api/model/cv/mobilenet_v3.py:35-257
(h-swish/h-sigmoid activations, squeeze-excite blocks, per-stage
(kernel, expand, out, nonlinearity, SE, stride) plans for LARGE and SMALL).

TPU-first: NHWC, GroupNorm default (``norm='bn'`` for parity), depthwise
convs via ``feature_group_count`` so XLA lowers them onto the MXU as
grouped contractions.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm


def h_sigmoid(x):
    """relu6(x + 3) / 6 (reference mobilenet_v3.py:35-41)."""
    return nn.relu6(x + 3.0) / 6.0


def h_swish(x):
    """x * h_sigmoid(x) (reference mobilenet_v3.py:44-50)."""
    return x * h_sigmoid(x)


class SqueezeExcite(nn.Module):
    """SE block with divide-4 bottleneck (reference SqueezeBlock :64-81)."""

    divide: int = 4

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(c // self.divide)(s))
        s = h_sigmoid(nn.Dense(c)(s))
        return x * s[:, None, None, :]


class MobileBlock(nn.Module):
    """Inverted residual: expand 1x1 -> depthwise kxk -> (SE) -> project 1x1
    (reference MobileBlock :84-135)."""

    kernel: int
    expand: int
    out_ch: int
    strides: int
    use_se: bool
    act: str  # "RE" relu | "HS" h-swish
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        nonlin = nn.relu if self.act == "RE" else h_swish
        residual = x
        y = nn.Conv(self.expand, (1, 1), use_bias=False)(x)
        y = Norm(self.norm)(y, train)
        y = nonlin(y)
        y = nn.Conv(
            self.expand, (self.kernel, self.kernel),
            (self.strides, self.strides), padding="SAME",
            feature_group_count=self.expand, use_bias=False,
        )(y)
        y = Norm(self.norm)(y, train)
        if self.use_se:
            y = SqueezeExcite()(y)
        y = nonlin(y)
        y = nn.Conv(self.out_ch, (1, 1), use_bias=False)(y)
        y = Norm(self.norm)(y, train)
        if self.strides == 1 and residual.shape[-1] == self.out_ch:
            y = y + residual
        return y


# (kernel, expand, out, act, SE, stride) — reference mobilenet_v3.py:150-189.
_LARGE: Sequence[Tuple] = (
    (3, 16, 16, "RE", False, 1), (3, 64, 24, "RE", False, 2),
    (3, 72, 24, "RE", False, 1), (5, 72, 40, "RE", True, 2),
    (5, 120, 40, "RE", True, 1), (5, 120, 40, "RE", True, 1),
    (3, 240, 80, "HS", False, 2), (3, 200, 80, "HS", False, 1),
    (3, 184, 80, "HS", False, 1), (3, 184, 80, "HS", False, 1),
    (3, 480, 112, "HS", True, 1), (3, 672, 112, "HS", True, 1),
    (5, 672, 160, "HS", True, 1), (5, 672, 160, "HS", True, 2),
    (5, 960, 160, "HS", True, 1),
)
_SMALL: Sequence[Tuple] = (
    (3, 16, 16, "RE", True, 2), (3, 72, 24, "RE", False, 2),
    (3, 88, 24, "RE", False, 1), (5, 96, 40, "RE", True, 2),
    (5, 240, 40, "RE", True, 1), (5, 240, 40, "RE", True, 1),
    (5, 120, 48, "HS", True, 1), (5, 144, 48, "HS", True, 1),
    (5, 288, 96, "HS", True, 2), (5, 576, 96, "HS", True, 1),
    (5, 576, 96, "HS", True, 1),
)


class MobileNetV3(nn.Module):
    """Reference MobileNetV3 :137-257. ``small_input`` keeps stride-1 stem
    for 32x32 federated CIFAR inputs."""

    model_mode: str = "LARGE"
    num_classes: int = 10
    norm: str = "gn"
    dropout_rate: float = 0.2
    small_input: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        mode = self.model_mode.upper()
        if mode not in ("LARGE", "SMALL"):
            raise ValueError(f"model_mode must be LARGE or SMALL, got {mode!r}")
        plan = _LARGE if mode == "LARGE" else _SMALL
        last_expand = 960 if mode == "LARGE" else 576
        stem_strides = 1 if self.small_input else 2
        x = nn.Conv(16, (3, 3), (stem_strides, stem_strides),
                    padding="SAME", use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = h_swish(x)
        for k, e, o, act, se, s in plan:
            x = MobileBlock(k, e, o, s, se, act, self.norm)(x, train)
        x = nn.Conv(last_expand, (1, 1), use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = h_swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = h_swish(nn.Dense(1280)(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("mobilenet_v3")
def mobilenet_v3(num_classes: int = 10, model_mode: str = "LARGE",
                 norm: str = "gn", small_input: bool = True,
                 dropout_rate: float = 0.2, **_):
    return MobileNetV3(model_mode=model_mode, num_classes=num_classes,
                       norm=norm, small_input=small_input,
                       dropout_rate=dropout_rate)
