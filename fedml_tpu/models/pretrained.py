"""Pretrained-weights save/load.

Parity with the reference's checkpoint loading (model/cv/resnet.py:209-220
loads ``.pth`` state_dicts for resnet56 ``pretrained=True``; ckpt dirs under
model/cv/pretrained/). TPU-native formats:

- ``save_params`` / ``load_params``: flat ``.npz`` of the NetState (params +
  model_state), path-keyed — portable, no pickle;
- orbax checkpoints from fedml_tpu.obs.checkpoint restore full run state;
  this module is for model-only weights (zoo distribution);
- the reference's actual torch ``.pth`` files convert via
  fedml_tpu.models.torch_convert (forward-equivalence-tested mapping).
"""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np

from fedml_tpu.trainer.local import NetState

_SEP = "::"


def _flatten(tree, prefix: str) -> Dict[str, np.ndarray]:
    out = {}

    def visit(path, leaf):
        keys = [str(getattr(k, "key", k)) for k in path]
        out[prefix + _SEP + _SEP.join(keys)] = np.asarray(leaf)

    jax.tree_util.tree_map_with_path(visit, tree)
    return out


def save_params(net: NetState, path: str) -> None:
    flat = {**_flatten(net.params, "params"),
            **_flatten(net.model_state, "state")}
    np.savez(path, **flat)


def load_params(net: NetState, path: str) -> NetState:
    """Load weights saved by :func:`save_params` into ``net``'s structure.
    Shapes/keys must match exactly IN BOTH DIRECTIONS — a missing key,
    shape mismatch, or unused checkpoint entry (wrong architecture whose
    common layers happen to match) raises with the offending key."""
    with np.load(path if path.endswith(".npz") else path + ".npz") as data:
        used = set()

        def rebuild(tree, prefix):
            def visit(path_keys, leaf):
                keys = [str(getattr(k, "key", k)) for k in path_keys]
                key = prefix + _SEP + _SEP.join(keys)
                if key not in data:
                    raise KeyError(
                        f"checkpoint {path!r} is missing {key!r} "
                        f"(available: {sorted(data.files)[:5]}...)")
                arr = data[key]
                if arr.shape != leaf.shape:
                    raise ValueError(
                        f"{key!r}: checkpoint shape {arr.shape} != model "
                        f"shape {leaf.shape}")
                used.add(key)
                return arr.astype(leaf.dtype)

            return jax.tree_util.tree_map_with_path(visit, tree)

        out = NetState(rebuild(net.params, "params"),
                       rebuild(net.model_state, "state"))
        leftover = set(data.files) - used
        if leftover:
            raise ValueError(
                f"checkpoint {path!r} has {len(leftover)} entries the model "
                f"does not use (first: {sorted(leftover)[:3]}) — wrong "
                "architecture?")
        return out
