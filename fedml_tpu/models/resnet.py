"""ResNets for federated CV workloads.

Parity targets:
- CIFAR ResNet-56/110 with Bottleneck blocks [6,6,6]/[12,12,12]
  (reference fedml_api/model/cv/resnet.py:113-246 — note the reference's
  "resnet56" is the bottleneck variant, 16→64 widths; we mirror that).
- ImageNet-style ResNet-18/34/50/101/152 with **GroupNorm** (reference
  fedml_api/model/cv/resnet_gn.py:108-235, default 32 channels/group, used
  for fed_cifar100 per Reddi'20).

TPU-first choices: NHWC layout, GroupNorm default (BatchNorm running stats
are a known FL pathology — the reference's robust aggregator special-cases
them, fedml_core/robustness/robust_aggregation.py:27-29; a ``norm='bn'``
variant is provided for strict parity and its batch_stats ride NetState).

KNOWN LIMITATION of ``norm='bn'`` with ragged clients: padded duplicate
samples inside a partially-masked batch enter the BatchNorm batch
statistics (the mask guards losses and optimizer updates, not the forward
normalization). With per-client sample counts that are multiples of the
batch size this is exact; otherwise prefer GroupNorm (the default, and the
setting the reference's published fed_cifar100 baseline uses).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model


class Norm(nn.Module):
    """GroupNorm (32 groups, clipped to channel count) or BatchNorm."""

    kind: str = "gn"
    groups: int = 32
    dtype: Any = None  # compute dtype (params stay float32)

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.kind == "bn":
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)(x)
        c = x.shape[-1]
        # num_groups must divide the channel count: largest divisor of c
        # that is <= self.groups (reference group_normalization.py defaults
        # to 32 ch/group on power-of-two widths; MobileNetV3/EfficientNet
        # widths like 72/88/200 need the divisor search).
        g = min(self.groups, c)
        while c % g:
            g -= 1
        return nn.GroupNorm(num_groups=g, dtype=self.dtype)(x)


class BottleneckBlock(nn.Module):
    planes: int
    strides: int = 1
    norm: str = "gn"
    expansion: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = Norm(self.norm, dtype=self.dtype)(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), (self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype)(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.planes * self.expansion, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype)(y, train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.planes * self.expansion, (1, 1),
                (self.strides, self.strides), use_bias=False, name="downsample",
                dtype=self.dtype,
            )(x)
            residual = Norm(self.norm, dtype=self.dtype)(residual, train)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    planes: int
    strides: int = 1
    norm: str = "gn"
    expansion: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.planes, (3, 3), (self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = Norm(self.norm, dtype=self.dtype)(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype)(y, train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.planes, (1, 1), (self.strides, self.strides),
                use_bias=False, name="downsample", dtype=self.dtype,
            )(x)
            residual = Norm(self.norm, dtype=self.dtype)(residual, train)
        return nn.relu(residual + y)


class CifarResNet(nn.Module):
    """CIFAR-style 3-stage ResNet (reference resnet.py:113-200)."""

    layers: Sequence[int] = (6, 6, 6)  # 56 = 6*3*3 + 2
    num_classes: int = 10
    norm: str = "gn"
    dtype: Any = None  # compute dtype; jnp.bfloat16 = mixed precision

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = Norm(self.norm, dtype=self.dtype)(x, train)
        x = nn.relu(x)
        for stage, (planes, n_blocks) in enumerate(zip((16, 32, 64), self.layers)):
            for i in range(n_blocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = BottleneckBlock(planes, strides, self.norm,
                                    dtype=self.dtype)(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNetGN(nn.Module):
    """ImageNet-style ResNet with GroupNorm (reference resnet_gn.py:108-235),
    stem adapted for small inputs when ``small_input`` (fed_cifar100 runs
    24x24 crops through the ImageNet stem in the reference; we keep that
    possible but default to a 3x3 stem for 32x32)."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # resnet18
    block: str = "basic"  # "basic" | "bottleneck"
    num_classes: int = 100
    norm: str = "gn"
    small_input: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        x = Norm(self.norm, dtype=self.dtype)(x, train)
        x = nn.relu(x)
        if not self.small_input:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        blk = BasicBlock if self.block == "basic" else BottleneckBlock
        for stage, n_blocks in enumerate(self.stage_sizes):
            planes = 64 * (2 ** stage)
            for i in range(n_blocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = blk(planes, strides, self.norm, dtype=self.dtype)(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


from fedml_tpu.models.registry import resolve_dtype as _dt  # noqa: E402


@register_model("resnet56")
def resnet56(num_classes: int = 10, norm: str = "gn", dtype=None, **_):
    return CifarResNet(layers=(6, 6, 6), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype))


@register_model("resnet110")
def resnet110(num_classes: int = 10, norm: str = "gn", dtype=None, **_):
    return CifarResNet(layers=(12, 12, 12), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype))


@register_model("resnet20")
def resnet20(num_classes: int = 10, norm: str = "gn", dtype=None, **_):
    """Small CIFAR ResNet (2-2-2 bottleneck) — test/dryrun workhorse."""
    return CifarResNet(layers=(2, 2, 2), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype))


@register_model("resnet18_gn")
def resnet18_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(2, 2, 2, 2), block="basic", num_classes=num_classes)


@register_model("resnet34_gn")
def resnet34_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="basic", num_classes=num_classes)


@register_model("resnet50_gn")
def resnet50_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="bottleneck", num_classes=num_classes)


@register_model("resnet101_gn")
def resnet101_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 23, 3), block="bottleneck", num_classes=num_classes)


@register_model("resnet152_gn")
def resnet152_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 8, 36, 3), block="bottleneck", num_classes=num_classes)
