"""ResNets for federated CV workloads.

Parity targets:
- CIFAR ResNet-56/110 with Bottleneck blocks [6,6,6]/[12,12,12]
  (reference fedml_api/model/cv/resnet.py:113-246 — note the reference's
  "resnet56" is the bottleneck variant, 16→64 widths; we mirror that).
- ImageNet-style ResNet-18/34/50/101/152 with **GroupNorm** (reference
  fedml_api/model/cv/resnet_gn.py:108-235, default 32 channels/group, used
  for fed_cifar100 per Reddi'20).

TPU-first choices: NHWC layout, GroupNorm default (BatchNorm running stats
are a known FL pathology — the reference's robust aggregator special-cases
them, fedml_core/robustness/robust_aggregation.py:27-29; a ``norm='bn'``
variant is provided for strict parity and its batch_stats ride NetState).

KNOWN LIMITATION of ``norm='bn'`` with ragged clients: padded duplicate
samples inside a partially-masked batch enter the BatchNorm batch
statistics (the mask guards losses and optimizer updates, not the forward
normalization). With per-client sample counts that are multiples of the
batch size this is exact; otherwise prefer GroupNorm (the default, and the
setting the reference's published fed_cifar100 baseline uses).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model


def norm_groups(c: int, groups: int = 32) -> int:
    """The GroupNorm group-count policy: the largest divisor of the
    channel count that is <= ``groups`` (reference group_normalization.py
    defaults to 32 ch/group on power-of-two widths; MobileNetV3/
    EfficientNet widths like 72/88/200 need the divisor search). Single
    source — ``parallel/layout.py`` reads the same policy to keep a
    lane-padded physical twin's grouping exact."""
    g = min(groups, c)
    while c % g:
        g -= 1
    return g


class Norm(nn.Module):
    """GroupNorm (32 groups, clipped to channel count), BatchNorm,
    ``"gn_fused"`` (the pallas fused GroupNorm kernel,
    fedml_tpu.ops.group_norm — same math and param tree as ``"gn"``;
    measured SLOWER than XLA's conv-fused lowering at CIFAR-ResNet
    shapes, so not the default — docs/ROOFLINE.md), or ``"none"``
    (identity — the measurement ablation docs/ROOFLINE.md uses to
    attribute normalization cost; not a training configuration).

    ``logical_channels`` (lane-fill compute layouts,
    ``parallel/layout.py``): when the module runs a lane-PADDED physical
    channel count, the group size must stay what the LOGICAL model's
    policy chose — logical channels keep their exact grouping (bit-equal
    statistics) and the zero pad channels fill whole extra groups of the
    same size, where they normalize to exactly zero. 0 = physical is
    logical (the default, byte-identical to the pre-layout behavior)."""

    kind: str = "gn"
    groups: int = 32
    dtype: Any = None  # compute dtype (params stay float32)
    logical_channels: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.kind == "none":
            return x
        if self.kind == "bn":
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)(x)
        c = x.shape[-1]
        c_log = self.logical_channels or c
        cpg = c_log // norm_groups(c_log, self.groups)
        if c % cpg:
            raise ValueError(
                f"padded channel count {c} is not a multiple of the "
                f"logical group size {cpg} (logical {c_log} channels): "
                "pad channels in whole-group quanta or the logical "
                "statistics change (parallel/layout.py pads accordingly)")
        g = c // cpg
        if self.kind == "gn_fused":
            # name="GroupNorm_0" matches nn.GroupNorm's auto-name in the
            # "gn" branch → identical param trees; checkpoints are
            # interchangeable between the two kinds.
            return _GroupNormFused(num_groups=g, dtype=self.dtype,
                                   name="GroupNorm_0")(x)
        return nn.GroupNorm(num_groups=g, dtype=self.dtype)(x)


class _GroupNormFused(nn.Module):
    """nn.GroupNorm drop-in backed by the pallas fused kernel
    (fedml_tpu.ops.group_norm): same params (scale/bias), same f32-stats
    numerics, one VMEM pass fwd and one fused backward."""

    num_groups: int
    dtype: Any = None
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        from fedml_tpu.ops.group_norm import group_norm

        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        return group_norm(x.astype(self.dtype or x.dtype), scale, bias,
                          self.num_groups, self.epsilon)


class BottleneckBlock(nn.Module):
    #: ``logical_planes`` (lane-fill layouts): the LOGICAL width this
    #: block's ``planes`` was padded up from — forwarded to every Norm so
    #: the padded twin keeps the logical grouping. 0 = planes is logical.
    planes: int
    strides: int = 1
    norm: str = "gn"
    expansion: int = 4
    dtype: Any = None
    logical_planes: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        lp = self.logical_planes
        residual = x
        y = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = Norm(self.norm, dtype=self.dtype, logical_channels=lp)(y, train)
        y = nn.relu(y)
        # Explicit (1,1) padding == torch conv3x3(padding=1): identical to
        # "SAME" at stride 1, and at stride 2 it keeps the reference's
        # sampling grid (SAME would pad (0,1) and shift the windows) — so
        # converted torch checkpoints reproduce outputs exactly.
        y = nn.Conv(self.planes, (3, 3), (self.strides, self.strides),
                    padding=((1, 1), (1, 1)), use_bias=False,
                    dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype, logical_channels=lp)(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.planes * self.expansion, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype,
                 logical_channels=lp * self.expansion)(y, train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.planes * self.expansion, (1, 1),
                (self.strides, self.strides), use_bias=False, name="downsample",
                dtype=self.dtype,
            )(x)
            residual = Norm(self.norm, dtype=self.dtype,
                            logical_channels=lp * self.expansion)(
                residual, train)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    planes: int
    strides: int = 1
    norm: str = "gn"
    expansion: int = 1
    dtype: Any = None
    logical_planes: int = 0  # see BottleneckBlock

    @nn.compact
    def __call__(self, x, train: bool = False):
        lp = self.logical_planes
        residual = x
        # torch conv3x3(padding=1) grid — see BottleneckBlock.
        y = nn.Conv(self.planes, (3, 3), (self.strides, self.strides),
                    padding=((1, 1), (1, 1)), use_bias=False,
                    dtype=self.dtype)(x)
        y = Norm(self.norm, dtype=self.dtype, logical_channels=lp)(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.planes, (3, 3), padding=((1, 1), (1, 1)),
                    use_bias=False, dtype=self.dtype)(y)
        y = Norm(self.norm, dtype=self.dtype, logical_channels=lp)(y, train)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.planes, (1, 1), (self.strides, self.strides),
                use_bias=False, name="downsample", dtype=self.dtype,
            )(x)
            residual = Norm(self.norm, dtype=self.dtype,
                            logical_channels=lp)(residual, train)
        return nn.relu(residual + y)


def space_to_depth(x, block: int = 2):
    """[B, H, W, C] → [B, H/b, W/b, C·b²]: move 2x2 spatial patches into
    channels — the classic TPU transform for small-channel CNN stems
    (narrow early stages under-fill the 128-lane MXU; see
    docs/ROOFLINE.md)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        b, h // block, w // block, c * block * block)


class CifarResNet(nn.Module):
    """CIFAR-style 3-stage ResNet (reference resnet.py:113-200).

    ``stem="s2d"`` is the TPU-friendly variant the roofline analysis
    names as the first lever against lane under-fill: a 2x2
    space-to-depth input transform (3→12 channels, 32→16 spatial) with
    stage widths doubled to (32, 64, 128). Per-conv FLOPs stay ~equal
    (H·W·C² is invariant under half-spatial/double-channel), but every
    stage's channel count doubles its MXU lane fill — stage 3 fills all
    128 lanes. NOT the reference model (4x params per conv): the bench
    keeps the primary config on the standard stem and reports the s2d
    variant as a separate submetric."""

    layers: Sequence[int] = (6, 6, 6)  # 56 = 6*3*3 + 2
    num_classes: int = 10
    norm: str = "gn"
    dtype: Any = None  # compute dtype; jnp.bfloat16 = mixed precision
    stem: str = "conv"  # "conv" (reference) | "s2d" (TPU lane-fill variant)
    #: Stage-width / stem-channel overrides (None/0 = the stem kind's
    #: defaults). ``parallel/layout.py`` builds lane-padded physical
    #: twins through these; they also admit deliberately non-reference
    #: widths for lane-fill measurement models.
    widths: Any = None  # Optional[Tuple[int, int, int]]
    stem_width: int = 0
    #: Set by the layout transform on a PADDED twin: the logical widths
    #: the physical ones were padded up from, threaded to every Norm so
    #: grouping (and therefore the math on the logical channels) stays
    #: bit-identical to the logical model. None/0 = widths are logical.
    logical_widths: Any = None
    logical_stem: int = 0

    def stage_widths(self):
        """(stem_ch, per-stage widths) after overrides — the shapes the
        param tree will carry (layout planning reads this)."""
        if self.stem == "s2d":
            widths, stem_ch = (32, 64, 128), 32
        elif self.stem == "conv":
            widths, stem_ch = (16, 32, 64), 16
        else:
            raise ValueError(f"unknown stem {self.stem!r}: expected conv|s2d")
        return (self.stem_width or stem_ch,
                tuple(self.widths) if self.widths else widths)

    @nn.compact
    def __call__(self, x, train: bool = False):
        stem_ch, widths = self.stage_widths()
        log_w = tuple(self.logical_widths) if self.logical_widths \
            else (0,) * len(widths)
        if self.stem == "s2d":
            x = space_to_depth(x, 2)
        x = nn.Conv(stem_ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = Norm(self.norm, dtype=self.dtype,
                 logical_channels=self.logical_stem)(x, train)
        x = nn.relu(x)
        for stage, (planes, n_blocks) in enumerate(zip(widths, self.layers)):
            for i in range(n_blocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = BottleneckBlock(planes, strides, self.norm,
                                    dtype=self.dtype,
                                    logical_planes=log_w[stage])(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNetGN(nn.Module):
    """ImageNet-style ResNet with GroupNorm (reference resnet_gn.py:108-235),
    stem adapted for small inputs when ``small_input`` (fed_cifar100 runs
    24x24 crops through the ImageNet stem in the reference; we keep that
    possible but default to a 3x3 stem for 32x32)."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # resnet18
    block: str = "basic"  # "basic" | "bottleneck"
    num_classes: int = 100
    norm: str = "gn"
    small_input: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        x = Norm(self.norm, dtype=self.dtype)(x, train)
        x = nn.relu(x)
        if not self.small_input:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        blk = BasicBlock if self.block == "basic" else BottleneckBlock
        for stage, n_blocks in enumerate(self.stage_sizes):
            planes = 64 * (2 ** stage)
            for i in range(n_blocks):
                strides = 2 if (stage > 0 and i == 0) else 1
                x = blk(planes, strides, self.norm, dtype=self.dtype)(x, train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


from fedml_tpu.models.registry import resolve_dtype as _dt  # noqa: E402


@register_model("resnet56")
def resnet56(num_classes: int = 10, norm: str = "gn", dtype=None,
             stem: str = "conv", widths=None, **_):
    return CifarResNet(layers=(6, 6, 6), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype), stem=stem, widths=widths)


@register_model("resnet56_s2d")
def resnet56_s2d(num_classes: int = 10, norm: str = "gn", dtype=None, **_):
    """The measured lane-fill variant as a first-class registry name
    (CLI: ``--model resnet56_s2d``): 2x2 space-to-depth stem, stage
    widths doubled — docs/ROOFLINE.md measured it at ~3.2x the reference
    stem's samples/sec (MFU 2.9% → 8.7%) at equal per-conv FLOPs. NOT
    weight-compatible with the reference model (4x params per conv) —
    ``torch_convert`` refuses reference checkpoints for it loudly."""
    return CifarResNet(layers=(6, 6, 6), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype), stem="s2d")


@register_model("resnet110")
def resnet110(num_classes: int = 10, norm: str = "gn", dtype=None,
              stem: str = "conv", **_):
    return CifarResNet(layers=(12, 12, 12), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype), stem=stem)


@register_model("resnet20")
def resnet20(num_classes: int = 10, norm: str = "gn", dtype=None,
             stem: str = "conv", widths=None, **_):
    """Small CIFAR ResNet (2-2-2 bottleneck) — test/dryrun workhorse."""
    return CifarResNet(layers=(2, 2, 2), num_classes=num_classes, norm=norm,
                       dtype=_dt(dtype), stem=stem, widths=widths)


@register_model("resnet10_gn")
def resnet10_gn(num_classes: int = 100, **_):
    """Reduced-depth ResNet-GN (one basic block per stage): the
    ``CI_LITE_DEPTH`` compile proxy for the fed_cifar100 row — same
    4-stage GroupNorm architecture, loader path, and flag wiring as
    resnet18_gn at a CPU-compilable depth, so ``reproduce_baselines.sh
    fed_cifar100_resnet18`` is exercised in CI instead of documented as
    too slow (REPRO.md CI-lite table)."""
    return ResNetGN(stage_sizes=(1, 1, 1, 1), block="basic", num_classes=num_classes)


@register_model("resnet18_gn")
def resnet18_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(2, 2, 2, 2), block="basic", num_classes=num_classes)


@register_model("resnet34_gn")
def resnet34_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="basic", num_classes=num_classes)


@register_model("resnet50_gn")
def resnet50_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 6, 3), block="bottleneck", num_classes=num_classes)


@register_model("resnet101_gn")
def resnet101_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 4, 23, 3), block="bottleneck", num_classes=num_classes)


@register_model("resnet152_gn")
def resnet152_gn(num_classes: int = 100, **_):
    return ResNetGN(stage_sizes=(3, 8, 36, 3), block="bottleneck", num_classes=num_classes)
