"""Compact encoder–decoder segmentation net (UNet-style, GroupNorm).

Fills the fedseg model role (the reference trains DeepLab-family nets via an
external repo; its in-repo fedseg package is model-agnostic —
FedSegAggregator only needs [B, H, W, C] logits). GroupNorm everywhere: the
reference needed SynchronizedBatchNorm (batchnorm_utils.py, 462 LoC) to sync
BN across GPUs — GN makes that machinery unnecessary and is the
federated-friendly choice (BN stats don't average well across non-IID
clients).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm


def _gn(c: int) -> Norm:
    # Divisor-aware GroupNorm (c is unused — Norm reads channels from x;
    # kept for call-site readability).
    return Norm("gn")


class ConvBlock(nn.Module):
    c: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.c, (3, 3), use_bias=False)(x)
        x = nn.relu(_gn(self.c)(x))
        x = nn.Conv(self.c, (3, 3), use_bias=False)(x)
        return nn.relu(_gn(self.c)(x))


class UNet(nn.Module):
    """Down/up levels with skip connections; logits at input resolution."""

    num_classes: int
    base: int = 16
    levels: int = 3

    @nn.compact
    def __call__(self, x, train: bool = False):
        skips = []
        c = self.base
        for _ in range(self.levels):
            x = ConvBlock(c)(x, train)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            c *= 2
        x = ConvBlock(c)(x, train)
        for skip in reversed(skips):
            c //= 2
            b, h, w, _ = skip.shape
            x = jnp.reshape(
                jnp.broadcast_to(x[:, :, None, :, None, :],
                                 (b, x.shape[1], 2, x.shape[2], 2, x.shape[3])),
                (b, x.shape[1] * 2, x.shape[2] * 2, x.shape[3]),
            )
            # Match the skip's spatial dims exactly: crop the 2x upsample if
            # oversized, edge-pad if undersized (odd dims floor through
            # max_pool, so 2*floor(h/2) can be h-1).
            x = x[:, :h, :w, :]
            dh, dw = h - x.shape[1], w - x.shape[2]
            if dh or dw:
                x = jnp.pad(x, ((0, 0), (0, dh), (0, dw), (0, 0)), mode="edge")
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock(c)(x, train)
        return nn.Conv(self.num_classes, (1, 1))(x)


@register_model("unet")
def unet(num_classes: int = 21, base: int = 16, levels: int = 3, **_):
    return UNet(num_classes=num_classes, base=base, levels=levels)
