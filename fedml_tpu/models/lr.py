"""Logistic regression (reference: fedml_api/model/linear/lr.py:4-11).

The reference applies a sigmoid on the output and pairs it with
``nn.CrossEntropyLoss`` (a quirk we do not reproduce: here the model returns
logits and the loss applies softmax, which is the numerically sane form)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn

from fedml_tpu.models.registry import register_model


class LogisticRegression(nn.Module):
    num_classes: int = 10
    dtype: Any = None  # compute dtype (params stay float32)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, name="linear",
                        dtype=self.dtype)(x)


@register_model("lr")
def _lr(num_classes: int = 10, **_):
    return LogisticRegression(num_classes=num_classes)
