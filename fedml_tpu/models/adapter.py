"""Frozen-base / low-rank-adapter model surgery for federated finetuning.

The cross-device workload that dominates federated learning today —
finetuning a shared transformer on-device — never ships the base model
back: clients train small low-rank (LoRA-style) adapter pairs injected
next to the frozen projections (arXiv:2108.06098's low-rank-update
framing; FedNLP, arXiv:2104.08815) and upload ONLY the adapter delta, so
the wire payload shrinks by the rank ratio BEFORE any codec runs.

This module is the pure seam between "a model with adapters injected"
(``models/transformer.py`` adds ``lora_*`` params next to the scoped
dense projections when built with ``adapter_rank > 0``) and the
federated machinery that should only ever see the adapter tree:

- :func:`split_frozen` / :func:`merge_params` — partition a param tree
  into ``(base, adapters)`` by the ``lora_`` leaf-name convention and
  reassemble it, a lossless bijection (``merge(split(p)) == p``, tested).
- :func:`adapter_model_fns` — a drop-in :class:`~fedml_tpu.trainer.
  local.ModelFns` twin whose ``init`` returns the ADAPTER tree as the
  trainable net (the frozen base is captured once on device) and whose
  ``apply`` merges base + adapters per call. Everything downstream —
  the jitted client step, aggregation, codecs (``tree_spec`` of the
  adapter net), checkpoints, the wire — operates on the adapter tree
  without knowing adapters exist.
- :class:`PersonalAdapterStore` — per-client PERSONALIZED adapter state
  as one ``[N, adapter_dim]`` float32 host array (optionally
  memmap-spilled next to a sharded store), the storage shape that makes
  million-client personalization the problem ``ClientDirectory`` /
  ``ShardedFederatedStore`` already solved: O(clients x adapter_dim)
  bytes, cohort gathers page in only the sampled rows.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, NamedTuple, Optional

import numpy as np

#: Leaf-name prefix marking adapter params (models/transformer._lora_delta
#: names every injected pair ``lora_<site>_a`` / ``lora_<site>_b``).
ADAPTER_PREFIX = "lora_"


def is_adapter_name(name) -> bool:
    return isinstance(name, str) and name.startswith(ADAPTER_PREFIX)


def split_frozen(params):
    """Partition a (nested-dict) param tree into ``(base, adapters)`` by
    the ``lora_`` leaf-name convention. Both halves keep their nesting;
    empty sub-dicts are dropped, so ``merge_params`` reassembles the
    exact original tree."""
    base, adapters = {}, {}
    for k, v in params.items():
        if isinstance(v, dict):
            b, a = split_frozen(v)
            if b:
                base[k] = b
            if a:
                adapters[k] = a
        elif is_adapter_name(k):
            adapters[k] = v
        else:
            base[k] = v
    return base, adapters


def merge_params(base, adapters):
    """Inverse of :func:`split_frozen`: reassemble the full param tree.
    A key present as a LEAF in both halves is a structure corruption
    (adapters drifted from the base they were split from) and raises."""
    out = dict(base)
    for k, v in adapters.items():
        cur = out.get(k)
        if isinstance(v, dict) and isinstance(cur, dict):
            out[k] = merge_params(cur, v)
        elif k in out:
            raise ValueError(
                f"adapter/base trees collide at key {k!r}: the adapter "
                "tree was not split from this base")
        else:
            out[k] = v
    return out


def param_count(tree) -> int:
    from fedml_tpu.obs.flops import count_params

    return count_params(tree)


class AdapterFns(NamedTuple):
    """:class:`~fedml_tpu.trainer.local.ModelFns`-compatible functional
    interface over the ADAPTER tree, plus the holder dict ``init``
    populates with the frozen base (``holder["base"]``) — exposed so
    drills can pin the base's bitwise invariance."""

    init: Callable
    apply: Callable
    holder: dict


def adapter_model_fns(model, holder: Optional[dict] = None,
                      base_params=None) -> AdapterFns:
    """Build the adapter-level ModelFns for a model injected with
    ``lora_*`` params: ``init(rng, x)`` runs the FULL deterministic init,
    splits off the frozen base into ``holder["base"]`` (device-resident
    once — jit captures it as a constant, it is never re-uploaded or
    donated), and returns a NetState whose ``params`` are the adapter
    tree alone; ``apply`` merges base + adapters per call.

    ``base_params`` swaps a PRETRAINED base in for the fresh init's (the
    finetuning story: a dense-trained checkpoint's params — adapter
    leaves absent since injection leaves base paths unchanged — become
    the frozen base while the adapters still start at the exact-identity
    LoRA init). Structure must match the split base or ``init`` raises.

    Raises when the model has NO adapter params (an adapter config
    against a dense model must refuse, not silently train the dense arm)
    or carries mutable collections (BatchNorm stats would mutate the
    "frozen" base — transformers here are LayerNorm-only)."""
    import jax

    from fedml_tpu.trainer.local import NetState, model_fns

    full_fns = model_fns(model)
    holder = {} if holder is None else holder

    def init(rng, sample_x) -> "NetState":
        full = full_fns.init(rng, sample_x)
        base, adapters = split_frozen(full.params)
        if not jax.tree.leaves(adapters):
            raise ValueError(
                "adapter finetuning needs a model with injected adapter "
                f"params (no '{ADAPTER_PREFIX}*' leaves found) — build it "
                "with adapter_rank > 0 (models/transformer.py)")
        if full.model_state:
            raise NotImplementedError(
                "adapter finetuning requires a frozen base with no "
                "mutable collections (BatchNorm running stats would "
                f"mutate it); got {sorted(full.model_state)}")
        if base_params is not None:
            import jax.numpy as jnp

            want = jax.tree.structure(base)
            got = jax.tree.structure(base_params)
            if want != got:
                raise ValueError(
                    "base_params does not match the model's frozen-base "
                    f"structure: expected {want}, got {got} — pass the "
                    "dense checkpoint's params (adapter leaves excluded)")
            base = jax.tree.map(jnp.asarray, base_params)
        holder["base"] = base
        return NetState(adapters, full.model_state)

    def apply(net: "NetState", x, train=False, rng=None):
        # The base lookup happens at TRACE time: jit captures the frozen
        # tree as on-device constants shared across calls.
        full = NetState(merge_params(holder["base"], net.params),
                        net.model_state)
        return full_fns.apply(full, x, train=train, rng=rng)

    return AdapterFns(init=init, apply=apply, holder=holder)


class PersonalAdapterStore:
    """Per-client personalized adapter state: ONE ``[n_clients, D]``
    float32 host array (``D`` = the flattened adapter dim), optionally
    memmap-spilled to disk so a million-client store costs disk, not
    RSS — the ShardedFederatedStore discipline applied to adapter state.
    Rows are keyed by GLOBAL client id (the ``ClientDirectory``'s id
    space), so the store composes with re-sharded deployments unchanged.

    Never-personalized clients read as the caller-provided default (the
    current global adapters), so a cohort gather always yields usable
    state.

    **Concurrency.** The serving plane (fedml_tpu.serve) gathers request
    rows WHILE the training fleet scatters personalization updates — the
    store's first concurrent reader. All row access is copy-on-read
    under ``self._lock``: ``gather`` copies the cohort slice and its
    ``seen`` mask inside the critical section, so a row is always one
    consistent scatter's bytes (never a torn half-write) and the
    returned array is private to the caller; ``scatter`` and the
    checkpoint surface take the same lock. The lock bounds only the
    memcpy, not the fallback fill or any downstream compute."""

    def __init__(self, n_clients: int, template_params, *,
                 spill_dir: Optional[str] = None):
        from fedml_tpu.comm.codec import tree_to_vector_np
        from fedml_tpu.core.compression import tree_spec

        self.n_clients = int(n_clients)
        self.spec = tree_spec(template_params)
        self.dim = int(sum(self.spec.sizes))
        self.memmapped = spill_dir is not None
        if self.memmapped:
            path = os.path.join(spill_dir, "personal_adapters.npy")
            self._data = np.lib.format.open_memmap(
                path, mode="w+", dtype=np.float32,
                shape=(self.n_clients, self.dim))
        else:
            self._data = np.zeros((self.n_clients, self.dim), np.float32)
        self.seen = np.zeros(self.n_clients, bool)
        self._to_vec = tree_to_vector_np
        self._lock = threading.Lock()

    def nbytes(self) -> int:
        return int(self._data.nbytes)

    def vec_of(self, params) -> np.ndarray:
        return self._to_vec(params)

    def tree_of(self, vec: np.ndarray):
        from fedml_tpu.comm.codec import vector_to_tree_np

        return vector_to_tree_np(np.asarray(vec, np.float32), self.spec)

    def gather(self, idx, default_params) -> np.ndarray:
        """``[k, D]`` personal vectors for the cohort; rows never
        scattered to read as ``default_params`` (the global adapters).
        Copy-on-read under the store lock: the returned array is a
        private snapshot whose rows are each one complete scatter."""
        idx = np.asarray(idx, np.int64)
        with self._lock:
            out = self._data[idx].astype(np.float32, copy=True)
            missing = ~self.seen[idx]
        if missing.any():
            out[missing] = self.vec_of(default_params)[None]
        return out

    def scatter(self, idx, vecs) -> None:
        idx = np.asarray(idx, np.int64)
        vecs = np.asarray(vecs, np.float32)
        with self._lock:
            self._data[idx] = vecs
            self.seen[idx] = True

    # -- checkpoint surface (bit-equal restore is test-pinned) ----------
    def state_dict(self) -> dict:
        with self._lock:
            return {"personal_vecs": np.array(self._data),
                    "personal_seen": np.array(self.seen)}

    def load_state_dict(self, state) -> None:
        vecs = np.asarray(state["personal_vecs"], np.float32)
        if vecs.shape != self._data.shape:
            raise ValueError(
                f"personal adapter checkpoint shape {vecs.shape} does not "
                f"match the store ({self._data.shape}) — different "
                "adapter rank/scope or client count")
        with self._lock:
            self._data[:] = vecs
            self.seen[:] = np.asarray(state["personal_seen"], bool)
