"""DARTS differentiable-NAS search space for FedNAS.

Parity target: reference fedml_api/model/cv/darts/ —
- operations set (operations.py): none / skip / avg_pool_3x3 / max_pool_3x3 /
  sep_conv_3x3 / sep_conv_5x5 / dil_conv_3x3 / dil_conv_5x5,
- MixedOp + Cell with 4 intermediate nodes, concat of the last
  ``multiplier`` states (model_search.py),
- architecture parameters alphas_normal/alphas_reduce of shape
  [n_edges, n_ops], n_edges = Σ(2+i) (model_search.py _initialize_alphas),
- genotype derivation: per node keep the top-2 incoming edges ranked by the
  strongest non-``none`` op weight (model_search.py genotype()).

TPU-first: alphas are ordinary flax params (``alphas_normal``/
``alphas_reduce`` at the network root), so the FedNAS bilevel update is a
params-pytree partition, not a separate parameter group object; all ops are
static-shaped NHWC modules; GroupNorm replaces BatchNorm (FL pathology —
see fedml_tpu/models/resnet.py) and the 2nd-order arch gradient is an exact
``jax.grad`` through one unrolled SGD step (fedml_tpu/algos/fednas.py),
replacing the reference's finite-difference Hessian-vector product
(darts/architect.py:229).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm

PRIMITIVES = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


class ReLUConvNorm(nn.Module):
    c_out: int
    kernel: int = 1
    strides: int = 1
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        x = nn.Conv(self.c_out, (self.kernel, self.kernel),
                    (self.strides, self.strides), padding="SAME",
                    use_bias=False)(x)
        return Norm(self.norm)(x, train)


class SepConv(nn.Module):
    """Depthwise-separable conv ×2 (reference operations.py SepConv)."""

    c_out: int
    kernel: int
    strides: int
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, s in enumerate((self.strides, 1)):
            c_in = x.shape[-1]
            x = nn.relu(x)
            x = nn.Conv(c_in, (self.kernel, self.kernel), (s, s),
                        padding="SAME", feature_group_count=c_in,
                        use_bias=False)(x)
            x = nn.Conv(self.c_out, (1, 1), use_bias=False)(x)
            x = Norm(self.norm)(x, train)
        return x


class DilConv(nn.Module):
    """Dilated depthwise-separable conv (reference operations.py DilConv)."""

    c_out: int
    kernel: int
    strides: int
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_in = x.shape[-1]
        x = nn.relu(x)
        x = nn.Conv(c_in, (self.kernel, self.kernel),
                    (self.strides, self.strides), padding="SAME",
                    kernel_dilation=(2, 2), feature_group_count=c_in,
                    use_bias=False)(x)
        x = nn.Conv(self.c_out, (1, 1), use_bias=False)(x)
        return Norm(self.norm)(x, train)


class FactorizedReduce(nn.Module):
    """Stride-2 channel-preserving reduce for skip on reduction edges."""

    c_out: int
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(x)
        a = nn.Conv(self.c_out // 2, (1, 1), (2, 2), use_bias=False)(x)
        # The shifted branch loses one row/col; on odd spatial dims its
        # stride-2 output would be one smaller than ``a``'s ceil(H/2), so
        # pad the shift back to keep both branches the same size.
        shifted = x[:, 1:, 1:, :]
        pad_h = x.shape[1] % 2
        pad_w = x.shape[2] % 2
        if pad_h or pad_w:
            shifted = jnp.pad(
                shifted, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        b = nn.Conv(self.c_out - self.c_out // 2, (1, 1), (2, 2),
                    use_bias=False)(shifted)
        x = jnp.concatenate([a, b], axis=-1)
        return Norm(self.norm)(x, train)


class MixedOp(nn.Module):
    """Softmax-weighted sum over all candidate ops on one edge."""

    c_out: int
    strides: int
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, w, train: bool = False):
        outs = []
        for prim in PRIMITIVES:
            s = self.strides
            if prim == "none":
                # SAME-padding output size = ceil(H/s), matching the pool
                # and conv branches on odd spatial dims.
                o = jnp.zeros(x.shape[:1] + (-(-x.shape[1] // s),
                                             -(-x.shape[2] // s),
                                             self.c_out), x.dtype)
            elif prim == "max_pool_3x3":
                o = nn.max_pool(x, (3, 3), strides=(s, s), padding="SAME")
            elif prim == "avg_pool_3x3":
                o = nn.avg_pool(x, (3, 3), strides=(s, s), padding="SAME")
            elif prim == "skip_connect":
                o = x if s == 1 else FactorizedReduce(self.c_out,
                                                      self.norm)(x, train)
            elif prim.startswith("sep_conv"):
                k = int(prim[-1])
                o = SepConv(self.c_out, k, s, self.norm)(x, train)
            else:  # dil_conv
                k = int(prim[-1])
                o = DilConv(self.c_out, k, s, self.norm)(x, train)
            outs.append(o)
        return sum(w[i] * outs[i] for i in range(len(PRIMITIVES)))


class SearchCell(nn.Module):
    """DARTS cell: ``steps`` intermediate nodes, dense edges from all
    predecessors, output = concat of last ``multiplier`` nodes."""

    c: int
    steps: int = 4
    multiplier: int = 4
    reduction: bool = False
    reduction_prev: bool = False
    norm: str = "gn"

    @nn.compact
    def __call__(self, s0, s1, weights, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.c, self.norm)(s0, train)
        else:
            s0 = ReLUConvNorm(self.c, 1, 1, self.norm)(s0, train)
        s1 = ReLUConvNorm(self.c, 1, 1, self.norm)(s1, train)
        states = [s0, s1]
        offset = 0
        for _ in range(self.steps):
            acc = None
            for j, h in enumerate(states):
                strides = 2 if self.reduction and j < 2 else 1
                o = MixedOp(self.c, strides, self.norm)(
                    h, weights[offset + j], train)
                acc = o if acc is None else acc + o
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


def n_edges(steps: int) -> int:
    return sum(2 + i for i in range(steps))


class DartsNetwork(nn.Module):
    """Searchable network (reference model_search.py Network)."""

    c: int = 16
    layers: int = 8
    steps: int = 4
    multiplier: int = 4
    stem_multiplier: int = 3
    num_classes: int = 10
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.multiplier > self.steps:
            raise ValueError(
                f"multiplier ({self.multiplier}) must be <= steps "
                f"({self.steps}): a cell concatenates its last `multiplier` "
                "INTERMEDIATE nodes, and there are only `steps` of them")
        E, K = n_edges(self.steps), len(PRIMITIVES)
        alphas_normal = self.param(
            "alphas_normal", nn.initializers.normal(1e-3), (E, K))
        alphas_reduce = self.param(
            "alphas_reduce", nn.initializers.normal(1e-3), (E, K))
        w_normal = nn.softmax(alphas_normal, axis=-1)
        w_reduce = nn.softmax(alphas_reduce, axis=-1)

        c_curr = self.stem_multiplier * self.c
        s = nn.Conv(c_curr, (3, 3), padding="SAME", use_bias=False)(x)
        s0 = s1 = Norm(self.norm)(s, train)

        c_curr = self.c
        reduction_prev = False
        reductions = {self.layers // 3, 2 * self.layers // 3} - {0}
        for layer in range(self.layers):
            reduction = layer in reductions
            if reduction:
                c_curr *= 2
            cell_out = SearchCell(
                c_curr, self.steps, self.multiplier, reduction,
                reduction_prev, self.norm,
            )(s0, s1, w_reduce if reduction else w_normal, train)
            s0, s1 = s1, cell_out
            reduction_prev = reduction
        out = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


class Genotype(NamedTuple):
    normal: Sequence[Tuple[str, int]]
    normal_concat: Sequence[int]
    reduce: Sequence[Tuple[str, int]]
    reduce_concat: Sequence[int]


def derive_genotype(alphas_normal, alphas_reduce, steps: int = 4,
                    multiplier: int = 4) -> Genotype:
    """Reference model_search.py genotype(): per node, keep the two
    incoming edges with the strongest non-none op; record (op, src)."""
    import numpy as np

    def parse(alphas):
        w = np.asarray(jnp.asarray(alphas))
        w = np.exp(w - w.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        gene, offset = [], 0
        none_idx = PRIMITIVES.index("none")
        for i in range(steps):
            n_in = 2 + i
            rows = w[offset:offset + n_in]
            scored = []
            for j in range(n_in):
                ops = np.delete(rows[j], none_idx)
                names = [p for p in PRIMITIVES if p != "none"]
                best = int(np.argmax(ops))
                scored.append((float(ops[best]), names[best], j))
            scored.sort(reverse=True)
            for score, name, j in scored[:2]:
                gene.append((name, j))
            offset += n_in
        return gene

    concat = list(range(2 + steps - multiplier, steps + 2))
    return Genotype(parse(alphas_normal), concat, parse(alphas_reduce), concat)


class GenotypeCell(nn.Module):
    """Discrete cell built from a searched genotype — the retraining model
    (reference darts/model.py Cell: each intermediate node sums its two
    chosen ops; output = concat of the genotype's concat nodes)."""

    genotype: Genotype
    c: int
    reduction: bool = False
    reduction_prev: bool = False
    norm: str = "gn"

    def _op(self, name: str, h, strides: int, train: bool):
        if name == "max_pool_3x3":
            return nn.max_pool(h, (3, 3), strides=(strides, strides), padding="SAME")
        if name == "avg_pool_3x3":
            return nn.avg_pool(h, (3, 3), strides=(strides, strides), padding="SAME")
        if name == "skip_connect":
            return h if strides == 1 else FactorizedReduce(self.c, self.norm)(h, train)
        if name.startswith("sep_conv"):
            return SepConv(self.c, int(name[-1]), strides, self.norm)(h, train)
        if name.startswith("dil_conv"):
            return DilConv(self.c, int(name[-1]), strides, self.norm)(h, train)
        raise ValueError(f"unknown genotype op {name!r}")

    @nn.compact
    def __call__(self, s0, s1, train: bool = False):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.c, self.norm)(s0, train)
        else:
            s0 = ReLUConvNorm(self.c, 1, 1, self.norm)(s0, train)
        s1 = ReLUConvNorm(self.c, 1, 1, self.norm)(s1, train)
        gene = self.genotype.reduce if self.reduction else self.genotype.normal
        concat = (self.genotype.reduce_concat if self.reduction
                  else self.genotype.normal_concat)
        states = [s0, s1]
        for i in range(0, len(gene), 2):
            acc = None
            for name, src in gene[i:i + 2]:
                strides = 2 if self.reduction and src < 2 else 1
                o = self._op(name, states[src], strides, train)
                acc = o if acc is None else acc + o
            states.append(acc)
        return jnp.concatenate([states[k] for k in concat], axis=-1)


class GenotypeNetwork(nn.Module):
    """Retraining network from a fixed genotype (reference darts/model.py
    NetworkCIFAR: stem → cells with reductions at 1/3 and 2/3 depth →
    pooled classifier)."""

    genotype: Genotype
    num_classes: int = 10
    c: int = 36
    layers: int = 8
    stem_multiplier: int = 3
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        c_curr = self.stem_multiplier * self.c
        x = nn.Conv(c_curr, (3, 3), padding="SAME", use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        s0 = s1 = x
        c = self.c
        reduction_prev = False
        # Same schedule as DartsNetwork (incl. the -{0} guard for tiny
        # depths) — the retrain net must match the search net that produced
        # the genotype.
        reductions = {self.layers // 3, 2 * self.layers // 3} - {0}
        for i in range(self.layers):
            reduction = i in reductions
            if reduction:
                c *= 2
            s0, s1 = s1, GenotypeCell(
                self.genotype, c, reduction, reduction_prev, self.norm
            )(s0, s1, train)
            reduction_prev = reduction
        x = jnp.mean(s1, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


@register_model("darts")
def darts(num_classes: int = 10, c: int = 16, layers: int = 8,
          steps: int = 4, multiplier: int = 4, norm: str = "gn", **_):
    return DartsNetwork(c=c, layers=layers, steps=steps,
                        multiplier=multiplier, num_classes=num_classes,
                        norm=norm)


@register_model("darts_genotype")
def darts_genotype(genotype: Genotype, num_classes: int = 10, c: int = 16,
                   layers: int = 8, norm: str = "gn", **_):
    """Retrain a searched architecture (reference darts/train.py path)."""
    # Hashable genotype (tuples, not lists) — flax module fields are static.
    genotype = Genotype(
        tuple(tuple(e) for e in genotype.normal),
        tuple(genotype.normal_concat),
        tuple(tuple(e) for e in genotype.reduce),
        tuple(genotype.reduce_concat),
    )
    return GenotypeNetwork(genotype=genotype, num_classes=num_classes, c=c,
                           layers=layers, norm=norm)


def genotype_to_dot(genotype: Genotype, which: str = "normal",
                    name: str = "cell") -> str:
    """Render one cell of a genotype as Graphviz DOT text.

    Role parity with the reference's darts visualizer (model/cv/darts/
    visualize.py), which shells out to the ``graphviz`` package; emitting
    DOT text keeps the framework dependency-free — pipe the string to any
    ``dot -Tpdf`` to get the same drawing. Nodes: the two input states
    ``c_{k-2}``/``c_{k-1}``, the intermediate steps, and ``c_{k}``; one
    labeled edge per (op, src) genotype entry; concat edges into ``c_{k}``.
    """
    if which not in ("normal", "reduce"):
        raise ValueError(f"which must be 'normal' or 'reduce', got {which!r}")
    edges = getattr(genotype, which)
    concat = getattr(genotype, f"{which}_concat")
    steps = len(edges) // 2

    def node(i: int) -> str:
        return {0: '"c_{k-2}"', 1: '"c_{k-1}"'}.get(i, f'"{i - 2}"')

    lines = [
        f'digraph "{name}_{which}" {{',
        "  rankdir=LR;",
        '  node [shape=box style=rounded];',
        '  "c_{k-2}" [shape=oval];',
        '  "c_{k-1}" [shape=oval];',
        '  "c_{k}" [shape=oval];',
    ]
    for step in range(steps):
        for op, src in edges[2 * step: 2 * step + 2]:
            lines.append(f'  {node(src)} -> "{step}" [label="{op}"];')
    for src in concat:
        lines.append(f'  {node(src)} -> "c_{{k}}";')
    lines.append("}")
    return "\n".join(lines)
