"""EfficientNet B0–B7 (Tan & Le 2019) in flax.

Parity target: reference fedml_api/model/cv/efficientnet.py:36-305 +
efficientnet_utils.py (MBConv blocks with squeeze-excite and drop-connect,
compound width/depth scaling per variant, swish activation).

TPU-first: NHWC, depthwise convs as grouped contractions, GroupNorm default
(reference uses BatchNorm; ``norm='bn'`` gives strict parity), stochastic
depth (drop-connect) via per-sample bernoulli mask under the 'dropout' rng.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.resnet import Norm

# (width_mult, depth_mult, resolution, dropout) per variant
# (reference efficientnet_utils.py params dict).
_PARAMS = {
    "b0": (1.0, 1.0, 224, 0.2), "b1": (1.0, 1.1, 240, 0.2),
    "b2": (1.1, 1.2, 260, 0.3), "b3": (1.2, 1.4, 300, 0.3),
    "b4": (1.4, 1.8, 380, 0.4), "b5": (1.6, 2.2, 456, 0.4),
    "b6": (1.8, 2.6, 528, 0.5), "b7": (2.0, 3.1, 600, 0.5),
}

# Base B0 stage plan: (expand, channels, repeats, kernel, stride)
# (reference efficientnet.py blocks_args / efficientnet_utils decode).
_BASE_PLAN: Sequence[Tuple[int, int, int, int, int]] = (
    (1, 16, 1, 3, 1), (6, 24, 2, 3, 2), (6, 40, 2, 5, 2), (6, 80, 3, 3, 2),
    (6, 112, 3, 5, 1), (6, 192, 4, 5, 2), (6, 320, 1, 3, 1),
)


def round_filters(filters: int, width_mult: float, divisor: int = 8) -> int:
    """Channel rounding to multiples of 8 (reference efficientnet_utils
    round_filters) — also MXU-lane friendly."""
    filters *= width_mult
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:
        new += divisor
    return int(new)


def round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * repeats))


class MBConv(nn.Module):
    """Mobile inverted bottleneck + SE + drop-connect
    (reference MBConvBlock efficientnet.py:36-135)."""

    expand: int
    out_ch: int
    kernel: int
    strides: int
    se_ratio: float = 0.25
    drop_rate: float = 0.0
    norm: str = "gn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        residual = x
        y = x
        mid = in_ch * self.expand
        if self.expand != 1:
            y = nn.Conv(mid, (1, 1), use_bias=False)(y)
            y = Norm(self.norm)(y, train)
            y = nn.swish(y)
        y = nn.Conv(mid, (self.kernel, self.kernel),
                    (self.strides, self.strides), padding="SAME",
                    feature_group_count=mid, use_bias=False)(y)
        y = Norm(self.norm)(y, train)
        y = nn.swish(y)
        # Squeeze-excite on pre-expansion channel count.
        se_ch = max(1, int(in_ch * self.se_ratio))
        s = jnp.mean(y, axis=(1, 2))
        s = nn.swish(nn.Dense(se_ch)(s))
        s = nn.sigmoid(nn.Dense(mid)(s))
        y = y * s[:, None, None, :]
        y = nn.Conv(self.out_ch, (1, 1), use_bias=False)(y)
        y = Norm(self.norm)(y, train)
        if self.strides == 1 and in_ch == self.out_ch:
            if train and self.drop_rate > 0.0:
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(
                    rng, keep, (y.shape[0], 1, 1, 1)).astype(y.dtype)
                y = y * mask / keep
            y = y + residual
        return y


class EfficientNet(nn.Module):
    """Reference EfficientNet efficientnet.py:138-305 with compound scaling."""

    variant: str = "b0"
    num_classes: int = 10
    norm: str = "gn"
    small_input: bool = True
    drop_connect_rate: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        w_mult, d_mult, _res, dropout = _PARAMS[self.variant]
        stem_strides = 1 if self.small_input else 2
        x = nn.Conv(round_filters(32, w_mult), (3, 3),
                    (stem_strides, stem_strides), padding="SAME",
                    use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = nn.swish(x)
        total_blocks = sum(round_repeats(r, d_mult) for _, _, r, _, _ in _BASE_PLAN)
        idx = 0
        for expand, ch, repeats, kernel, stride in _BASE_PLAN:
            out_ch = round_filters(ch, w_mult)
            for i in range(round_repeats(repeats, d_mult)):
                x = MBConv(
                    expand, out_ch, kernel, stride if i == 0 else 1,
                    drop_rate=self.drop_connect_rate * idx / total_blocks,
                    norm=self.norm,
                )(x, train)
                idx += 1
        x = nn.Conv(round_filters(1280, w_mult), (1, 1), use_bias=False)(x)
        x = Norm(self.norm)(x, train)
        x = nn.swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register_model("efficientnet")
def efficientnet(num_classes: int = 10, variant: str = "b0", norm: str = "gn",
                 small_input: bool = True, drop_connect_rate: float = 0.2, **_):
    return EfficientNet(variant=variant, num_classes=num_classes, norm=norm,
                        small_input=small_input,
                        drop_connect_rate=drop_connect_rate)
