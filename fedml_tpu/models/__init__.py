"""Model zoo (flax.linen), capability parity with the reference's
``fedml_api/model`` (SURVEY.md §2.6). Models are created through
``create_model(name, ...)`` mirroring the reference's ``create_model`` switch
(fedml_experiments/distributed/fedavg/main_fedavg.py:354-390)."""

from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.models.pretrained import load_params, save_params
from fedml_tpu.models.registry import create_model, register_model
from fedml_tpu.models.torch_convert import (
    load_torch_checkpoint,
    load_torch_gkt_checkpoint,
)

__all__ = ["LogisticRegression", "create_model", "register_model",
           "save_params", "load_params", "load_torch_checkpoint",
           "load_torch_gkt_checkpoint"]
