"""Model zoo (flax.linen), capability parity with the reference's
``fedml_api/model`` (SURVEY.md §2.6). Models are created through
``create_model(name, ...)`` mirroring the reference's ``create_model`` switch
(fedml_experiments/distributed/fedavg/main_fedavg.py:354-390)."""

from fedml_tpu.models.lr import LogisticRegression
from fedml_tpu.models.registry import create_model, register_model

__all__ = ["LogisticRegression", "create_model", "register_model"]
