"""Name → constructor registry for the model zoo.

Mirrors the reference's ``create_model(args, model_name, output_dim)`` switch
(fedml_experiments/distributed/fedavg/main_fedavg.py:354-390) as an extensible
registry instead of an if/elif chain.
"""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def resolve_dtype(dtype):
    """'bf16'/'bfloat16' → jnp.bfloat16 (CLI-friendly); None/np dtype
    passthrough. The shared compute-dtype convention for every model
    factory that supports mixed precision."""
    if dtype in ("bf16", "bfloat16"):
        import jax.numpy as jnp

        return jnp.bfloat16
    return dtype


def register_model(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def create_model(name: str, **kwargs):
    if name not in _REGISTRY:
        # Import side-effect registration of the full zoo. Keep this list in
        # sync with the modules that exist — import errors must propagate.
        import fedml_tpu.models.cnn  # noqa: F401
        import fedml_tpu.models.darts  # noqa: F401
        import fedml_tpu.models.efficientnet  # noqa: F401
        import fedml_tpu.models.gan  # noqa: F401
        import fedml_tpu.models.lr  # noqa: F401
        import fedml_tpu.models.mobilenet  # noqa: F401
        import fedml_tpu.models.mobilenet_v3  # noqa: F401
        import fedml_tpu.models.resnet  # noqa: F401
        import fedml_tpu.models.resnet_split  # noqa: F401
        import fedml_tpu.models.rnn  # noqa: F401
        import fedml_tpu.models.transformer  # noqa: F401
        import fedml_tpu.models.unet  # noqa: F401
        import fedml_tpu.models.vfl  # noqa: F401
        import fedml_tpu.models.vgg  # noqa: F401
        import fedml_tpu.models.vit  # noqa: F401
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
