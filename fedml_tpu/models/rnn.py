"""Federated language models (LSTM).

Parity: fedml_api/model/nlp/rnn.py —
- ``RNNOriginalFedAvg`` (:4-36): McMahan'17 Shakespeare char-LM — embedding
  (vocab 90 → 8), 2×LSTM(256), dense to vocab; next-char logits at every
  position.
- ``RNNStackOverflow`` (:39-70): Reddi'20 next-word prediction — embedding
  (vocab 10004 → 96), 1×LSTM(670), dense 96 then dense to vocab.

Inputs are int token ids ``[B, T]``; outputs ``[B, T, vocab]``. Pair with
``seq_softmax_ce`` (mean next-token CE per example) from the trainer.
``nn.RNN``/``OptimizedLSTMCell`` unrolls under ``lax.scan``; XLA fuses the
gate matmuls into MXU-friendly batched GEMMs.
"""

from __future__ import annotations

import flax.linen as nn

from fedml_tpu.models.registry import register_model


class RNNOriginalFedAvg(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    vocab_size: int = 10004  # 10000 + pad/bos/eos/oov
    embedding_dim: int = 96
    hidden_size: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)


@register_model("rnn")
def rnn(vocab_size: int = 90, **_):
    return RNNOriginalFedAvg(vocab_size=vocab_size)


@register_model("rnn_stackoverflow")
def rnn_stackoverflow(vocab_size: int = 10004, **_):
    return RNNStackOverflow(vocab_size=vocab_size)
