"""Vision Transformer classifier (flax), reusing the transformer encoder
blocks with non-causal attention.

New TPU-era capability — the reference's vision zoo tops out at CNNs
(ResNet/VGG/EfficientNet, fedml_api/model/cv/). A ViT is the natural
MXU-friendly image model: patch embedding is one big matmul and the
encoder is the same Block as the transformer LM, so the pluggable
``attn_fn`` (pallas flash attention on chip) carries over unchanged.
Mean-pooled (GAP) head rather than a class token — simpler and just as
standard for small ViTs; no BatchNorm anywhere, so the model is
federated-safe by construction (no running stats to average).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.models.transformer import Block


class ViT(nn.Module):
    num_classes: int
    patch: int = 4
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 4
    dropout: float = 0.0
    attn_fn: Optional[Callable] = None  # e.g. pallas flash attention

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, h, w, c = x.shape
        if h % self.patch or w % self.patch:
            raise ValueError(
                f"image {h}x{w} not divisible by patch size {self.patch}")
        # Patchify: one conv with stride=patch — a single strided matmul
        # on the MXU, no im2col on the host.
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), name="patch_embed")(x)
        x = x.reshape(b, -1, self.d_model)  # [B, T=h*w/p^2, D]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, x.shape[1], self.d_model))
        x = x + pos
        if self.dropout and train:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for _ in range(self.n_layers):
            x = Block(self.n_heads, self.d_model, attn_fn=self.attn_fn,
                      causal=False)(x, train)
        x = nn.LayerNorm()(x)
        x = jnp.mean(x, axis=1)  # GAP head
        return nn.Dense(self.num_classes, name="head")(x)


@register_model("vit")
def vit(num_classes: int = 10, patch: int = 4, d_model: int = 128,
        n_heads: int = 4, n_layers: int = 4, dropout: float = 0.0,
        attn_fn: Optional[Callable] = None, **_):
    """ViT-Tiny-ish default sized for CIFAR (32x32/4 → 64 tokens)."""
    return ViT(num_classes=num_classes, patch=patch, d_model=d_model,
               n_heads=n_heads, n_layers=n_layers, dropout=dropout,
               attn_fn=attn_fn)
