"""Decoder-only transformer LM (flax) with pluggable attention.

New TPU-era capability (the reference's NLP ceiling is an 80-char LSTM,
model/nlp/rnn.py:4): a causal LM whose attention implementation is injected
— dense single-chip attention by default, ring attention over a mesh
``sp`` axis for long-context training (fedml_tpu.parallel.ring_attention).
Pre-LN blocks, learned positional embeddings, bf16-friendly.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from fedml_tpu.models.registry import register_model
from fedml_tpu.parallel.ring_attention import reference_attention

#: The adapter scopes the factory accepts: which dense projections get a
#: low-rank (LoRA) pair injected NEXT TO them. Base param paths are
#: UNCHANGED by injection (the adapters are extra ``lora_*`` params in
#: the same module), so a dense-trained checkpoint loads straight into
#: the adapter model's frozen base (models/adapter.py splits by name).
ADAPTER_SCOPES = ("attn", "mlp", "all")


def lora_delta(a, b, x, *, alpha: float, rank: int):
    """The low-rank residual ``(alpha/rank) * (x @ A) @ B`` (Hu et al.
    2021; FedPara/LoRA-style low-rank updates, arXiv:2108.06098) for ONE
    adapter pair — the single expression both the training-time module
    injection (:func:`_lora_delta`) and the serving plane's KV-decode
    path (fedml_tpu.serve.forward) evaluate, so the two can never
    diverge numerically."""
    return (alpha / rank) * ((x @ a) @ b)


def lora_delta_batched(a, b, x, *, alpha: float, rank: int):
    """Batched-B twin of :func:`lora_delta`: ``B`` per-row adapter pairs
    ``a [B, d, r]`` / ``b [B, r, o]`` applied to ``x [B, ..., d]`` inside
    ONE dispatch — the multi-tenant serving move (fedml_tpu.serve): B
    different personalized models share a single batched forward instead
    of B per-request dispatches. The contraction order matches
    :func:`lora_delta` exactly (x·A then ·B, scale last), so the B=1
    slice is bitwise-equal to the per-request path (test-pinned)."""
    xa = jnp.einsum("b...d,bdr->b...r", x, a)
    return (alpha / rank) * jnp.einsum("b...r,bro->b...o", xa, b)


def _lora_delta(mod: nn.Module, name: str, x, out_dim: int, rank: int,
                alpha: float, dtype):
    """Module-side injection of :func:`lora_delta`: creates the pair next
    to a dense projection. ``A`` is small-normal, ``B`` zero — the
    injected model is exactly the base model at init. Param names carry
    the ``lora_`` prefix :mod:`fedml_tpu.models.adapter` splits on."""
    a = mod.param(f"lora_{name}_a", nn.initializers.normal(0.02),
                  (x.shape[-1], rank))
    b = mod.param(f"lora_{name}_b", nn.initializers.zeros, (rank, out_dim))
    if dtype is not None:
        a, b = a.astype(dtype), b.astype(dtype)
    return lora_delta(a, b, x, alpha=alpha, rank=rank)


class MHA(nn.Module):
    n_heads: int
    d_model: int
    attn_fn: Optional[Callable] = None  # (q,k,v[,causal]) -> o, else dense
    causal: bool = True
    dtype: Any = None  # compute dtype (params stay float32)
    adapter_rank: int = 0  # 0 = no adapters: param tree identical to pre-LoRA
    adapter_alpha: float = 16.0

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        d_head = self.d_model // self.n_heads
        qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=self.dtype)(x)
        if self.adapter_rank:
            qkv = qkv + _lora_delta(self, "qkv", x, 3 * self.d_model,
                                    self.adapter_rank, self.adapter_alpha,
                                    self.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, t, self.n_heads, d_head)
        q, k, v = q.reshape(shp), k.reshape(shp), v.reshape(shp)
        if self.attn_fn is not None:
            # Forward causal when the injected attention accepts it (e.g.
            # flash_attention defaults to causal=False — silently building a
            # non-causal decoder would make training look great and
            # generation garbage). Pre-bound callables (ring attention from
            # make_ring_attention, partials, lambdas) are used as-is.
            import inspect

            try:
                accepts_causal = "causal" in inspect.signature(self.attn_fn).parameters
            except (TypeError, ValueError):
                accepts_causal = False
            if accepts_causal:
                o = self.attn_fn(q, k, v, causal=self.causal)
            else:
                o = self.attn_fn(q, k, v)
        else:
            o = reference_attention(q, k, v, causal=self.causal)
        o = o.reshape(b, t, self.d_model)
        out = nn.Dense(self.d_model, use_bias=False, dtype=self.dtype)(o)
        if self.adapter_rank:
            out = out + _lora_delta(self, "out", o, self.d_model,
                                    self.adapter_rank, self.adapter_alpha,
                                    self.dtype)
        return out


class Block(nn.Module):
    n_heads: int
    d_model: int
    mlp_ratio: int = 4
    attn_fn: Optional[Callable] = None
    causal: bool = True
    dtype: Any = None
    adapter_rank: int = 0
    adapter_scope: str = "attn"  # which projections get LoRA pairs
    adapter_alpha: float = 16.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        r = self.adapter_rank
        attn_r = r if self.adapter_scope in ("attn", "all") else 0
        mlp_r = r if self.adapter_scope in ("mlp", "all") else 0
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MHA(self.n_heads, self.d_model, self.attn_fn, self.causal,
                    dtype=self.dtype, adapter_rank=attn_r,
                    adapter_alpha=self.adapter_alpha)(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        up = nn.Dense(self.mlp_ratio * self.d_model, dtype=self.dtype)(h)
        if mlp_r:
            up = up + _lora_delta(self, "mlp_in", h,
                                  self.mlp_ratio * self.d_model, mlp_r,
                                  self.adapter_alpha, self.dtype)
        up = nn.gelu(up)
        down = nn.Dense(self.d_model, dtype=self.dtype)(up)
        if mlp_r:
            down = down + _lora_delta(self, "mlp_out", up, self.d_model,
                                      mlp_r, self.adapter_alpha, self.dtype)
        return x + down


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    max_len: int = 2048
    attn_fn: Optional[Callable] = None
    causal: bool = True
    dtype: Any = None  # compute dtype; jnp.bfloat16 = mixed precision
    #: LoRA adapter injection (models/adapter.py): rank 0 leaves the
    #: param tree byte-identical to the pre-adapter model; rank > 0 adds
    #: ``lora_*`` pairs next to the scoped projections. Embeddings and
    #: the logits head stay base-only (frozen in adapter finetuning).
    adapter_rank: int = 0
    adapter_scope: str = "attn"
    adapter_alpha: float = 16.0

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        b, t = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype)(tokens)
        pos = nn.Embed(self.max_len, self.d_model,
                       dtype=self.dtype)(jnp.arange(t))
        x = x + pos[None]
        for _ in range(self.n_layers):
            x = Block(self.n_heads, self.d_model, attn_fn=self.attn_fn,
                      causal=self.causal, dtype=self.dtype,
                      adapter_rank=self.adapter_rank,
                      adapter_scope=self.adapter_scope,
                      adapter_alpha=self.adapter_alpha)(x, train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        # Logits in f32: softmax-CE over a 10k vocab is the one place bf16
        # rounding visibly hurts the loss.
        return nn.Dense(self.vocab_size, use_bias=False)(x).astype(jnp.float32)


@register_model("transformer_lm")
def transformer_lm(vocab_size: int = 90, d_model: int = 128, n_heads: int = 4,
                   n_layers: int = 2, max_len: int = 2048,
                   attn_fn: Optional[Callable] = None, causal: bool = True,
                   attn: str = "dense", dtype=None, adapter_rank: int = 0,
                   adapter_scope: str = "attn", adapter_alpha: float = 16.0,
                   **_):
    """``attn="flash"`` swaps in the pallas fused kernel
    (fedml_tpu.ops.flash_attention) — O(T) memory, faster than dense on
    TPU from T≈2k with bf16 activations (measured crossover: bench
    flash_attention_sweep). ``attn_fn`` (a callable) overrides both.

    ``adapter_rank > 0`` injects LoRA pairs (scope ``attn`` | ``mlp`` |
    ``all``) for parameter-efficient federated finetuning — see
    fedml_tpu.models.adapter / fedml_tpu.algos.fedadapter."""
    if attn_fn is None and attn == "flash":
        from fedml_tpu.ops.flash_attention import flash_attention
        attn_fn = flash_attention  # MHA forwards causal= (it inspects)
    elif attn_fn is None and attn != "dense":
        raise ValueError(f"unknown attn {attn!r}: expected dense|flash")
    if adapter_rank and adapter_scope not in ADAPTER_SCOPES:
        raise ValueError(
            f"unknown adapter_scope {adapter_scope!r}: expected one of "
            f"{ADAPTER_SCOPES}")
    if adapter_rank < 0:
        raise ValueError(f"adapter_rank must be >= 0, got {adapter_rank}")
    from fedml_tpu.models.registry import resolve_dtype
    return TransformerLM(vocab_size=vocab_size, d_model=d_model,
                         n_heads=n_heads, n_layers=n_layers, max_len=max_len,
                         attn_fn=attn_fn, causal=causal,
                         dtype=resolve_dtype(dtype),
                         adapter_rank=int(adapter_rank),
                         adapter_scope=adapter_scope,
                         adapter_alpha=float(adapter_alpha))
