"""Lane-fill compute layouts: logical model, lane-aligned client step.

docs/ROOFLINE.md pins why the CIFAR CNN hot path under-delivers: channel
dims below the MXU's 128-lane width leave lanes idle (dw-eff 0.31 → 1.04
exactly as channels reach 128). This module makes channel-dim padding a
FRAMEWORK capability instead of a per-model fork, with a hard invisibility
contract:

- the **logical** model — what clients train against, servers aggregate,
  checkpoints store, the wire ships, and every bit-equality pin sees —
  keeps its reference shapes everywhere;
- the jitted client step runs a **physical** twin whose channel dims are
  padded up to lane/sublane-friendly multiples, via a pure pad-on-entry /
  slice-on-exit wrapper around the local trainer
  (:func:`wrap_local_train`). Padding never crosses the client-step
  boundary.

The padded twin is EXACT, not approximate (tested bit-equal in fp32,
tests/test_layout.py): every padded parameter entry is zero and *stays*
zero through training — zero input-channel slices contribute nothing
forward, and the zero output-filters receive zero gradient back (the
classifier's padded input rows are zero, so no gradient ever reaches a
padded channel). GroupNorm is the one layer where padding could leak:
the pad channels must fill WHOLE extra groups of the logical group size
(``models/resnet.Norm(logical_channels=...)``), where they normalize to
exactly zero; :func:`pad_channels` bakes that constraint into the pad
quantum. Dropout-bearing models are REFUSED: their mask draw shapes
follow the physical layout, so padded-vs-logical exactness is
unattainable by construction.

When padding pays vs hurts (measured — docs/EXECUTION.md "MFU
playbook"): the MXU charges a full 128-lane pass whatever the channel
count, so padding an already-small dim (16 → 128) multiplies FLOPs
without moving wall-clock; padding pays on dims sitting just UNDER a
lane multiple (96/120 → 128) and is near-free otherwise. MFU accounting
here is always against the LOGICAL model's FLOPs — padding can never
inflate the numerator.

Supported families: ``CifarResNet`` (gn/bn/none norms) and
``CNNOriginalFedAvg``. Others refuse loudly. Space-to-depth stems
(``stem="s2d"``) compose — s2d trades spatial extent for channel depth
at constant FLOPs and remains the first lever; this transform squares
up whatever widths remain misaligned.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class LayoutPolicy:
    """The pad policy: round channel dims up to ``sublane`` multiples,
    and snap to the next ``lane`` multiple when already within
    ``lane_snap`` of it (96 → 128 at the default 0.25; 16 stays 16 —
    padding 8x the FLOPs for an already-paid lane pass hurts,
    docs/ROOFLINE.md)."""

    lane: int = 128
    sublane: int = 8
    lane_snap: float = 0.25


def pad_width(c: int, policy: LayoutPolicy) -> int:
    """The policy's target physical width for a logical channel count
    (before any GroupNorm group-quantum constraint)."""
    target = -(-c // policy.sublane) * policy.sublane
    next_lane = -(-c // policy.lane) * policy.lane
    if (next_lane - c) <= policy.lane_snap * policy.lane:
        target = max(target, next_lane)
    return target


def pad_channels(c: int, policy: LayoutPolicy, quanta: Tuple[int, ...] = ()
                 ) -> int:
    """Smallest physical width >= the policy target that is a multiple of
    the sublane AND of every ``quanta`` entry (GroupNorm group sizes at
    each scale the width appears at — pad channels must fill whole
    groups or the logical statistics change). Never below ``c``."""
    q = math.lcm(policy.sublane, *quanta) if quanta else policy.sublane
    target = max(pad_width(c, policy), c)
    p = -(-target // q) * q
    return max(p, c)


def _pad_spec(logical_shape, physical_shape):
    if len(logical_shape) != len(physical_shape) or any(
            p < l for l, p in zip(logical_shape, physical_shape)):
        raise ValueError(
            f"physical leaf {physical_shape} does not embed logical "
            f"{logical_shape}")
    return tuple((0, p - l) for l, p in zip(logical_shape, physical_shape))


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


@dataclass
class ComputeLayout:
    """The logical↔physical mapping for one model: a physical twin
    module plus pure, jit-traceable ``pad`` (embed logical params into
    the zero-initialized physical tree) and ``unpad`` (slice the logical
    block back out). ``pad``/``unpad`` operate on ``NetState``-shaped
    pytrees (params + model_state) and are exact inverses on the
    logical block."""

    logical_model: Any
    physical_model: Any
    #: path-string → (pad_leaf, unpad_leaf) overrides for leaves whose
    #: logical block is not a leading slice (flatten-boundary Dense
    #: kernels interleave channels into the row index).
    overrides: Dict[str, Tuple[Callable, Callable]] = field(
        default_factory=dict)
    #: flatten-order-aligned per-leaf records, built by ``_build_specs``:
    #: (path string, logical shape, pad spec or None-for-override)
    _leaves: Any = None

    @property
    def is_identity(self) -> bool:
        return not self.overrides and all(
            spec is not None and not any(hi for _, hi in spec)
            for _, _, spec in self._leaves)

    def _build_specs(self, sample_x):
        from fedml_tpu.trainer.local import model_fns

        sample = sample_x if hasattr(sample_x, "dtype") else \
            jax.ShapeDtypeStruct(np.shape(sample_x),
                                 np.asarray(sample_x).dtype)
        key = jax.ShapeDtypeStruct((2,), np.uint32)

        def shapes(module):
            fns = model_fns(module)
            return jax.eval_shape(lambda k, x: fns.init(k, x), key, sample)

        log, phys = shapes(self.logical_model), shapes(self.physical_model)
        paths_l, treedef_l = jax.tree_util.tree_flatten_with_path(log)
        paths_p, treedef_p = jax.tree_util.tree_flatten_with_path(phys)
        if treedef_l != treedef_p:
            raise ValueError(
                "logical and physical models have different param trees")
        leaves = []
        for (pl, ll), (pp, lp) in zip(paths_l, paths_p):
            if ll.dtype != lp.dtype:
                raise ValueError(
                    f"{_path_str(pl)}: dtype drift {ll.dtype} vs {lp.dtype}")
            path = _path_str(pl)
            spec = None if path in self.overrides \
                else _pad_spec(ll.shape, lp.shape)
            leaves.append((path, tuple(ll.shape), spec))
        unknown = set(self.overrides) - {p for p, _, _ in leaves}
        if unknown:
            raise ValueError(f"override paths not in the param tree: "
                             f"{sorted(unknown)}")
        self._leaves = leaves

    def _apply(self, net, which: int):
        paths, treedef = jax.tree_util.tree_flatten_with_path(net)
        if len(paths) != len(self._leaves):
            raise ValueError(
                f"net has {len(paths)} leaves, layout expects "
                f"{len(self._leaves)}")
        out = []
        for (p, leaf), (path, shape, spec) in zip(paths, self._leaves):
            if _path_str(p) != path:
                raise ValueError(
                    f"leaf order mismatch: {_path_str(p)} vs {path}")
            if spec is None:
                out.append(self.overrides[path][which](leaf))
            elif which == 0:  # pad
                out.append(jnp.pad(leaf, spec)
                           if any(hi for _, hi in spec) else leaf)
            else:  # unpad
                out.append(leaf if tuple(leaf.shape) == shape else
                           leaf[tuple(slice(0, s) for s in shape)])
        return jax.tree.unflatten(treedef, out)

    def pad(self, net):
        """Logical NetState → physical (zero-fill the pad block). Pure;
        traced inside the jitted client step."""
        return self._apply(net, 0)

    def unpad(self, net):
        """Physical NetState → logical (slice the leading block)."""
        return self._apply(net, 1)

    def describe(self) -> Dict[str, Any]:
        """Machine-readable summary (bench/docs): logical param count
        and how many leaves carry pad."""
        padded = sum(1 for _, _, s in self._leaves
                     if s is None or any(hi for _, hi in s))
        return {"leaves": len(self._leaves), "padded_leaves": padded,
                "logical_params": int(sum(
                    np.prod(s) for _, s, _ in self._leaves)),
                "identity": self.is_identity}


# --- model-family physical-twin builders ------------------------------

def _cifar_resnet_twin(model, policy: LayoutPolicy):
    from fedml_tpu.models.resnet import norm_groups

    if model.norm not in ("gn", "bn", "none"):
        raise NotImplementedError(
            f"compute_layout supports CifarResNet norm in gn|bn|none; "
            f"got {model.norm!r}")
    if model.logical_widths or model.logical_stem:
        raise ValueError("model is already a padded physical twin")
    stem_ch, widths = model.stage_widths()
    gn = model.norm == "gn"

    def quanta(width, scales):
        # GroupNorm sites this stage width feeds (x1 for the in-block
        # norms, x expansion for the block output): a physical width p
        # appears at each site as p*scale channels, which must hold
        # whole logical groups — (p*scale) % cpg(w*scale) == 0, i.e.
        # p % (cpg / gcd(scale, cpg)) == 0.
        if not gn:
            return ()
        out = []
        for scale in scales:
            c = width * scale
            cpg = c // norm_groups(c)
            out.append(cpg // math.gcd(scale, cpg))
        return tuple(out)

    e = 4  # BottleneckBlock expansion
    p_widths = tuple(pad_channels(w, policy, quanta(w, (1, e)))
                     for w in widths)
    p_stem = pad_channels(stem_ch, policy, quanta(stem_ch, (1,)))
    if p_widths == tuple(widths) and p_stem == stem_ch:
        return model  # identity
    return type(model)(
        layers=tuple(model.layers), num_classes=model.num_classes,
        norm=model.norm, dtype=model.dtype, stem=model.stem,
        widths=p_widths, stem_width=p_stem,
        logical_widths=tuple(widths), logical_stem=stem_ch), {}


def _cnn_original_twin(model, policy: LayoutPolicy, sample_x):
    c1, c2 = model.widths or (32, 64)
    p1, p2 = pad_channels(c1, policy), pad_channels(c2, policy)
    if (p1, p2) == (c1, c2):
        return model
    twin = type(model)(num_classes=model.num_classes,
                       only_digits=model.only_digits, stem=model.stem,
                       widths=(p1, p2), hidden=model.hidden)
    # Flatten boundary: Dense_0's kernel rows interleave (h, w, channel)
    # — a tail pad would bind logical weights to the wrong physical
    # rows. Pad/slice the channel axis through a reshape instead.
    shape = np.shape(sample_x)
    h, w = shape[1], shape[2]
    if model.stem == "s2d":
        h, w = h // 2, w // 2
    h, w = h // 4, w // 4  # two 2x2 max-pools on SAME convs
    hidden = twin.hidden

    def pad_dense(leaf):
        k = leaf.reshape(h, w, c2, hidden)
        return jnp.pad(k, ((0, 0), (0, 0), (0, p2 - c2), (0, 0))).reshape(
            h * w * p2, hidden)

    def unpad_dense(leaf):
        return leaf.reshape(h, w, p2, hidden)[:, :, :c2].reshape(
            h * w * c2, hidden)

    return twin, {".params/Dense_0/kernel": (pad_dense, unpad_dense)}


def compute_layout(model, sample_x, *, lane: int = 128, sublane: int = 8,
                   lane_snap: float = 0.25):
    """Build the lane-fill :class:`ComputeLayout` for a supported model,
    or raise ``NotImplementedError`` naming the supported families.
    Returns a layout whose ``is_identity`` is True when the policy pads
    nothing (callers then skip the wrapper entirely).

    ``sample_x``: one batched input (shape/dtype only) — flatten-boundary
    leaf mappings depend on the feature-map dims."""
    from fedml_tpu.models.cnn import CNNDropOut, CNNOriginalFedAvg
    from fedml_tpu.models.resnet import CifarResNet

    policy = LayoutPolicy(lane=lane, sublane=sublane, lane_snap=lane_snap)
    overrides: Dict[str, Tuple[Callable, Callable]] = {}
    if isinstance(model, CifarResNet):
        twin = _cifar_resnet_twin(model, policy)
    elif isinstance(model, CNNOriginalFedAvg):
        twin = _cnn_original_twin(model, policy, sample_x)
    elif isinstance(model, CNNDropOut):
        raise NotImplementedError(
            "compute_layout cannot pad dropout-bearing models: the mask "
            "draw shapes follow the PHYSICAL layout, so padded-vs-logical "
            "exactness is unattainable by construction (CNNDropOut; use "
            "CNNOriginalFedAvg or a GroupNorm conv net)")
    else:
        raise NotImplementedError(
            f"compute_layout has no physical-twin builder for "
            f"{type(model).__name__}; supported: CifarResNet (gn/bn/none"
            "), CNNOriginalFedAvg")
    if isinstance(twin, tuple):
        twin, overrides = twin
    layout = ComputeLayout(logical_model=model, physical_model=twin,
                           overrides=overrides)
    layout._build_specs(sample_x)
    return layout


def step_dtype_model(model, dtype):
    """COMPUTE-dtype twin for the bf16 client step
    (``cfg.client_step_dtype="bf16"``): a clone of ``model`` whose
    layers compute in ``dtype`` while the PARAM TREE stays float32
    (flax's ``dtype=`` casts inputs and params at each layer's compute;
    ``param_dtype`` is untouched) — so the jitted client step's matmuls
    run at bf16 MXU rate while gradients, the optimizer update, the
    aggregation, and the server carry all stay fp32. The param tree is
    structurally identical to the logical model's, so everything above
    the client step (checkpoints, the wire, robust aggregators, the
    compute-layout pad/unpad) is untouched.

    Requires the model family to expose a ``dtype`` compute field
    (CifarResNet, CNNOriginalFedAvg/CNNDropOut, LogisticRegression);
    refuses loudly otherwise — silently training fp32 under a bf16 flag
    is exactly the drift the loud-refusal convention exists for."""
    fields = getattr(type(model), "__dataclass_fields__", {})
    if "dtype" not in fields:
        raise NotImplementedError(
            f"client_step_dtype: {type(model).__name__} has no compute-"
            "dtype field; supported families expose `dtype` "
            "(CifarResNet, CNNOriginalFedAvg, CNNDropOut, "
            "LogisticRegression)")
    return model.clone(dtype=dtype)


def im2col_layout(model, sample_x):
    """Conv lane shaping beyond s2d (docs/EXECUTION.md "MFU playbook"):
    a :class:`ComputeLayout` whose physical twin rephrases the 5x5 STEM
    conv as patch extraction + a 1x1 conv — the MXU contraction dim
    grows from Cin (1, or 4 under s2d) to k²·Cin (25/100), one dense
    GEMM instead of a thin-channel conv. Algebraically the same dot per
    output position (the kernel mapping is a pure transpose+reshape in
    ``conv_general_dilated_patches``'s (c, kh, kw) channel order, exact
    both ways); XLA may associate the 25-element reduction differently
    than the conv lowering, so the step carries the CNN family's
    documented ~1-ulp tolerance rather than the ResNet family's
    bit-exactness. Widths are NOT padded here — compose measurement-wise
    with ``compute_layout`` via the bench A/B, not structurally.

    Supported: ``CNNOriginalFedAvg`` (stem "conv" or "s2d"). Dropout
    models refuse for the usual mask-shape reason; other families have
    no 5x5 stem to rephrase."""
    from fedml_tpu.models.cnn import CNNOriginalFedAvg

    if not isinstance(model, CNNOriginalFedAvg):
        raise NotImplementedError(
            f"im2col_layout has no stem-rephrasing twin for "
            f"{type(model).__name__}; supported: CNNOriginalFedAvg")
    if model.im2col:
        raise ValueError("model is already an im2col physical twin")
    c1 = (model.widths or (32, 64))[0]
    cin = 4 if model.stem == "s2d" else 1
    k = 5

    def pad_stem(leaf):  # [5, 5, cin, c1] -> [1, 1, cin*25, c1]
        return jnp.transpose(leaf, (2, 0, 1, 3)).reshape(
            1, 1, cin * k * k, c1)

    def unpad_stem(leaf):
        return jnp.transpose(
            leaf.reshape(cin, k, k, c1), (1, 2, 0, 3))

    twin = model.clone(im2col=True)
    layout = ComputeLayout(
        logical_model=model, physical_model=twin,
        overrides={".params/Conv_0/kernel": (pad_stem, unpad_stem)})
    layout._build_specs(sample_x)
    return layout


def wrap_local_train(local_train, layout: ComputeLayout):
    """Wrap a PHYSICAL-model local trainer into the logical-shape
    contract: ``wrapped(net_logical, x, y, mask, rng) -> (net_logical',
    loss)``. Pad-on-entry, slice-on-exit — the only place physical
    shapes exist; everything above (aggregation, robust aggregators,
    carry protocol, checkpoints, the wire) keeps seeing logical
    shapes."""

    def wrapped(net, x, y, mask, rng):
        phys, loss = local_train(layout.pad(net), x, y, mask, rng)
        return layout.unpad(phys), loss

    return wrapped
