"""Expert parallelism: top-1 mixture-of-experts with all_to_all dispatch.

New TPU capability (nothing comparable exists in the reference, SURVEY.md
§2.10): E experts' MLPs live one-per-device on an ``ep`` mesh axis; tokens
are sharded over the same axis. Each device routes its tokens (top-1 +
softmax gate), packs them into per-expert capacity buffers, and a single
``lax.all_to_all`` ships every buffer to its expert's device — the
canonical MoE dispatch that rides ICI. Expert compute is one batched MLP;
a second all_to_all returns outputs, which are unpacked and gate-weighted.

Tokens over capacity are dropped (output 0 — standard Switch-style
behavior); with ``capacity >= tokens_per_device`` no token can drop and the
sharded result equals the dense oracle exactly (tested).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map


class MoEParams(NamedTuple):
    w_router: jax.Array  # [d, E]
    w_in: jax.Array      # [E, d, h]
    b_in: jax.Array      # [E, h]
    w_out: jax.Array     # [E, h, d]
    b_out: jax.Array     # [E, d]


def init_moe(rng, d: int, hidden: int, n_experts: int) -> MoEParams:
    k1, k2, k3 = jax.random.split(rng, 3)
    s_in = 1.0 / jnp.sqrt(d)
    s_out = 1.0 / jnp.sqrt(hidden)
    return MoEParams(
        w_router=jax.random.normal(k1, (d, n_experts)) * s_in,
        w_in=jax.random.normal(k2, (n_experts, d, hidden)) * s_in,
        b_in=jnp.zeros((n_experts, hidden)),
        w_out=jax.random.normal(k3, (n_experts, hidden, d)) * s_out,
        b_out=jnp.zeros((n_experts, d)),
    )


def _expert_mlp(x, w_in, b_in, w_out, b_out):
    return jax.nn.gelu(x @ w_in + b_in) @ w_out + b_out


def moe_reference(params: MoEParams, x: jnp.ndarray) -> jnp.ndarray:
    """Dense oracle: every expert runs on every token, outputs masked by the
    top-1 routing decision and weighted by the softmax gate. [N, d] → [N, d]."""
    logits = x @ params.w_router  # [N, E]
    idx = jnp.argmax(logits, axis=-1)
    gate = jnp.take_along_axis(jax.nn.softmax(logits, -1), idx[:, None], -1)[:, 0]
    all_out = jax.vmap(
        lambda w_in, b_in, w_out, b_out: _expert_mlp(x, w_in, b_in, w_out, b_out)
    )(params.w_in, params.b_in, params.w_out, params.b_out)  # [E, N, d]
    sel = jnp.take_along_axis(
        all_out, idx[None, :, None], axis=0)[0]  # [N, d]
    return sel * gate[:, None]


def make_moe_ep(mesh, axis: str = "ep", capacity: int | None = None):
    """``moe(params, x) -> y`` with tokens AND experts sharded over
    ``mesh[axis]``; one expert per device (E == mesh size). ``capacity`` =
    max tokens each (source device → expert) pair can carry per call;
    defaults to tokens_per_device (lossless)."""
    n_dev = int(mesh.shape[axis])

    def validated(params, x):
        e = params.w_in.shape[0]
        if e != n_dev:
            raise ValueError(
                f"MoE has {e} experts but the '{axis}' mesh axis has "
                f"{n_dev} devices; this layout runs one expert per device "
                f"(a mismatch would silently drop tokens routed to experts "
                f">= {n_dev})")
        return _moe(params, x)

    @partial(shard_map, mesh=mesh,
             in_specs=(
                 MoEParams(P(), P(axis), P(axis), P(axis), P(axis)),
                 P(axis),
             ),
             out_specs=P(axis), check_vma=False)
    def _moe(params, x):
        n_local, d = x.shape
        cap = capacity or n_local
        # Local routing over the FULL router (replicated) --------------
        logits = x @ params.w_router  # [n_local, E]
        idx = jnp.argmax(logits, axis=-1)
        gate = jnp.take_along_axis(
            jax.nn.softmax(logits, -1), idx[:, None], -1)[:, 0]
        # Pack per-expert capacity buffers -----------------------------
        onehot = jax.nn.one_hot(idx, n_dev, dtype=jnp.int32)  # [n, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1  # slot per token, -1 if other expert
        pos = jnp.max(pos, axis=1)  # [n]
        keep = pos < cap
        dispatch = (
            jax.nn.one_hot(idx, n_dev, dtype=x.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1,
                             dtype=x.dtype)[:, None, :]
        )[:, :, :cap]  # [n, E, cap] (overflow slot truncated)
        buf = jnp.einsum("nec,nd->ecd", dispatch, x)  # [E, cap, d]
        # Ship buffers to their expert's device ------------------------
        recv = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                  tiled=True)  # [n_dev*cap, d] for MY expert
        # Expert compute (device-local expert 0 of the sharded stack) --
        y = _expert_mlp(recv, params.w_in[0], params.b_in[0],
                        params.w_out[0], params.b_out[0])
        # Return outputs to the token owners ---------------------------
        back = jax.lax.all_to_all(
            y.reshape(n_dev, cap, d), axis, split_axis=0, concat_axis=0,
            tiled=True).reshape(n_dev, cap, d)  # [E, cap, d] from each expert
        out = jnp.einsum("nec,ecd->nd", dispatch, back)
        return out * (gate * keep.astype(x.dtype))[:, None]

    return validated
