"""Ring attention: exact attention over sequences sharded across a mesh
axis (sequence/context parallelism).

The reference has NO long-context machinery (SURVEY.md §2.10 — its largest
sequence is an 80-char LSTM window); this is the TPU-native capability axis
the task mandates. Design follows the blockwise/ring formulation (Liu &
Abbeel; Ring Attention with Blockwise Transformers): each device holds a
sequence shard of Q, K, V; K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while every device accumulates its Q-shard's
attention with a streaming (online) softmax — running max ``m``, normalizer
``l``, and unnormalized output ``o`` — so the result is bit-for-bit exact
attention, never materializing the full [T, T] score matrix.

Collectives ride the mesh axis (ICI when the axis maps to ICI), overlapping
the permute of block ``i+1`` with compute of block ``i`` is left to XLA's
latency-hiding scheduler.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map


def _block_attn(q, k, v, scale, mask):
    """One (Q-shard × KV-block) partial: returns scores-softmax pieces.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], mask: [Tq, Tk] bool (True=keep).
    Returns (m, l, o) block stats: m [B,H,Tq], l [B,H,Tq], o [B,Tq,H,D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # exp(-inf - -inf) guards: fully-masked rows get m=-inf; make exp 0.
    p = jnp.exp(s - jnp.where(jnp.isfinite(m), m, 0.0)[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m, l, o


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = False):
    """Body to run INSIDE shard_map: q/k/v are the local shards
    [B, T_local, H, D]; returns the local attention output shard.

    Streaming-softmax accumulation across ring steps; the K/V pair rotates
    ``n`` times so every Q shard sees every KV block exactly once.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    perm = [(j, (j + 1) % n) for j in range(n)]

    q_pos = my * t + jnp.arange(t)  # global positions of the local Q rows

    def accumulate(i, o, m, l, k_cur, v_cur):
        src = (my - i) % n  # whose KV block we hold at step i
        k_pos = src * t + jnp.arange(t)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((t, t), bool)
        bm, bl, bo = _block_attn(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m, bm)
        # Correction factors; exp(-inf - -inf)=nan guard via where.
        c_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        c_blk = jnp.where(jnp.isfinite(bm), jnp.exp(bm - m_new), 0.0)
        l = l * c_old + bl * c_blk
        o = (o * c_old.transpose(0, 2, 1)[..., None]
             + bo * c_blk.transpose(0, 2, 1)[..., None])
        return o, m_new, l

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = accumulate(i, o, m, l, k_cur, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((b, h, t), -jnp.inf, q.dtype)
    l0 = jnp.zeros((b, h, t), q.dtype)
    # n-1 rotating steps, then the final block WITHOUT the trailing
    # ppermute pair (its result would be discarded — dead ICI traffic).
    o, m, l, k_last, v_last = jax.lax.fori_loop(0, n - 1, step, (o0, m0, l0, k, v))
    o, m, l = accumulate(n - 1, o, m, l, k_last, v_last)
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return o / denom


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = False):
    """[B, T, H, D] full arrays → exact attention, sequence axis sharded
    over ``mesh[axis_name]``; output replicates the input sharding."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    def attn(q, k, v):
        return ring_attention_sharded(q, k, v, axis_name, causal=causal)

    return attn


def reference_attention(q, k, v, causal: bool = False):
    """Naive full-matrix attention (test oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
