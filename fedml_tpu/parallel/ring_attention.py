"""Ring attention: exact attention over sequences sharded across a mesh
axis (sequence/context parallelism).

The reference has NO long-context machinery (SURVEY.md §2.10 — its largest
sequence is an 80-char LSTM window); this is the TPU-native capability axis
the task mandates. Design follows the blockwise/ring formulation (Liu &
Abbeel; Ring Attention with Blockwise Transformers): each device holds a
sequence shard of Q, K, V; K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while every device accumulates its Q-shard's
attention with a streaming (online) softmax — running max ``m``, normalizer
``l``, and unnormalized output ``o`` — so the result is bit-for-bit exact
attention, never materializing the full [T, T] score matrix.

Collectives ride the mesh axis (ICI when the axis maps to ICI), overlapping
the permute of block ``i+1`` with compute of block ``i`` is left to XLA's
latency-hiding scheduler.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map


def _block_attn(q, k, v, scale, mask):
    """One (Q-shard × KV-block) partial: returns scores-softmax pieces.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], mask: [Tq, Tk] bool (True=keep).
    Returns (m, l, o) block stats: m [B,H,Tq], l [B,H,Tq], o [B,Tq,H,D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # exp(-inf - -inf) guards: fully-masked rows get m=-inf; make exp 0.
    p = jnp.exp(s - jnp.where(jnp.isfinite(m), m, 0.0)[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m, l, o


def ring_attention_sharded(q, k, v, axis_name: str, causal: bool = False):
    """Body to run INSIDE shard_map: q/k/v are the local shards
    [B, T_local, H, D]; returns the local attention output shard.

    Streaming-softmax accumulation across ring steps; the K/V pair rotates
    ``n`` times so every Q shard sees every KV block exactly once.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    perm = [(j, (j + 1) % n) for j in range(n)]

    q_pos = my * t + jnp.arange(t)  # global positions of the local Q rows

    def accumulate(i, o, m, l, k_cur, v_cur):
        src = (my - i) % n  # whose KV block we hold at step i
        k_pos = src * t + jnp.arange(t)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((t, t), bool)
        bm, bl, bo = _block_attn(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m, bm)
        # Correction factors; exp(-inf - -inf)=nan guard via where.
        c_old = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)
        c_blk = jnp.where(jnp.isfinite(bm), jnp.exp(bm - m_new), 0.0)
        l = l * c_old + bl * c_blk
        o = (o * c_old.transpose(0, 2, 1)[..., None]
             + bo * c_blk.transpose(0, 2, 1)[..., None])
        return o, m_new, l

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        o, m, l = accumulate(i, o, m, l, k_cur, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((b, h, t), -jnp.inf, q.dtype)
    l0 = jnp.zeros((b, h, t), q.dtype)
    # n-1 rotating steps, then the final block WITHOUT the trailing
    # ppermute pair (its result would be discarded — dead ICI traffic).
    o, m, l, k_last, v_last = jax.lax.fori_loop(0, n - 1, step, (o0, m0, l0, k, v))
    o, m, l = accumulate(n - 1, o, m, l, k_last, v_last)
    denom = jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
    return o / denom


# ---------------------------------------------------------------------------
# Ring attention with the pallas flash kernels as the per-shard computation
# (r3): each (Q-shard x KV-block) partial runs the fused MXU kernel instead
# of dense einsums; per-block (o, lse) pairs merge with log-sum-exp algebra.
# Backward is its OWN ring pass (Liu & Abbeel §3.2) reusing the block-level
# FlashAttention-2 kernels: the dk/dv accumulators rotate WITH their K/V
# blocks so every gradient lands home after n permutes, and dq accumulates
# locally — wired through jax.custom_vjp, so AD never needs to transpose a
# ppermute.
# ---------------------------------------------------------------------------


def _to3(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from3(x3, b, h):
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _ring_cases(my, src, causal):
    """0 = full block (src strictly before my), 1 = diagonal (causal
    within the block), 2 = skip (entirely above the causal diagonal)."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2)).astype(jnp.int32)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_attention_sharded(q, k, v, axis_name: str, causal: bool = False):
    """Flash-kernel ring attention body (run INSIDE shard_map): local
    shards [B, T_local, H, D] → local output shard. Exact attention —
    matches :func:`ring_attention_sharded` / dense to numerical
    precision, at flash-kernel speed and O(T_local) memory per step."""
    o3, _ = _ring_flash_fwd_core(q, k, v, axis_name, causal)
    return _from3(o3, q.shape[0], q.shape[2])


def _ring_flash_fwd_core(q, k, v, axis_name, causal):
    from fedml_tpu.ops.flash_attention import _SUB, NEG_INF, _auto_blk, _fwd

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    # Divisor-aligned blocks: the pallas grid is t//blk, so a non-divisor
    # block (e.g. T_local=384 with a clamped 256) would silently drop the
    # tail rows of the shard. _auto_blk mirrors flash_attention's guard.
    bq, bk = _auto_blk(t, 256), _auto_blk(t, 512)
    perm = [(j, (j + 1) % n) for j in range(n)]
    q3 = _to3(q)
    bh = b * h

    def block(kind, k3, v3):
        def full(_):
            return _fwd(q3, k3, v3, scale, False, bq, bk)

        def diag(_):
            return _fwd(q3, k3, v3, scale, True, bq, bk)

        def skip(_):
            return (jnp.zeros_like(q3),
                    jnp.full((bh, _SUB, t), NEG_INF, jnp.float32))

        return jax.lax.switch(kind, (full, diag, skip), None)

    def accumulate(i, o_acc, lse_acc, k_cur, v_cur):
        src = (my - i) % n
        o_b3, lse_b = block(_ring_cases(my, src, causal),
                            _to3(k_cur), _to3(v_cur))
        lse_b = lse_b[:, 0, :]  # [bh, t]
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        w_a = jnp.exp(lse_acc - lse_new)[..., None]
        w_b = jnp.exp(lse_b - lse_new)[..., None]
        # f32 rescale-and-add: with bf16 inputs the per-step rounding
        # would otherwise compound across ring steps (the backward's
        # accumulators are f32 for the same reason).
        return (o_acc * w_a + o_b3.astype(jnp.float32) * w_b), lse_new

    def step(i, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        o_acc, lse_acc = accumulate(i, o_acc, lse_acc, k_cur, v_cur)
        return (o_acc, lse_acc,
                jax.lax.ppermute(k_cur, axis_name, perm),
                jax.lax.ppermute(v_cur, axis_name, perm))

    o0 = jnp.zeros_like(q3, jnp.float32)
    lse0 = jnp.full((bh, t), NEG_INF, jnp.float32)
    # n-1 rotating steps + the final block without the dead trailing permute.
    o_acc, lse_acc, k_last, v_last = jax.lax.fori_loop(
        0, n - 1, step, (o0, lse0, k, v))
    o_acc, lse_acc = accumulate(n - 1, o_acc, lse_acc, k_last, v_last)
    return o_acc.astype(q.dtype), lse_acc


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal):
    o3, lse = _ring_flash_fwd_core(q, k, v, axis_name, causal)
    return (_from3(o3, q.shape[0], q.shape[2]),
            (q, k, v, o3, lse))


def _ring_flash_vjp_bwd(axis_name, causal, res, do):
    """Backward ring pass: (k, v, dk_acc, dv_acc) rotate together — after
    n permutes every dk/dv accumulator is back on its owner with every
    Q-shard's contribution; dq accumulates locally."""
    from fedml_tpu.ops.flash_attention import _SUB, _auto_blk, _bwd

    q, k, v, o3, lse = res
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    bq, bk = _auto_blk(t, 256), _auto_blk(t, 512)  # divisor-aligned (see fwd)
    perm = [(j, (j + 1) % n) for j in range(n)]
    q3, do3 = _to3(q), _to3(do)
    lse_sub = jnp.broadcast_to(lse[:, None, :], (lse.shape[0], _SUB,
                                                 lse.shape[1]))

    def block_bwd(kind, k3, v3):
        def run(causal_flag):
            return lambda _: _bwd(q3, k3, v3, o3, lse_sub, do3, scale,
                                  causal_flag, bq, bk)

        def skip(_):
            return (jnp.zeros_like(q3), jnp.zeros_like(k3),
                    jnp.zeros_like(v3))

        return jax.lax.switch(kind, (run(False), run(True), skip), None)

    def step(i, carry):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - i) % n
        dq_c, dk_c, dv_c = block_bwd(_ring_cases(my, src, causal),
                                     _to3(k_cur), _to3(v_cur))
        dq_acc = dq_acc + dq_c.astype(dq_acc.dtype)
        dk_cur = dk_cur + _from3(dk_c, b, h).astype(dk_cur.dtype)
        dv_cur = dv_cur + _from3(dv_c, b, h).astype(dv_cur.dtype)
        return (dq_acc,
                jax.lax.ppermute(k_cur, axis_name, perm),
                jax.lax.ppermute(v_cur, axis_name, perm),
                jax.lax.ppermute(dk_cur, axis_name, perm),
                jax.lax.ppermute(dv_cur, axis_name, perm))

    dq0 = jnp.zeros_like(q3, jnp.float32)
    carry = (dq0, k, v, jnp.zeros_like(k, jnp.float32),
             jnp.zeros_like(v, jnp.float32))
    # Full n steps each ending in a permute: the dk/dv accumulators make a
    # complete loop and land back on their owners.
    dq_acc, _, _, dk_home, dv_home = jax.lax.fori_loop(0, n, step, carry)
    return (_from3(dq_acc, b, h).astype(q.dtype),
            dk_home.astype(k.dtype), dv_home.astype(v.dtype))


ring_flash_attention_sharded.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def make_ring_flash_attention(mesh, axis_name: str = "sp",
                              causal: bool = False):
    """[B, T, H, D] full arrays → exact attention with the pallas flash
    kernels per shard; sequence axis sharded over ``mesh[axis_name]``.
    Drop-in for :func:`make_ring_attention` (same pluggable attn_fn
    contract), differentiable."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    def attn(q, k, v):
        return ring_flash_attention_sharded(q, k, v, axis_name,
                                            causal=causal)

    return attn


def make_ring_attention(mesh, axis_name: str = "sp", causal: bool = False):
    """[B, T, H, D] full arrays → exact attention, sequence axis sharded
    over ``mesh[axis_name]``; output replicates the input sharding."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )
    def attn(q, k, v):
        return ring_attention_sharded(q, k, v, axis_name, causal=causal)

    return attn


def reference_attention(q, k, v, causal: bool = False):
    """Naive full-matrix attention (test oracle)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
