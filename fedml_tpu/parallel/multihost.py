"""Multi-host (pod-scale) scaffolding.

The reference scales across machines with mpirun + hostfiles
(run_fedavg_distributed_pytorch.sh:19-21); the TPU-native equivalent is
``jax.distributed`` + a hybrid DCN×ICI device mesh: the outer mesh axis
maps to hosts (collectives cross DCN), inner axes ride ICI within each
host's chips. ``hybrid_mesh`` uses
``mesh_utils.create_hybrid_device_mesh`` so collective-heavy axes (clients,
tp) stay on ICI and only the host-level aggregation crosses DCN.

Single-host processes (this environment) run unchanged: ``initialize`` is a
no-op when no coordinator is configured, and ``hybrid_mesh`` falls back to
a flat mesh.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the multi-host runtime. Arguments fall back to the standard env
    vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, the
    TPU-pod equivalents of the reference's mpi_host_file). Returns True if
    distributed mode was initialized, False for single-process runs."""
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return False
    kw = {"coordinator_address": addr}
    # Only pass what is explicitly configured — unset values stay None so
    # jax.distributed can auto-detect the pod topology (forcing 1/0 here
    # would make every host start its own single-process "cluster").
    n = num_processes if num_processes is not None else os.environ.get("JAX_NUM_PROCESSES")
    pid = process_id if process_id is not None else os.environ.get("JAX_PROCESS_ID")
    if n is not None:
        kw["num_processes"] = int(n)
    if pid is not None:
        kw["process_id"] = int(pid)
    jax.distributed.initialize(**kw)
    return True


def hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int] = (),
                axis_names: Tuple[str, ...] = ("clients",)) -> Mesh:
    """Hybrid DCN×ICI mesh following the jax ``create_hybrid_device_mesh``
    contract: ``ici_shape`` and ``dcn_shape`` have the SAME rank (one entry
    per mesh axis) and axis ``i``'s global size is ``ici[i] * dcn[i]``. Put
    the DCN factor on the axis whose collective tolerates DCN latency (for
    FL, the client axis: ``hybrid_mesh((chips_per_host, k), (n_hosts, 1),
    ("clients", "model"))``) and keep ``1`` everywhere else so those
    collectives stay on ICI. Empty/all-ones ``dcn_shape`` → plain
    single-host mesh over the local devices."""
    if dcn_shape and int(np.prod(dcn_shape)) > 1:
        if len(dcn_shape) != len(ici_shape):
            raise ValueError(
                f"dcn_shape rank {len(dcn_shape)} must equal ici_shape rank "
                f"{len(ici_shape)} (per-axis factors; use 1 for ICI-only axes)")
        if len(axis_names) != len(ici_shape):
            raise ValueError("axis_names must have one name per mesh axis")
        try:
            devices = mesh_utils.create_hybrid_device_mesh(
                tuple(ici_shape), tuple(dcn_shape))
        except ValueError:
            # Non-TPU devices (CPU multi-process testing) have no usable
            # slice topology; there the process boundary IS the DCN
            # boundary, so fall back to one-granule-per-process. On real
            # TPU the error propagates — retrying a mismatched ici/dcn
            # shape with process granules could silently build a mesh
            # whose DCN axis cuts across ICI-connected hosts.
            if jax.devices()[0].platform == "tpu":
                raise
            devices = mesh_utils.create_hybrid_device_mesh(
                tuple(ici_shape), tuple(dcn_shape), process_is_granule=True)
        return Mesh(devices, axis_names)
    n = int(np.prod(ici_shape))
    devices = mesh_utils.create_device_mesh(
        tuple(ici_shape), devices=jax.devices()[:n])
    return Mesh(devices, axis_names)


def dcn_client_mesh(n_hosts: int, per_host: int,
                    axis: str = "clients") -> Mesh:
    """The pod-scale CLIENT mesh: a ``("hosts", axis)`` DCN×ICI mesh
    whose ``"hosts"`` axis (the :data:`fedml_tpu.parallel.shard.DCN_AXIS`
    convention) is the inter-host DCN dimension and whose client axis
    rides ICI within each host. Round builders that see this mesh pin
    client groups per host: stage-1 aggregation runs as an ICI-axis-only
    collective and only G = ``n_hosts`` group partials cross DCN
    (``make_sharded_round``'s hierarchical reduction, docs/PLATFORMS.md
    "Multi-host").

    Under ``jax.distributed`` this is ``hybrid_mesh`` with the DCN
    factor on the hosts axis; in a SINGLE process it degrades to
    :func:`simulated_dcn_mesh` — the forced factorization the tests and
    the ci smoke drive, where the "hosts" boundary is simulated but the
    reduction runs the exact pod program."""
    if jax.process_count() > 1:
        return hybrid_mesh((1, per_host), (n_hosts, 1), ("hosts", axis))
    return simulated_dcn_mesh(n_hosts, per_host, axis)


def simulated_dcn_mesh(n_hosts: int, per_host: int,
                       axis: str = "clients") -> Mesh:
    """Single-process FORCED DCN×ICI factorization: ``n_hosts × per_host``
    local devices reshaped into a ``("hosts", axis)`` mesh. No process
    boundary exists — the point is that the compiled reduction is the
    pod-shaped program (ICI-axis stage 1, G-partial stage 2), so its
    semantics (bit-equality, group statistics, refusals) are testable on
    one box."""
    n = n_hosts * per_host
    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"simulated_dcn_mesh({n_hosts}x{per_host}) needs {n} devices, "
            f"have {len(devices)}")
    return Mesh(np.array(devices[:n]).reshape(n_hosts, per_host),
                ("hosts", axis))


def process_local_client_slice(n_clients: int) -> slice:
    """Which contiguous client range this host owns when client data is
    loaded per-host (each host loads only its shard — unlike the reference,
    where every rank loads the full dataset, main_fedavg.py:133)."""
    pid, n = jax.process_index(), jax.process_count()
    per = n_clients // n
    extra = n_clients % n
    start = pid * per + min(pid, extra)
    return slice(start, start + per + (1 if pid < extra else 0))
