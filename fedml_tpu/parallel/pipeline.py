"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp`` axis.

New TPU capability (absent from the reference, SURVEY.md §2.10 — though its
SplitNN is conceptually a 2-stage pipeline across processes;
split_nn/client_manager.py:35-65): each device on the ``pp`` mesh axis holds
ONE stage's parameters; microbatches flow device-to-device via
``lax.ppermute``. With S stages and M microbatches the schedule runs
S+M−1 ticks; at tick t, stage s processes microbatch t−s (bubble fraction
(S−1)/(S+M−1), the GPipe bound). The last stage accumulates its outputs,
replicated to every device with one ``psum`` — results are bit-equal to
applying the stages sequentially (tested).

Differentiable end-to-end (ppermute has a transpose rule), so pipeline
training works by wrapping the whole thing in ``jax.grad``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map


def make_pipeline(stage_fn, mesh, axis: str = "pp"):
    """``pipe(stage_params, x) -> y``.

    ``stage_params``: pytree with a leading stage axis [S, ...], sharded
    over ``mesh[axis]`` (one stage per device). ``stage_fn(params, x)`` maps
    one microbatch through one stage; every stage must preserve the
    microbatch shape (equal widths — pad stages if not). ``x``: [M, B, d]
    microbatches, replicated; returns [M, B, d], replicated.
    """

    n_stages = int(mesh.shape[axis])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def validated(stage_params, x):
        s = jax.tree.leaves(stage_params)[0].shape[0]
        if s != n_stages:
            raise ValueError(
                f"stage_params has {s} stages but the '{axis}' mesh axis has "
                f"{n_stages} devices; this schedule runs one stage per "
                "device (a mismatch would silently drop stages)")
        return _pipe(stage_params, x)

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
             check_vma=False)
    def _pipe(stage_params, x):
        params_local = jax.tree.map(lambda a: a[0], stage_params)
        s = jax.lax.axis_index(axis)
        m, b, d = x.shape

        def tick(t, carry):
            prev_out, acc = carry
            # Receive the upstream stage's last output.
            recv = jax.lax.ppermute(prev_out, axis, perm)
            mb = t - s
            active = (mb >= 0) & (mb < m)
            x_in = jnp.where(s == 0, x[jnp.clip(t, 0, m - 1)], recv)
            out = stage_fn(params_local, x_in)
            out = jnp.where(active, out, jnp.zeros_like(out))
            is_last = s == n_stages - 1
            acc = acc.at[jnp.clip(mb, 0, m - 1)].add(
                jnp.where(active & is_last, out, jnp.zeros_like(out)))
            return out, acc

        out0 = jnp.zeros((b, d), x.dtype)
        acc0 = jnp.zeros_like(x)
        _, acc = jax.lax.fori_loop(0, n_stages + m - 1, tick, (out0, acc0))
        # Only the last stage wrote anything; replicate its buffer.
        return jax.lax.psum(acc, axis)

    return validated


def stack_stage_params(per_stage_params):
    """[pytree, pytree, ...] (equal structures) → pytree with leading stage
    axis, ready for :func:`make_pipeline`."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def sequential_reference(stage_fn, per_stage_params, x):
    """Oracle: run the stages one after another on all microbatches."""

    def apply_all(xmb):
        for p in per_stage_params:
            xmb = stage_fn(p, xmb)
        return xmb

    return jax.vmap(apply_all)(x)
