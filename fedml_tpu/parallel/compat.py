"""jax API-version compatibility shims for the parallel machinery.

The codebase targets the current jax spelling — top-level
``jax.shard_map`` with the ``check_vma`` replication-check kwarg. Older
jaxlibs (< 0.6, e.g. the 0.4.x baked into some containers) keep
shard_map in ``jax.experimental.shard_map`` and call the same kwarg
``check_rep``. This wrapper keeps every call site on the new spelling
and translates once, here, instead of try/excepting in six modules.
"""

from __future__ import annotations

try:  # current jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _LEGACY = False
except ImportError:  # jax < 0.6: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY = True


def shard_map(f, **kwargs):
    """``jax.shard_map`` with the modern keyword surface on any
    supported jax. Call-site pattern is always keyword-only after ``f``
    (``partial(shard_map, mesh=..., in_specs=..., out_specs=...,
    check_vma=False)``), which both generations accept."""
    if _LEGACY and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)


def pallas_tpu_compiler_params():
    """The pallas-TPU CompilerParams class under its current name —
    jax < 0.6 spells it ``TPUCompilerParams`` (same fields). Imported by
    the pallas kernels (ops/flash_attention.py, ops/group_norm.py) so
    the next rename is a one-place fix."""
    from jax.experimental.pallas import tpu as pltpu

    return getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
