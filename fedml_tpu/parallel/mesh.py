"""Device-mesh helpers.

The FL simulator's primary parallel axis is ``clients`` — the TPU-native
replacement for the reference's one-OS-process-per-client MPI layout
(SURVEY.md §2.9). A second optional ``model`` axis is reserved for
tensor-parallel large-model federation (splitnn/gkt-scale models).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def client_mesh(num_devices: Optional[int] = None, axis_name: str = "clients") -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    mesh_devices = mesh_utils.create_device_mesh((n,), devices=devices[:n])
    return Mesh(mesh_devices, (axis_name,))


def mesh_2d(client_parallel: int, model_parallel: int,
            axis_names: Sequence[str] = ("clients", "model")) -> Mesh:
    devices = mesh_utils.create_device_mesh((client_parallel, model_parallel))
    return Mesh(devices, tuple(axis_names))
