from fedml_tpu.parallel.layout import (
    ComputeLayout,
    LayoutPolicy,
    compute_layout,
    wrap_local_train,
)
from fedml_tpu.parallel.mesh import client_mesh, mesh_2d
from fedml_tpu.parallel.shard import (
    make_fused_round_step,
    make_fused_stateful_round_step,
    make_sharded_round,
    make_step_window_scan,
    make_vmap_round,
)
from fedml_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
)
from fedml_tpu.parallel.tensor_parallel import make_tp_forward, shard_tp_params
from fedml_tpu.parallel.pipeline import (
    make_pipeline,
    sequential_reference,
    stack_stage_params,
)
from fedml_tpu.parallel.multihost import hybrid_mesh, initialize, process_local_client_slice
from fedml_tpu.parallel.expert_parallel import (
    init_moe,
    make_moe_ep,
    moe_reference,
)

__all__ = [
    "ComputeLayout",
    "LayoutPolicy",
    "compute_layout",
    "wrap_local_train",
    "client_mesh",
    "mesh_2d",
    "make_fused_round_step",
    "make_fused_stateful_round_step",
    "make_sharded_round",
    "make_step_window_scan",
    "make_vmap_round",
    "make_ring_attention",
    "reference_attention",
    "make_tp_forward",
    "shard_tp_params",
    "init_moe",
    "make_moe_ep",
    "moe_reference",
    "make_pipeline",
    "sequential_reference",
    "stack_stage_params",
    "hybrid_mesh",
    "initialize",
    "process_local_client_slice",
]
