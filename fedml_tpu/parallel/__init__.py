from fedml_tpu.parallel.mesh import client_mesh
from fedml_tpu.parallel.shard import make_sharded_round, make_vmap_round

__all__ = ["client_mesh", "make_sharded_round", "make_vmap_round"]
