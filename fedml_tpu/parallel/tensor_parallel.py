"""Tensor parallelism for the transformer (Megatron-style, shard_map).

New TPU capability (the reference's models are small CNNs/LSTMs — no TP
exists there, SURVEY.md §2.10): the transformer block's two big matmul
pairs are sharded over a ``tp`` mesh axis —

- MLP: W_in column-sharded → per-device hidden shard → W_out row-sharded →
  ``psum`` (one collective per MLP);
- Attention: heads split across devices (QKV column-sharded, output proj
  row-sharded → ``psum``).

Implemented as a functional transform over a ``TransformerLM``'s params:
``shard_tp_params`` splits the replicated parameter pytree into per-device
shards, and ``make_tp_forward`` runs the block-parallel forward inside
``shard_map`` — activations replicated, parameters device-local, exactly
matching the unsharded model's math (tested to 1e-5).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map


def _split(arr, n, axis):
    return jnp.stack(jnp.split(arr, n, axis=axis))


def _split_qkv(kernel, n):
    """Fused QKV kernel [d, 3d]: device i must get (Q_i, K_i, V_i) — its
    heads' columns from EACH of the three projections, not a contiguous
    3d/n column chunk (which would hand device 0 a slice of Q only)."""
    q, k, v = jnp.split(kernel, 3, axis=1)
    w = q.shape[1] // n
    return jnp.stack([
        jnp.concatenate([p[:, i * w:(i + 1) * w] for p in (q, k, v)], axis=1)
        for i in range(n)
    ])


def shard_tp_params(params: Dict[str, Any], n_dev: int) -> Dict[str, Any]:
    """Split a TransformerLM param tree for tp: per-layer QKV/W_in sharded on
    the OUTPUT dim, out-proj/W_out on the INPUT dim; everything else
    replicated (stacked n_dev times on a new leading axis so the whole tree
    has a uniform [n_dev, ...] layout for shard_map)."""

    def visit(path, leaf):
        keys = [getattr(k, "key", str(k)) for k in path]
        name = "/".join(keys)
        if "Dense_0" in name and "MHA_" in name and keys[-1] == "kernel":
            return _split_qkv(leaf, n_dev)  # QKV fused: per-head column shard
        if "Dense_1" in name and "MHA_" in name and keys[-1] == "kernel":
            return _split(leaf, n_dev, axis=0)  # out proj: row shard
        if "Dense_0" in name and "Block_" in name and "MHA_" not in name and keys[-1] == "kernel":
            return _split(leaf, n_dev, axis=1)  # MLP in: column shard
        if "Dense_0" in name and "Block_" in name and "MHA_" not in name and keys[-1] == "bias":
            return _split(leaf, n_dev, axis=0)
        if "Dense_1" in name and "Block_" in name and "MHA_" not in name and keys[-1] == "kernel":
            return _split(leaf, n_dev, axis=0)  # MLP out: row shard
        return jnp.broadcast_to(leaf[None], (n_dev,) + leaf.shape)

    return jax.tree_util.tree_map_with_path(visit, params)


def make_tp_forward(model, mesh, axis: str = "tp"):
    """``fwd(sharded_params, tokens) -> logits`` running the TP math inside
    shard_map. ``model`` is a TransformerLM (used for static shape config:
    layers, heads, dims). The tp size must divide the head count."""
    n_dev = int(mesh.shape[axis])
    if model.n_heads % n_dev:
        raise ValueError(
            f"tp={n_dev} must divide n_heads={model.n_heads} "
            "(attention heads are split across the tp axis)")
    d_model = model.d_model
    n_layers = model.n_layers
    heads_local = model.n_heads // n_dev
    d_head = d_model // model.n_heads
    causal = model.causal

    def block(x, p, prefix):
        # --- attention (heads sharded) ---------------------------------
        h = _layernorm(x, p[f"{prefix}/LayerNorm_0"])
        qkv = h @ p[f"{prefix}/MHA_0/Dense_0"]["kernel"]  # [B,T,3*dm/n]
        b, t, _ = qkv.shape
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, t, heads_local, d_head)
        from fedml_tpu.parallel.ring_attention import reference_attention

        o = reference_attention(q.reshape(shp), k.reshape(shp), v.reshape(shp),
                                causal=causal)
        o = o.reshape(b, t, heads_local * d_head)
        attn = jax.lax.psum(o @ p[f"{prefix}/MHA_0/Dense_1"]["kernel"], axis)
        x = x + attn
        # --- MLP (hidden sharded) --------------------------------------
        h = _layernorm(x, p[f"{prefix}/LayerNorm_1"])
        mid = jax.nn.gelu(h @ p[f"{prefix}/Dense_0"]["kernel"]
                          + p[f"{prefix}/Dense_0"]["bias"])
        out = jax.lax.psum(mid @ p[f"{prefix}/Dense_1"]["kernel"], axis)
        # W_out bias is replicated — add once (outside the psum).
        out = out + p[f"{prefix}/Dense_1"]["bias"]
        return x + out

    def _layernorm(x, p):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]

    def flat(params):
        """dict keyed by 'a/b/c' path → leaf (built per call; cheap)."""
        out = {}

        def visit(path, leaf):
            keys = [getattr(kk, "key", str(kk)) for kk in path]
            out["/".join(keys[:-1])] = out.get("/".join(keys[:-1]), {})
            out["/".join(keys[:-1])][keys[-1]] = leaf

        jax.tree_util.tree_map_with_path(visit, params)
        return out

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
             check_vma=False)
    def fwd(sharded_params, tokens):
        p = flat(jax.tree.map(lambda a: a[0], sharded_params))
        x = p["Embed_0"]["embedding"][tokens]
        pos = p["Embed_1"]["embedding"][: tokens.shape[1]]
        x = x + pos[None]
        for i in range(n_layers):
            x = block(x, p, f"Block_{i}")
        x = _layernorm(x, p["LayerNorm_0"])
        return x @ p["Dense_0"]["kernel"]

    return fwd
