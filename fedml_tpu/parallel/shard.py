"""Client-parallel FedAvg rounds.

``make_vmap_round``: all sampled clients train on one chip (vmap over the
client axis) — the single-device standalone simulator.

``make_sharded_round``: clients sharded over a mesh axis with ``shard_map``;
the server weighted average becomes per-shard partial weighted sums reduced
with ``lax.psum`` over ICI. This *is* the aggregation the reference performs
by MPI-sending pickled state_dicts to rank 0 and looping over keys
(FedAVGAggregator.py:59-88) — here it is one XLA collective.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from fedml_tpu.core.tree import tree_weighted_mean


def make_vmap_round(local_train, client_transform=None):
    """``round_fn(params, x, y, mask, weights, loss_weights, rng) ->
    (avg_params, mean_loss)`` with client-stacked inputs ``[C, S, B, ...]``.

    ``weights [C]`` weight the model average; ``loss_weights [C]`` weight the
    reported train loss (true sample counts — algorithms like FedNova
    aggregate with n_i/τ_i weights but still report sample-weighted loss).
    Padded client slots carry weight 0 in both.

    ``client_transform(global_net, client_net) -> client_net`` is applied to
    every trained client model before averaging (robust clipping etc.).
    """

    def round_fn(params, x, y, mask, weights, loss_weights, rng):
        rngs = client_rngs(rng, x.shape[0], 0)
        client_params, losses = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(params, x, y, mask, rngs)
        if client_transform is not None:
            client_params = jax.vmap(client_transform, in_axes=(None, 0))(
                params, client_params
            )
        avg = tree_weighted_mean(client_params, weights)
        lw = loss_weights / jnp.maximum(jnp.sum(loss_weights), 1e-12)
        return avg, jnp.sum(losses * lw)

    return round_fn


def client_rngs(rng, n_local, offset):
    """Per-client rng streams keyed by GLOBAL client slot, so the vmap and
    shard_map paths produce bitwise-identical randomness (shuffle order,
    dropout) for the same sampled round."""
    return jax.vmap(lambda i: jax.random.fold_in(rng, i))(offset + jnp.arange(n_local))


def make_sharded_round(local_train, mesh, axis: str = "clients", client_transform=None):
    """Sharded round: client axis split over ``mesh[axis]``; output replicated.

    Weighted average = psum of per-shard weighted partial sums / psum of
    weights — exact regardless of how clients land on shards.
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def round_fn(params, x, y, mask, weights, loss_weights, rng):
        # Same global-slot-keyed streams as the vmap path.
        shard_idx = jax.lax.axis_index(axis)
        rngs = client_rngs(rng, x.shape[0], shard_idx * x.shape[0])
        client_params, losses = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(params, x, y, mask, rngs)
        if client_transform is not None:
            client_params = jax.vmap(client_transform, in_axes=(None, 0))(
                params, client_params
            )
        w = weights.astype(jnp.float32)
        total = jax.lax.psum(jnp.sum(w), axis)
        wn = w / jnp.maximum(total, 1e-12)
        avg = jax.tree.map(
            lambda p: jax.lax.psum(
                jnp.einsum("c,c...->...", wn, p.astype(jnp.float32)), axis
            ).astype(p.dtype),
            client_params,
        )
        lw = loss_weights.astype(jnp.float32)
        lw = lw / jnp.maximum(jax.lax.psum(jnp.sum(lw), axis), 1e-12)
        loss = jax.lax.psum(jnp.sum(losses * lw), axis)
        return avg, loss

    return round_fn
