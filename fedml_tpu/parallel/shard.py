"""Client-parallel FedAvg rounds.

``make_vmap_round``: all sampled clients train on one chip (vmap over the
client axis) — the single-device standalone simulator.

``make_sharded_round``: clients sharded over a mesh axis with ``shard_map``;
the server weighted average becomes per-shard partial weighted sums reduced
with ``lax.psum`` over ICI. This *is* the aggregation the reference performs
by MPI-sending pickled state_dicts to rank 0 and looping over keys
(FedAVGAggregator.py:59-88) — here it is one XLA collective.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from fedml_tpu.parallel.compat import shard_map

from fedml_tpu.core.tree import tree_weighted_mean

#: Mesh-axis naming convention for the pod-scale compute plane
#: (parallel/multihost.py builds these meshes): a mesh whose FIRST axis
#: is named ``"hosts"`` carries a DCN×ICI factorization — that axis is
#: the slow inter-host (DCN) dimension and the client axis that follows
#: is intra-host ICI. The client dimension of every round operand is
#: then sharded over BOTH axes (hosts-major, so global client-slot order
#: is host order), and the reductions below keep their collectives on
#: the ICI axis wherever the math allows, crossing DCN only with
#: host-level partials (arXiv:1903.05133's sparse global reduction).
DCN_AXIS = "hosts"


def mesh_dcn_axis(mesh):
    """The mesh's DCN (inter-host) axis name, or ``None`` for a flat
    single-host mesh."""
    if mesh is not None and DCN_AXIS in mesh.axis_names:
        return DCN_AXIS
    return None


def client_axis(mesh):
    """The ICI client axis — the axis round builders vmap/shard clients
    over. On a flat mesh this is ``axis_names[0]`` (the historical
    contract); on a DCN×ICI mesh it is the first non-DCN axis."""
    for a in mesh.axis_names:
        if a != DCN_AXIS:
            return a
    raise ValueError(f"mesh {mesh.axis_names} has no client axis")


def client_axes(mesh, axis=None):
    """The mesh axes the CLIENT dimension is sharded over, DCN-major —
    ``("hosts", axis)`` on a hierarchical mesh, ``(axis,)`` otherwise.
    ``P(client_axes(mesh))`` is the partition spec of every
    client-stacked round operand."""
    if axis is None:
        axis = client_axis(mesh)
    d = mesh_dcn_axis(mesh)
    return (d, axis) if d else (axis,)


def client_shards(mesh, axis=None) -> int:
    """Total client shards = the product over the client axes (what the
    sampled cohort is padded to a multiple of)."""
    if mesh is None:
        return 1
    return int(np.prod([mesh.shape[a] for a in client_axes(mesh, axis)]))


def _psum_hier(v, axes):
    """``psum`` over the client axes, ICI first: on a flat mesh this is
    exactly the historical single-axis ``psum`` (bit-compatible with
    every existing pin); on a DCN×ICI mesh the ICI reduction completes
    HOST-LOCALLY and only the per-host partial crosses the DCN axis —
    the mean path's hierarchical reduction IS this association (one
    O(model) host partial per host on DCN instead of a flat all-reduce
    over every shard)."""
    for a in reversed(axes):
        v = jax.lax.psum(v, a)
    return v


def client_finite_mask(client_params) -> jnp.ndarray:
    """[C] float mask: 1.0 where EVERY leaf of that client's model is
    finite. Failure containment the reference lacks entirely (its only
    response to trouble is MPI Abort, fedml_api/utils/context.py:9-18): a
    client whose local training diverged to NaN/Inf must not poison the
    global average."""
    flags = [
        jnp.all(jnp.isfinite(leaf.reshape(leaf.shape[0], -1)), axis=1)
        for leaf in jax.tree.leaves(client_params)
    ]
    return jnp.all(jnp.stack(flags, axis=0), axis=0).astype(jnp.float32)


def run_clients_guarded(local_train, client_transform, nan_guard,
                        net, x, y, mask, rngs, corruptor=None, adv=None):
    """Shared per-round client-training prelude: vmapped local training,
    optional ADVERSARIAL corruption, optional post-transform (robust
    clipping etc.), and the NaN-guard zeroing. Returns ``(client_nets,
    losses, finite)`` where ``finite [C]`` is 1.0 for clients whose
    trained model is wholly finite (all-ones when the guard is off) —
    callers fold it into their aggregation weights. Used by the vmap
    round, the sharded round, and q-FedAvg's fair round so the guard
    semantics can never drift between them.

    ``client_transform`` is ``(global_net, client_net) -> client_net``,
    or — when the builder marked it ``transform.wants_rng = True`` —
    ``(global_net, client_net, rng) -> client_net`` for randomized
    transforms (stochastic quantization): the 3-arg form receives a
    per-client stream forked from the round's client rngs (fold_in with
    a transform-reserved constant, so it never collides with the streams
    local training consumed for shuffling/dropout/DP noise). An explicit
    attribute, not signature sniffing: partials and C-implemented
    callables would defeat ``inspect`` silently.

    ``corruptor`` is the device-side attack model for robustness drills
    (``core.faults.UpdateCorruptor.device_fn()``): a pure
    ``(global_net, client_nets, adv, rngs) -> client_nets`` applied to
    the trained stack where ``adv [C] > 0`` flags the adversary slots.
    It runs BEFORE the transform and the guard — exactly the real threat
    order: the server's defenses see the already-corrupted updates. Its
    per-client streams are forked with their own reserved constant
    (0xC0), disjoint from training's and the transform's (0x7F)."""
    client_nets, losses = jax.vmap(
        local_train, in_axes=(None, 0, 0, 0, 0)
    )(net, x, y, mask, rngs)
    if corruptor is not None:
        crngs = jax.vmap(lambda r: jax.random.fold_in(r, 0xC0))(rngs)
        client_nets = corruptor(net, client_nets, adv, crngs)
    if client_transform is not None:
        if getattr(client_transform, "wants_rng", False):
            trngs = jax.vmap(
                lambda r: jax.random.fold_in(r, 0x7F))(rngs)
            client_nets = jax.vmap(client_transform, in_axes=(None, 0, 0))(
                net, client_nets, trngs)
        else:
            client_nets = jax.vmap(client_transform, in_axes=(None, 0))(
                net, client_nets)
    if not nan_guard:
        return client_nets, losses, jnp.ones_like(losses)
    finite = client_finite_mask(client_nets)
    # Zero via where — NaN * 0 is still NaN.
    client_nets = jax.tree.map(
        lambda p: jnp.where(
            finite.reshape((-1,) + (1,) * (p.ndim - 1)).astype(bool),
            p, jnp.zeros((), p.dtype)),
        client_nets)
    losses = jnp.where(jnp.isfinite(losses), losses, 0.0)
    return client_nets, losses, finite


def _is_mean(aggregator) -> bool:
    return aggregator is None or getattr(aggregator, "is_mean", False)


def _robust_avg(aggregator, client_params, weights, params):
    """Aggregate with a non-mean Aggregator (core/robust_agg protocol)
    and keep the PREVIOUS global model when no client carries weight:
    order statistics over an empty participant set are meaningless — the
    aggregators' ±inf exclusion sentinels would leak into the model (the
    mean path's equivalent guard is the nan_guard ``any_ok`` select)."""
    avg = aggregator(client_params, weights)
    any_ok = jnp.sum(jnp.where(weights > 0, 1.0, 0.0)) > 0
    return jax.tree.map(lambda a, p: jnp.where(any_ok, a, p), avg, params)


def make_vmap_round(local_train, client_transform=None, nan_guard: bool = False,
                    with_client_losses: bool = False, aggregator=None,
                    corruptor=None):
    """``round_fn(params, x, y, mask, weights, loss_weights, rng) ->
    (avg_params, mean_loss)`` with client-stacked inputs ``[C, S, B, ...]``.

    ``weights [C]`` weight the model average; ``loss_weights [C]`` weight the
    reported train loss (true sample counts — algorithms like FedNova
    aggregate with n_i/τ_i weights but still report sample-weighted loss).
    Padded client slots carry weight 0 in both.

    ``client_transform(global_net, client_net) -> client_net`` is applied to
    every trained client model before averaging (robust clipping etc.).

    ``nan_guard`` zero-weights any client whose trained model contains a
    non-finite value (and its loss), so one diverged client cannot poison
    the round.

    ``with_client_losses`` appends the per-client training losses ``[C]``
    as a THIRD output — the in-round observable Oort's utility needs
    (Lai et al. §5), captured for free instead of a post-round eval pass.

    ``aggregator`` swaps the server reduction for a Byzantine-robust one
    (``core.robust_agg`` protocol — coord_median, trimmed_mean, krum,
    geometric_median). ``None`` or an ``is_mean`` aggregator keeps the
    existing weighted-mean path UNCHANGED (bit-equal). Under ``nan_guard``
    a diverged client's zeroed weight EXCLUDES it from the robust
    aggregator's order statistics (core/robust_agg weight semantics).

    ``corruptor`` enables the device-side attack drill: the round grows a
    trailing ``adv [C]`` operand (adversary mask) and the corruptor runs
    on the trained stack before the transform/guard — see
    :func:`run_clients_guarded`. The mask-driven form means the drill
    rides every tier, including the windowed ``lax.scan`` body."""
    if _is_mean(aggregator):
        aggregator = None

    def round_core(params, x, y, mask, weights, loss_weights, rng, adv):
        rngs = client_rngs(rng, x.shape[0], 0)
        client_params, losses, finite = run_clients_guarded(
            local_train, client_transform, nan_guard,
            params, x, y, mask, rngs, corruptor=corruptor, adv=adv)
        weights = weights * finite
        loss_weights = loss_weights * finite
        if aggregator is None:
            avg = tree_weighted_mean(client_params, weights)
            if nan_guard:
                # Every sampled client diverged → keep the previous global
                # model (a zero-total weighted mean would silently zero the
                # params).
                any_ok = jnp.sum(weights) > 0
                avg = jax.tree.map(
                    lambda a, p: jnp.where(any_ok, a, p), avg, params)
        else:
            avg = _robust_avg(aggregator, client_params, weights, params)
        lw = loss_weights / jnp.maximum(jnp.sum(loss_weights), 1e-12)
        mean_loss = jnp.sum(losses * lw)
        if with_client_losses:
            return avg, mean_loss, losses
        return avg, mean_loss

    if corruptor is None:
        def round_fn(params, x, y, mask, weights, loss_weights, rng):
            return round_core(params, x, y, mask, weights, loss_weights,
                              rng, None)
        return round_fn
    return round_core


def client_rngs(rng, n_local, offset):
    """Per-client rng streams keyed by GLOBAL client slot, so the vmap and
    shard_map paths produce bitwise-identical randomness (shuffle order,
    dropout) for the same sampled round."""
    return jax.vmap(lambda i: jax.random.fold_in(rng, i))(offset + jnp.arange(n_local))


def make_sharded_round(local_train, mesh, axis: str = "clients",
                       client_transform=None, nan_guard: bool = False,
                       with_client_losses: bool = False, aggregator=None,
                       corruptor=None, group_reduce: bool = False):
    """Sharded round: client axis split over ``mesh[axis]``; output replicated.

    Weighted average = psum of per-shard weighted partial sums / psum of
    weights — exact regardless of how clients land on shards.
    ``nan_guard`` and ``with_client_losses`` as in :func:`make_vmap_round`
    (the per-client losses come back client-sharded over ``axis``).

    ``aggregator`` (core/robust_agg protocol): a non-mean aggregator needs
    the FULL client-stacked update, which the partial-sum reduction never
    materializes — the round ``all_gather``s the trained stack (and the
    weights) along the client axis and runs the aggregator replicated on
    every shard. ``tiled`` gathers concatenate in axis order, which is
    exactly the global-slot order the vmap path stacks, so the aggregator
    sees bit-identical inputs on one chip and on a mesh. ``None`` / mean
    keeps the partial-sum ``psum`` fast path untouched (bit-equal).

    ``group_reduce`` — the HIERARCHICAL SPARSE REDUCTION (group-level
    partial aggregation + sparse global step, the arXiv:1903.05133
    shape) for ``group_composable`` aggregators. On a flat mesh each
    shard is a group: stage 1 runs the aggregator SHARD-LOCALLY over the
    shard's own clients (no communication); stage 2 ``all_gather``s only
    the G group partials + participation weights and applies the same
    aggregator across groups (a group whose clients were all excluded
    carries weight 0 and drops out — the "sparse" in sparse global
    reduction; the collective shrinks from C client models to G ≪ C
    group partials). On a DCN×ICI mesh (``multihost.py``; the mesh
    carries a ``"hosts"`` axis) client groups are PINNED PER HOST:
    stage 1 gathers the host's own client stack over the ICI axis only —
    zero DCN traffic — and applies the aggregator per host; stage 2
    crosses the DCN axis with exactly G = n_hosts group partials +
    participation mass, O(G·model) inter-host bytes instead of the flat
    path's O(C·model) client-stack ``all_gather``. Mean is already this
    reduction EXACTLY (per-shard partial sums + the hierarchical
    ``psum`` — ICI first, one host partial across DCN — which the mean
    path runs with or without the flag) and keeps its bit-equal fast
    path; the coordinate-wise statistics compose as median-of-medians /
    trim-of-trims — the hierarchical robust construction, semantically
    distinct from the flat statistic by design (and on a DCN mesh the
    group is the HOST, not the shard). Non-composable aggregators
    (krum, geometric_median) refuse ``group_reduce`` LOUDLY here: their
    exact semantics need the full client-stacked ``all_gather`` fallback
    (``group_reduce=False``).

    ``corruptor`` as in :func:`make_vmap_round`: the round grows a
    trailing client-sharded ``adv`` operand."""
    if _is_mean(aggregator):
        aggregator = None
    if group_reduce and aggregator is not None \
            and not getattr(aggregator, "group_composable", False):
        raise ValueError(
            f"aggregator {getattr(aggregator, 'name', aggregator)!r} does "
            "not compose group-wise (krum needs pairwise client "
            "distances, geometric_median a joint Weiszfeld fixpoint); "
            "use group_reduce=False to keep the exact full client-stack "
            "all_gather path, or a composable aggregator "
            "(mean/coord_median/trimmed_mean) for the hierarchical "
            "sparse reduction")

    axes = client_axes(mesh, axis)
    dcn = axes[0] if len(axes) > 1 else None
    gather_ax = axes if dcn else axis  # collective name(s) spanning C

    def body(params, x, y, mask, weights, loss_weights, rng, adv):
        # Same global-slot-keyed streams as the vmap path. On a DCN×ICI
        # mesh the flattened (hosts-major) axis index IS the global
        # shard slot — exactly the order P(("hosts", axis)) lays the
        # client dimension out in.
        shard_idx = jax.lax.axis_index(gather_ax)
        rngs = client_rngs(rng, x.shape[0], shard_idx * x.shape[0])
        client_params, losses, finite = run_clients_guarded(
            local_train, client_transform, nan_guard,
            params, x, y, mask, rngs, corruptor=corruptor, adv=adv)
        weights = weights * finite
        loss_weights = loss_weights * finite
        w = weights.astype(jnp.float32)
        if aggregator is None:
            total = _psum_hier(jnp.sum(w), axes)
            wn = w / jnp.maximum(total, 1e-12)
            avg = jax.tree.map(
                lambda p: _psum_hier(
                    jnp.einsum("c,c...->...", wn, p.astype(jnp.float32)),
                    axes).astype(p.dtype),
                client_params,
            )
            if nan_guard:
                # All-diverged round: keep the previous global model.
                avg = jax.tree.map(
                    lambda a, p: jnp.where(total > 0, a, p), avg, params)
        elif group_reduce:
            # Hierarchical sparse reduction. Stage 1's group is the
            # SHARD on a flat mesh (shard-local, zero communication) and
            # the HOST on a DCN×ICI mesh (the host's client stack
            # gathered over the ICI axis only — zero DCN traffic).
            # Stage 2 crosses the remaining axes with exactly G group
            # partials + participation mass. An all-excluded group's
            # partial may carry the aggregator's ±inf exclusion
            # sentinels — its zero participation weight gates it out of
            # stage 2, exactly the client-level weight semantics lifted
            # one level up.
            if dcn:
                g_params = jax.tree.map(
                    lambda p: jax.lax.all_gather(p, axis, axis=0,
                                                 tiled=True),
                    client_params)
                g_w = jax.lax.all_gather(w, axis, axis=0, tiled=True)
                part = aggregator(g_params, g_w)
                pw = jnp.sum(jnp.maximum(g_w, 0.0))
                stage2 = dcn
            else:
                part = aggregator(client_params, w)
                pw = jnp.sum(jnp.maximum(w, 0.0))
                stage2 = axis
            parts = jax.tree.map(
                lambda p: jax.lax.all_gather(p, stage2), part)  # [G, ...]
            pws = jax.lax.all_gather(pw, stage2)  # [G]
            avg = _robust_avg(aggregator, parts, pws, params)
        else:
            full = jax.tree.map(
                lambda p: jax.lax.all_gather(p, gather_ax, axis=0,
                                             tiled=True),
                client_params)
            w_full = jax.lax.all_gather(w, gather_ax, axis=0, tiled=True)
            avg = _robust_avg(aggregator, full, w_full, params)
        lw = loss_weights.astype(jnp.float32)
        lw = lw / jnp.maximum(_psum_hier(jnp.sum(lw), axes), 1e-12)
        loss = _psum_hier(jnp.sum(losses * lw), axes)
        if with_client_losses:
            return avg, loss, losses
        return avg, loss

    cs = P(axes)  # client-stacked operands: DCN-major on a hybrid mesh
    specs = (P(), cs, cs, cs, cs, cs, P())
    out_specs = ((P(), P(), cs) if with_client_losses
                 else (P(), P()))
    if corruptor is None:
        @partial(shard_map, mesh=mesh, in_specs=specs,
                 out_specs=out_specs, check_vma=False)
        def round_fn(params, x, y, mask, weights, loss_weights, rng):
            return body(params, x, y, mask, weights, loss_weights, rng, None)
    else:
        @partial(shard_map, mesh=mesh, in_specs=specs + (cs,),
                 out_specs=out_specs, check_vma=False)
        def round_fn(params, x, y, mask, weights, loss_weights, rng, adv):
            return body(params, x, y, mask, weights, loss_weights, rng, adv)

    return round_fn


def make_fused_round_step(round_fn, server_update=None):
    """ONE dispatch per host-loop round: client training + weighted
    aggregation (``round_fn``) + the algorithm's PURE server update,
    fused — ``make_window_scan``'s shape at W=1, without the scan.

    The host loop used to dispatch the round and the server update as
    separate jit calls with undonated intermediates: the old global
    model, the round average, and the new global model were all live at
    once (3 model-sized HBM copies on the round's critical path), and
    the server update paid its own dispatch. Callers jit this with
    ``donate_argnums=(0, 1)`` — the incoming ``(net, extra)`` carry is
    always replaced by the step's outputs, exactly the windowed scan's
    donation discipline, so XLA reuses the old buffers in place
    (``obs.sanitizer.donation_audit`` pins the single-copy steady
    state).

    Signature matches the scan body: ``step(net, extra, x, y, mask,
    weights, key, *aux) -> ((net', extra'), loss)`` with ``weights``
    used for both the model average and the loss weighting (the
    streaming host loop's convention) and ``key`` the round's rng key
    (randomized server updates fold_in from it — same protocol slot as
    the windowed carry)."""

    def step_fn(net, extra, x, y, mask, weights, key, *aux):
        avg, loss = round_fn(net, x, y, mask, weights, weights, key, *aux)
        if server_update is None:
            return (avg, extra), loss
        new_net, new_extra = server_update(net, avg, extra, key)
        return (new_net, new_extra), loss

    return step_fn


def make_fused_stateful_round_step(round_fn):
    """Fused ONE-dispatch round for ``make_stateful_client_round``-shaped
    rounds (SCAFFOLD's controls, FedDyn's corrections): cohort state
    gather + the stateful round + the masked scatter-merge run in the
    SAME dispatch, with the carry ``(net, (s_global, s_clients))`` —
    ``s_clients`` the FULL client-stacked state ``[N, ...]``. Callers
    jit with ``donate_argnums=(0, 1)`` so the old model AND the old
    state stack are reused in place (the host loop used to pay three
    dispatches — eager gather, round, eager scatter — and hold the old
    plus new state stacks live simultaneously).

    Signature matches the capability protocol's step shape:
    ``step(net, extra, x, y, mask, weights, key, idx, umask) ->
    ((net', extra'), loss)`` where ``idx [k]`` is the round's padded
    cohort index map and ``umask [k]`` gates the scatter (only clients
    that actually trained write their slot — padded and empty-client
    slots are routed out of bounds and dropped)."""
    from fedml_tpu.core.tree import gather_stacked, scatter_stacked

    def step_fn(net, extra, x, y, mask, weights, key, idx, umask):
        s_global, s_clients = extra
        sub = gather_stacked(s_clients, idx)
        new_net, new_global, new_sub, loss = round_fn(
            net, s_global, sub, x, y, mask, weights, key)
        s_clients = scatter_stacked(s_clients, idx, new_sub, umask)
        return (new_net, (new_global, s_clients)), loss

    return step_fn


def make_step_window_scan(step_fn):
    """``lax.scan`` a capability-protocol fused round step over a window
    of PRE-GATHERED rounds: the ONE step definition an algorithm
    publishes (``_build_fused_step``) serves both the fused host round
    (jitted with donation at W=1) and this scan — so windowed rounds are
    bit-equal to fused host rounds BY CONSTRUCTION, not by parallel
    implementations kept in sync.

    Returns ``scan_fn(net, extra, x, y, mask, weights, keys, *aux) ->
    ((net', extra'), losses)`` with ``x/y/mask [W, ...]``, ``weights
    [W, C]``, ``keys [W, 2]`` the per-round rng keys in round order, and
    ``aux`` any per-round scanned operands with leading axis W (the
    ``_window_scan_extras`` slot: SCAFFOLD's cohort index maps, the
    corruption drill's adversary masks, FedNova's τ-normalized
    weights)."""

    def scan_fn(net, extra, x, y, mask, weights, keys, *aux):
        def body(carry, inp):
            (xw, yw, mw, ww, kw), auxw = inp[:5], inp[5:]
            return step_fn(carry[0], carry[1], xw, yw, mw, ww, kw, *auxw)

        return jax.lax.scan(body, (net, extra),
                            (x, y, mask, weights, keys) + tuple(aux))

    return scan_fn


def make_window_scan(round_fn, server_update=None):
    """``lax.scan`` over a window of PRE-GATHERED rounds: one jitted
    dispatch runs W whole federated rounds back-to-back — the windowed
    execution tier's device side (host syncs drop from O(rounds) to
    O(rounds/W); see ``FedAvgAPI.train_rounds_windowed``).

    The scan CARRY is ``(net, extra)`` — the windowed carry protocol.
    Between rounds the per-algorithm ``server_update(net, avg, extra,
    key) -> (net', extra')`` is folded over the round average: ``None``
    (the default) is plain FedAvg (``net' = avg``, ``extra`` threaded
    untouched — pass ``extra=None``); FedOpt passes its pure jitted
    optax server step with ``extra`` the server optimizer state, so the
    adaptive-server algorithms ride the same one-dispatch-per-W-rounds
    tier as plain FedAvg (the "keep state on device, talk to the host
    less" lever of Parallel Restarted SGD, arXiv:1807.06629, applied at
    the dispatch boundary). ``key`` is the ROUND's rng key — the same
    key the host loop's ``run_round`` split for that round — so a
    randomized server update (FedAvgRobust's weak-DP noise) derives its
    stream by ``fold_in`` from it and stays bit-equal to the host loop
    without carrying a split chain (the PR-2 prefix-stability
    discipline; fedlint R1 forbids carried split chains in scan bodies).

    ``round_fn`` is the SAME per-round function the host loop dispatches
    (vmap round on one chip, shard_map round on a client mesh — jitted is
    fine, jit-under-scan inlines), so windowed rounds are bit-equal to
    host-loop rounds fed the same cohorts, weights, and rng keys.

    Returns ``scan_fn(net, extra, x, y, mask, weights, keys, *aux) ->
    ((net', extra'), losses)`` with ``x/y/mask [W, C, S, B, ...]``,
    ``weights [W, C]`` (sample counts x pad mask — used for BOTH the
    model average and the loss weighting, as the streaming host loop
    does), ``keys [W, 2]`` the per-round rng keys in round order, and
    ``aux`` any extra per-round scanned inputs (leading axis W) the
    round takes as trailing operands — the "round"-protocol slot
    ``FedAvgAPI._window_scan_extras`` fills (the corruption drill's
    ``[W, C]`` adversary mask).

    Since the capability-record refactor this is literally
    ``make_step_window_scan(make_fused_round_step(...))`` — the scanned
    body and the fused host round are the SAME function."""
    return make_step_window_scan(make_fused_round_step(round_fn,
                                                       server_update))


def make_stateful_window_scan(round_fn):
    """Windowed scan for ``make_stateful_client_round``-shaped rounds
    (SCAFFOLD's control variates): the carry protocol's "custom" form,
    where the round itself consumes and produces the carried state
    instead of a post-round ``server_update``.

    The carry is ``(net, (s_global, s_clients))`` with ``s_clients`` the
    FULL client-stacked state ``[N, ...]``. Each scanned round gathers
    its cohort's slots, runs the stateful round, and scatter-merges the
    updated slots back — INSIDE the scan body, because a client sampled
    by two rounds of the same window must see round t's state update in
    round t' > t (a per-window pre-gather/post-scatter would replay
    stale slots for repeat clients and break host-loop bit-equality).

    Returns ``scan_fn(net, extra, x, y, mask, weights, keys, idx, umask)
    -> ((net', extra'), losses)`` where ``idx [W, k]`` is the window's
    padded cohort index map (the same map ``gather_window`` consumed)
    and ``umask [W, k]`` gates the scatter — only clients that actually
    trained write their slot back (padded and empty-client slots are
    routed out of bounds and dropped, exactly as the host loop's
    ``scatter_stacked``).

    Since the capability-record refactor this is literally
    ``make_step_window_scan(make_fused_stateful_round_step(...))`` — the
    scanned body and the fused host round are the SAME function."""
    return make_step_window_scan(make_fused_stateful_round_step(round_fn))


def window_put(mesh, axis: str = "clients"):
    """``put`` callable for ``FederatedStore.gather_window`` on a client
    mesh: lays each ``[W, C, ...]`` superbatch field out with the client
    axis (dim 1) sharded over ``mesh[axis]`` — over ``("hosts", axis)``
    on a DCN×ICI mesh, so each host's H2D gather lands HOST-LOCAL and
    the ``WindowPrefetcher`` overlaps the next window's host-local
    gather + transfer against the current window's compute — and the
    window axis replicated, so every scanned round slice arrives already
    client-sharded for the shard_map round.

    The ``np.array`` copy is load-bearing: ``device_put`` of a large
    aligned numpy array ZERO-COPY aliases its memory on the CPU backend
    (reproduced: mutate after put → the device array changes; today's
    sharded put happens to copy, but that is backend behavior, not a
    contract), and gather_window hands this callable a VIEW of its
    reused staging buffers — an aliased put would let the next window's
    refill silently corrupt this window's in-flight superbatch. Aliasing
    the fresh copy instead is fine: nobody ever mutates it, and jax
    keeps it alive for the device array's lifetime."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P(None, client_axes(mesh, axis)))

    def put(a):
        return jax.device_put(np.array(a), sharding)

    # Contract with FederatedStore.gather_window (fedlint R2): this put
    # copies before putting, so the store must not insert a second
    # defensive copy of its staging buffers.
    put.copies = True
    return put


def make_stateful_client_round(body, mesh, axis: str = "clients"):
    """Round wrapper for algorithms carrying server + client-stacked
    state through the round (SCAFFOLD's controls, FedDyn's corrections).

    ``body(net, s_global, s_clients, x, y, mask, weights, rngs, cross)
    -> (net', s_global', s_clients', loss)`` is written ONCE by the
    algorithm; this wrapper supplies the per-client rng streams and the
    cross-shard reduction — identity on a single device, psum under
    shard_map (the hierarchical ICI-then-DCN association on a DCN×ICI
    mesh, like the mean round's reduction) — so the vmap and sharded
    paths cannot drift (the same shared-body discipline as
    make_vmap_round/make_sharded_round)."""
    if mesh is None:
        def round_fn(net, s_global, s_clients, x, y, mask, weights, rng):
            rngs = client_rngs(rng, x.shape[0], 0)
            return body(net, s_global, s_clients, x, y, mask, weights,
                        rngs, cross=lambda v: v)
        return round_fn

    axes = client_axes(mesh, axis)
    cs = P(axes)
    idx_ax = axes if len(axes) > 1 else axis

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), cs, cs, cs, cs, cs, P()),
        out_specs=(P(), P(), cs, P()),
        check_vma=False,
    )
    def round_fn(net, s_global, s_clients, x, y, mask, weights, rng):
        shard_idx = jax.lax.axis_index(idx_ax)
        rngs = client_rngs(rng, x.shape[0], shard_idx * x.shape[0])
        return body(net, s_global, s_clients, x, y, mask, weights, rngs,
                    cross=lambda v: _psum_hier(v, axes))

    return round_fn
