"""Ditto — personalized federated learning (Li et al. 2021).

New capability: the reference trains ONE global model; every client ends
with the same weights regardless of how skewed its local distribution is.
Ditto keeps a personal model v_k per client alongside the FedAvg global w:

    w   <- FedAvg round (unchanged)
    v_k <- v_k - lr * (grad f_k(v_k) + lam * (v_k - w))

The proximal pull lam*(v_k - w) interpolates between purely-local training
(lam = 0) and following the global model (lam -> inf), so each client
trades personalization against federation strength.

TPU design: the N personal models live as ONE client-stacked pytree
``[N, ...]`` on device; a round gathers the sampled clients' models,
vmaps the proximal local update (the same ``extra_grad_fn`` hook FedProx
uses, but anchored at the GLOBAL params instead of the entry params), and
scatters them back — no per-client Python state.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_local_train_fn_from_cfg,
)


# Canonical implementations moved to core.tree (the windowed scan needs
# them without importing an algorithm module); the underscore aliases
# stay for the existing importers (scaffold, feddyn, tests).
from fedml_tpu.core.tree import gather_stacked as _gather_stacked
from fedml_tpu.core.tree import scatter_stacked as _scatter_stacked


#: fold_in child reserved for the personal step's per-client streams —
#: forked from the ROUND key (the windowed carry protocol's key slot),
#: so the host loop and the scanned round derive identical randomness.
#: Disjoint from the trainer's client streams (fold_in on slot index),
#: the transform's 0x7F and the corruptor's 0xC0.
_PERSONAL_TAG = 0xD1770


class DittoAPI(FedAvgAPI):
    """FedAvg for the global model + per-client personal models with a
    proximal pull of strength ``lam`` toward the current global.

    Carry capability record ("custom" protocol): the personal-model
    stack IS the carry. The published step runs the standard global
    round, then gathers the cohort's personal models, applies the
    proximal personal update against the NEW global, and scatter-merges
    — one donated dispatch per round, scanned W-deep on the windowed
    tier. Streams from a ``FederatedStore`` (personal nets stay
    device-resident; the cohort rides the shared ``_cohort`` path).

    The personal step's rng streams fork from the ROUND key via
    ``fold_in`` (``_PERSONAL_TAG``) instead of a second ``self.rng``
    split — the prefix-stability discipline that makes windowed rounds
    bit-equal to host rounds. (This changed Ditto's personal-step
    randomness relative to the pre-record implementation; no test pins
    those streams.) Per-round metrics report the global train loss; the
    per-round ``personal_loss`` scalar was retired with the fused step
    (``evaluate_personalized`` remains the personalization metric)."""

    supports_streaming = True  # personal nets device-resident; cohort streams
    window_protocol = "custom"
    window_carry = "personal-model stack"

    def __init__(self, *args, lam: float = 0.1, **kw):
        self.lam = lam
        super().__init__(*args, **kw)
        n = int(self.train_fed.num_clients)
        # All personal models start from the same init as the global.
        self.personal_nets = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), self.net
        )
        self._personal_jit = None

    def _on_client_lr_change(self):
        """The personal trainer's cached jit bakes in the optimizer."""
        self._personal_jit = None

    def _personal_round_fn(self):
        """vmapped proximal personal update, prox anchored at the global
        params (``make_local_train_fn`` anchors ``extra_grad_fn`` at the
        ENTRY params — here v_k — so the global anchor w is bound in
        explicitly per call)."""
        if self._personal_jit is not None:
            return self._personal_jit
        lam = self.lam
        # The LIVE (possibly schedule-decayed) lr, not the cfg base lr.
        optimizer = make_client_optimizer(
            self.cfg.client_optimizer, self._client_lr, self.cfg.wd,
            self.cfg.grad_clip)

        def prox(params, _entry_anchor, w_global):
            return jax.tree.map(lambda v, w: lam * (v - w), params, w_global)

        def one(v_net, w_global_params, xb, yb, mb, rng):
            train = make_local_train_fn_from_cfg(
                self.fns.apply, optimizer, self.cfg, self._loss_fn,
                extra_grad_fn=partial(prox, w_global=w_global_params),
            )
            return train(v_net, xb, yb, mb, rng)

        def rounds(personal_sub, global_params, x, y, mask, rngs):
            return jax.vmap(one, in_axes=(0, None, 0, 0, 0, 0))(
                personal_sub, global_params, x, y, mask, rngs)

        self._personal_jit = jax.jit(rounds)
        return self._personal_jit

    # --- carry capability record ("custom"): personal nets ride the scan -
    def _build_fused_step(self):
        """ONE Ditto round as one donated dispatch: the standard global
        round (``round_fn`` — aggregation/guards/compression untouched)
        followed by the cohort's proximal personal updates against the
        NEW global, with the personal stack gathered/scatter-merged in
        the same dispatch. The scatter gate is the pad mask (``umask``):
        an empty sampled client's personal training is a tree_select
        no-op, so writing its unchanged slot back is bit-identical to
        skipping it."""
        round_fn = self.round_fn
        personal_fn = self._personal_round_fn()

        def step(net, personal_nets, x, y, mask, weights, key, idx, umask):
            avg, loss = round_fn(net, x, y, mask, weights, weights, key)
            personal_sub = _gather_stacked(personal_nets, idx)
            base = jax.random.fold_in(key, _PERSONAL_TAG)
            rngs = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(x.shape[0]))
            trained, _plosses = personal_fn(
                personal_sub, avg.params, x, y, mask, rngs)
            personal_nets = _scatter_stacked(
                personal_nets, idx, trained, umask)
            return (avg, personal_nets), loss

        return step

    def _window_carry_init(self):
        return self.personal_nets

    def _window_carry_commit(self, extra) -> None:
        self.personal_nets = extra

    def _window_scan_extras(self, idx2d, wmask2d):
        from fedml_tpu.obs.sanitizer import planned_transfer

        import numpy as np

        with planned_transfer():
            return (jnp.asarray(np.asarray(idx2d), jnp.int32),
                    jnp.asarray(np.asarray(wmask2d), jnp.float32))

    # -- checkpoint/resume: personal models are run state too -------------
    def checkpoint_extra_state(self):
        return {"personal_nets": self.personal_nets}

    def load_checkpoint_extra_state(self, extra) -> None:
        self.personal_nets = extra["personal_nets"]

    def evaluate_personalized(self) -> Dict[str, float]:
        """Sample-weighted mean per-client accuracy of each personal model
        on its OWN local shard — the quantity personalization optimizes
        (the global model's global-test eval remains ``evaluate()``).
        Store-backed federations iterate the population in host-gathered
        chunks (device holds one chunk of data + personal models at a
        time)."""
        f = self.train_fed
        fn = getattr(self, "_personal_eval_jit", None)
        if fn is None:  # cache: an inline vmap would re-trace every call
            fn = jax.jit(jax.vmap(
                lambda net, x, y, mask: self.eval_fn(net, x, y, mask)))
            self._personal_eval_jit = fn
        if self._streaming:
            import numpy as np

            tot_acc = tot_loss = tot_n = 0.0
            for lo in range(0, f.num_clients, 256):
                idx = np.arange(lo, min(lo + 256, f.num_clients))
                sub = f.gather_cohort(idx)
                psub = _gather_stacked(self.personal_nets, jnp.asarray(idx))
                m = fn(psub, sub.x, sub.y, sub.mask)
                num = np.asarray(m["num"])
                tot_acc += float((np.asarray(m["accuracy"]) * num).sum())
                tot_loss += float((np.asarray(m["loss"]) * num).sum())
                tot_n += float(num.sum())
            n = max(tot_n, 1.0)
            return {"personal_accuracy": tot_acc / n,
                    "personal_loss_eval": tot_loss / n}
        m = fn(self.personal_nets, f.x, f.y, f.mask)
        n = jnp.maximum(jnp.sum(m["num"]), 1.0)
        return {
            "personal_accuracy": float(jnp.sum(m["accuracy"] * m["num"]) / n),
            "personal_loss_eval": float(jnp.sum(m["loss"] * m["num"]) / n),
        }

    def evaluate_global_on_local(self) -> Dict[str, float]:
        """The comparison baseline: the single global model evaluated the
        same way (per-client local shards, sample-weighted). Reuses the
        inherited per-client eval (same cached jit) under a Ditto-specific
        key name."""
        m = self.evaluate_on_clients()
        return {"global_local_accuracy": m["clients_train_acc"]}
