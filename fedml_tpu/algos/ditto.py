"""Ditto — personalized federated learning (Li et al. 2021).

New capability: the reference trains ONE global model; every client ends
with the same weights regardless of how skewed its local distribution is.
Ditto keeps a personal model v_k per client alongside the FedAvg global w:

    w   <- FedAvg round (unchanged)
    v_k <- v_k - lr * (grad f_k(v_k) + lam * (v_k - w))

The proximal pull lam*(v_k - w) interpolates between purely-local training
(lam = 0) and following the global model (lam -> inf), so each client
trades personalization against federation strength.

TPU design: the N personal models live as ONE client-stacked pytree
``[N, ...]`` on device; a round gathers the sampled clients' models,
vmaps the proximal local update (the same ``extra_grad_fn`` hook FedProx
uses, but anchored at the GLOBAL params instead of the entry params), and
scatters them back — no per-client Python state.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.data.batching import gather_clients
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_local_train_fn_from_cfg,
)


# Canonical implementations moved to core.tree (the windowed scan needs
# them without importing an algorithm module); the underscore aliases
# stay for the existing importers (scaffold, feddyn, tests).
from fedml_tpu.core.tree import gather_stacked as _gather_stacked
from fedml_tpu.core.tree import scatter_stacked as _scatter_stacked


class DittoAPI(FedAvgAPI):
    """FedAvg for the global model + per-client personal models with a
    proximal pull of strength ``lam`` toward the current global."""

    supports_streaming = False  # personal nets are a device-resident [C, ...] stack

    def __init__(self, *args, lam: float = 0.1, **kw):
        self.lam = lam
        super().__init__(*args, **kw)
        n = int(self.train_fed.num_clients)
        # All personal models start from the same init as the global.
        self.personal_nets = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), self.net
        )
        self._personal_jit = None

    def _on_client_lr_change(self):
        """The personal trainer's cached jit bakes in the optimizer."""
        self._personal_jit = None

    def _personal_round_fn(self):
        """vmapped proximal personal update, prox anchored at the global
        params (``make_local_train_fn`` anchors ``extra_grad_fn`` at the
        ENTRY params — here v_k — so the global anchor w is bound in
        explicitly per call)."""
        if self._personal_jit is not None:
            return self._personal_jit
        lam = self.lam
        # The LIVE (possibly schedule-decayed) lr, not the cfg base lr.
        optimizer = make_client_optimizer(
            self.cfg.client_optimizer, self._client_lr, self.cfg.wd,
            self.cfg.grad_clip)

        def prox(params, _entry_anchor, w_global):
            return jax.tree.map(lambda v, w: lam * (v - w), params, w_global)

        def one(v_net, w_global_params, xb, yb, mb, rng):
            train = make_local_train_fn_from_cfg(
                self.fns.apply, optimizer, self.cfg, self._loss_fn,
                extra_grad_fn=partial(prox, w_global=w_global_params),
            )
            return train(v_net, xb, yb, mb, rng)

        def rounds(personal_sub, global_params, x, y, mask, rngs):
            return jax.vmap(one, in_axes=(0, None, 0, 0, 0, 0))(
                personal_sub, global_params, x, y, mask, rngs)

        self._personal_jit = jax.jit(rounds)
        return self._personal_jit

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        # 1) ordinary FedAvg round for the global model
        metrics = super().train_one_round(round_idx)
        # 2) proximal personal updates for the sampled clients
        idx, wmask = self.sample_round(round_idx)
        idx = jnp.asarray(idx)
        wmask_a = jnp.asarray(wmask, jnp.float32)
        sub = gather_clients(self.train_fed, idx)
        personal_sub = _gather_stacked(self.personal_nets, idx)
        self.rng, rnd = jax.random.split(self.rng)
        rngs = jax.vmap(lambda i: jax.random.fold_in(rnd, i))(
            jnp.arange(idx.shape[0]))
        trained, losses = self._personal_round_fn()(
            personal_sub, self.net.params, sub.x, sub.y, sub.mask, rngs)
        self.personal_nets = _scatter_stacked(
            self.personal_nets, idx, trained, wmask_a)
        metrics["personal_loss"] = float(
            jnp.sum(losses * wmask_a) / jnp.maximum(jnp.sum(wmask_a), 1.0))
        return metrics

    # -- checkpoint/resume: personal models are run state too -------------
    def checkpoint_extra_state(self):
        return {"personal_nets": self.personal_nets}

    def load_checkpoint_extra_state(self, extra) -> None:
        self.personal_nets = extra["personal_nets"]

    def evaluate_personalized(self) -> Dict[str, float]:
        """Sample-weighted mean per-client accuracy of each personal model
        on its OWN local shard — the quantity personalization optimizes
        (the global model's global-test eval remains ``evaluate()``)."""
        f = self.train_fed
        fn = getattr(self, "_personal_eval_jit", None)
        if fn is None:  # cache: an inline vmap would re-trace every call
            fn = jax.jit(jax.vmap(
                lambda net, x, y, mask: self.eval_fn(net, x, y, mask)))
            self._personal_eval_jit = fn
        m = fn(self.personal_nets, f.x, f.y, f.mask)
        n = jnp.maximum(jnp.sum(m["num"]), 1.0)
        return {
            "personal_accuracy": float(jnp.sum(m["accuracy"] * m["num"]) / n),
            "personal_loss_eval": float(jnp.sum(m["loss"] * m["num"]) / n),
        }

    def evaluate_global_on_local(self) -> Dict[str, float]:
        """The comparison baseline: the single global model evaluated the
        same way (per-client local shards, sample-weighted). Reuses the
        inherited per-client eval (same cached jit) under a Ditto-specific
        key name."""
        m = self.evaluate_on_clients()
        return {"global_local_accuracy": m["clients_train_acc"]}
