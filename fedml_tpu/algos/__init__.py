from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.centralized import CentralizedTrainer
from fedml_tpu.algos.decentralized import DecentralizedAPI
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.algos.fedgan import FedGanAPI
from fedml_tpu.algos.fednova import FedNovaAPI
from fedml_tpu.algos.fedopt import FedOptAPI
from fedml_tpu.algos.fedprox import FedProxAPI
from fedml_tpu.algos.hierarchical import HierarchicalFedAvgAPI
from fedml_tpu.algos.robust import FedAvgRobustAPI

__all__ = [
    "FedConfig",
    "CentralizedTrainer",
    "DecentralizedAPI",
    "FedAvgAPI",
    "FedGanAPI",
    "FedNovaAPI",
    "FedOptAPI",
    "FedProxAPI",
    "HierarchicalFedAvgAPI",
    "FedAvgRobustAPI",
]
