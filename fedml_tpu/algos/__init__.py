from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.centralized import CentralizedTrainer
from fedml_tpu.algos.decentralized import DecentralizedAPI
from fedml_tpu.algos.fedac import FedAcAPI, ServerAvgAPI
from fedml_tpu.algos.fedadapter import FedAdapterAPI
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.algos.fedgan import FedGanAPI
from fedml_tpu.algos.fedgkt import FedGKTAPI
from fedml_tpu.algos.fednas import FedNASAPI
from fedml_tpu.algos.fednova import FedNovaAPI
from fedml_tpu.algos.fedopt import FedOptAPI
from fedml_tpu.algos.fedprox import FedProxAPI
from fedml_tpu.algos.fedseg import FedSegAPI
from fedml_tpu.algos.hierarchical import HierarchicalFedAvgAPI
from fedml_tpu.algos.robust import FedAvgRobustAPI
from fedml_tpu.algos.split_nn import SplitNNAPI
from fedml_tpu.algos.turboaggregate import TurboAggregateAPI
from fedml_tpu.algos.ditto import DittoAPI
from fedml_tpu.algos.fedasync import FedML_FedAsync_distributed
from fedml_tpu.algos.fedbuff import FedML_FedBuff_distributed
from fedml_tpu.algos.fedbn import FedBNAPI
from fedml_tpu.algos.qfedavg import QFedAvgAPI
from fedml_tpu.algos.feddyn import FedDynAPI
from fedml_tpu.algos.scaffold import ScaffoldAPI
from fedml_tpu.algos.vertical_fl import VflAPI

__all__ = [
    "FedAcAPI",
    "FedAdapterAPI",
    "ServerAvgAPI",
    "DittoAPI",
    "FedBNAPI",
    "FedML_FedAsync_distributed",
    "FedML_FedBuff_distributed",
    "QFedAvgAPI",
    "FedDynAPI",
    "ScaffoldAPI",
    "FedConfig",
    "CentralizedTrainer",
    "DecentralizedAPI",
    "FedAvgAPI",
    "FedGanAPI",
    "FedGKTAPI",
    "FedNASAPI",
    "FedNovaAPI",
    "SplitNNAPI",
    "TurboAggregateAPI",
    "VflAPI",
    "FedOptAPI",
    "FedProxAPI",
    "FedSegAPI",
    "HierarchicalFedAvgAPI",
    "FedAvgRobustAPI",
]
