from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.algos.fedopt import FedOptAPI
from fedml_tpu.algos.fedprox import FedProxAPI

__all__ = ["FedConfig", "FedAvgAPI", "FedOptAPI", "FedProxAPI"]
