"""Carry capability records — the algorithm zoo's ONE declaration of how
(whether) each algorithm rides the multi-round execution tiers.

The windowed carry protocol (PR 3) already defines the shape every
stateful server update must take to scan: ``window_protocol`` plus the
``_window_*`` hooks ``(carry_init, server_update, carry_commit)`` and the
optional per-round ``_window_scan_extras``. What used to sit NEXT to that
protocol was a pile of per-class ``type(self)`` identity guards — each
tier hand-rolled its own exclusion list, the EXECUTION.md support matrix
was maintained by hand, and a newly converted algorithm had to win an
argument with three different guards before it ran fast.

This module derives ONE record per algorithm class from its declarations
(:func:`record_for`) and makes everything downstream consume it:

- the tier entry points (``train_rounds_windowed`` / ``_pipelined`` /
  ``_on_device`` and the fused round step) key their guards on the
  record and refuse with :func:`refusal` — a message derived from the
  record, naming the reason the class declared;
- the EXECUTION.md algorithm × tier support matrix is GENERATED from the
  records (:func:`render_matrix`, ``scripts/gen_support_matrix.py``) and
  drift-tested, so the docs cannot silently diverge from the guards;
- an algorithm opts in by declaring the protocol hooks (FedOpt's pure
  optax fold, SCAFFOLD/FedDyn's ``_build_fused_step``), and opts out by
  declaring ``window_protocol = None`` with a ``window_exclusion``
  reason — never by being added to an identity list.

Class-level declaration surface (all optional beyond ``window_protocol``):

``capability_name``
    Display name for the matrix (default: the class name).
``window_carry``
    Human description of the scan carry (matrix column), e.g.
    ``"server optimizer state"``; default ``"—"`` (no carry).
``window_exclusion``
    Why the algorithm sits out every scan tier. Required (by the drift
    test) when ``window_protocol`` is None; woven into every refusal.
``capability_tiers``
    Explicit tier dict for classes OUTSIDE the FedAvg family whose
    entry points are their own (DecentralizedAPI's on-device gossip
    scan). FedAvg-family records are derived structurally and must not
    set this.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Optional

#: (display name, module under fedml_tpu.algos, class name) — the zoo the
#: generated support matrix covers, in matrix row order. The simulator
#: tiers only: the message-passing servers (cross-silo, FedAsync,
#: FedBuff) are a different execution plane with their own matrix
#: (docs/EXECUTION.md "Wire formats × codecs × backends").
ZOO = (
    ("FedAvg", "fedavg", "FedAvgAPI"),
    ("FedProx", "fedprox", "FedProxAPI"),
    ("FedOpt", "fedopt", "FedOptAPI"),
    ("FedAc", "fedac", "FedAcAPI"),
    ("ServerAvg", "fedac", "ServerAvgAPI"),
    ("q-FedAvg", "qfedavg", "QFedAvgAPI"),
    ("FedNova", "fednova", "FedNovaAPI"),
    ("FedAvgRobust", "robust", "FedAvgRobustAPI"),
    ("SCAFFOLD", "scaffold", "ScaffoldAPI"),
    ("FedDyn", "feddyn", "FedDynAPI"),
    ("Ditto", "ditto", "DittoAPI"),
    ("FedAdapter", "fedadapter", "FedAdapterAPI"),
    ("FedBN", "fedbn", "FedBNAPI"),
    ("FedGAN", "fedgan", "FedGanAPI"),
    ("FedNAS", "fednas", "FedNASAPI"),
    ("FedSeg", "fedseg", "FedSegAPI"),
    ("TurboAggregate", "turboaggregate", "TurboAggregateAPI"),
    ("HierarchicalFL", "hierarchical", "HierarchicalFedAvgAPI"),
    ("Decentralized", "decentralized", "DecentralizedAPI"),
    ("FedGKT", "fedgkt", "FedGKTAPI"),
    ("SplitNN", "split_nn", "SplitNNAPI"),
    ("VerticalFL", "vertical_fl", "VflAPI"),
)


@dataclass(frozen=True)
class CarryCapability:
    """One algorithm's declared + structurally derived capability record.

    ``fused``/``pipelined``/``windowed``/``on_device`` are the STATIC
    tier eligibilities (what the class can ever do); runtime conditions
    — a resident layout where windowed needs a store, oort selection,
    a subsampled mesh for the on-device scan — still gate per call."""

    algorithm: str
    protocol: Optional[str]       # "round" | "custom" | None
    carry: str                    # matrix annotation of the scan carry
    excluded: Optional[str]       # declared reason when sitting out
    custom_round: bool            # per-round procedure != run_round + _server_update
    custom_builders: bool         # round_fn not from the shared vmap/sharded builders
    custom_step: bool             # provides its own _build_fused_step
    pure_server_update: bool      # a pure windowed server_update exists
    round_aux: bool               # per-round host-computed aux operands
    streaming: bool               # supports FederatedStore cohorts
    fused: bool
    pipelined: bool
    windowed: bool
    on_device: bool


def _fedavg_family_record(cls, name, carry, excluded) -> CarryCapability:
    from fedml_tpu.algos.fedavg import FedAvgAPI
    from fedml_tpu.algos.loop import FederatedLoop

    proto = cls.window_protocol
    custom_round = (cls.train_one_round is not FedAvgAPI.train_one_round
                    or cls.run_round is not FederatedLoop.run_round)
    custom_builders = (
        cls._make_vmap_round is not FedAvgAPI._make_vmap_round
        or cls._make_sharded_round is not FedAvgAPI._make_sharded_round)
    custom_step = cls._build_fused_step is not FedAvgAPI._build_fused_step
    # Pure windowed server update: either nothing to fold (plain
    # ``net' = avg``) or the class provides the pure hook alongside its
    # host-side override.
    pure = (cls._server_update is FedAvgAPI._server_update
            or cls._window_server_update is not FedAvgAPI._window_server_update)
    aux = (cls._round_aux is not FederatedLoop._round_aux
           or cls._window_scan_extras is not FedAvgAPI._window_scan_extras)
    streaming = bool(cls.supports_streaming)
    fused = pipelined = windowed = on_device = False
    if proto == "round":
        fused = not custom_round and pure
        # The pipelined loop applies _server_update host-side, so even
        # an impure/stateful override rides it — only a custom round
        # refuses (its per-round procedure would be silently dropped).
        pipelined = not custom_round
        windowed = fused and streaming
        # The on-device scan threads the same pure carry between rounds
        # but samples (or keeps full participation) INSIDE the jit — a
        # host-computed per-round aux operand has no slot there.
        on_device = fused and not aux
    elif proto == "custom":
        has_scan = (custom_step or cls._build_window_scan
                    is not FedAvgAPI._build_window_scan)
        fused = custom_step
        pipelined = custom_step   # the fused step pipelines like a round
        windowed = has_scan and streaming
    return CarryCapability(
        algorithm=name, protocol=proto, carry=carry, excluded=excluded,
        custom_round=custom_round, custom_builders=custom_builders,
        custom_step=custom_step, pure_server_update=pure, round_aux=aux,
        streaming=streaming, fused=fused, pipelined=pipelined,
        windowed=windowed, on_device=on_device)


@lru_cache(maxsize=None)
def record_for(cls) -> CarryCapability:
    """The capability record for an algorithm CLASS (cached per class).

    FedAvg-family classes are derived structurally from the carry
    protocol's hooks; standalone classes (their own training loops)
    declare ``capability_tiers`` explicitly or default to host-loop
    only with their ``window_exclusion`` reason."""
    from fedml_tpu.algos.fedavg import FedAvgAPI

    name = getattr(cls, "capability_name", cls.__name__)
    carry = getattr(cls, "window_carry", "—")
    excluded = getattr(cls, "window_exclusion", None)
    if isinstance(cls, type) and issubclass(cls, FedAvgAPI):
        return _fedavg_family_record(cls, name, carry, excluded)
    tiers = getattr(cls, "capability_tiers", {})
    proto = getattr(cls, "window_protocol", None)
    if proto is None and excluded is None:
        excluded = ("no windowed carry capability record declared "
                    "(window_protocol=None and no window_exclusion)")
    return CarryCapability(
        algorithm=name, protocol=proto, carry=carry, excluded=excluded,
        custom_round=True, custom_builders=True,
        custom_step=bool(tiers.get("fused")),
        pure_server_update=False, round_aux=False,
        streaming=bool(getattr(cls, "supports_streaming", False)),
        fused=bool(tiers.get("fused", False)),
        pipelined=bool(tiers.get("pipelined", False)),
        windowed=bool(tiers.get("windowed", False)),
        on_device=bool(tiers.get("on_device", False)))


def refusal(cls, tier: str) -> str:
    """The record-derived refusal message for ``cls`` on ``tier`` —
    every scan-tier guard raises with THIS, so the reason a class
    declared (or the structural fact that disqualifies it) reaches the
    user verbatim instead of a hand-rolled per-guard paraphrase."""
    rec = record_for(cls)
    name = cls.__name__
    if (tier == "train_rounds_windowed" and not rec.windowed
            and rec.excluded and rec.protocol is not None):
        # A class that rides other tiers but declares WHY the windowed
        # store tier does not apply (DecentralizedAPI's gossip).
        return (f"{name} opts out of the windowed tier: {rec.excluded}")
    if (tier == "train_rounds_windowed" and not rec.streaming
            and (rec.fused or rec.custom_step)):
        # The class rides the scan tiers but keeps client data
        # device-resident — the windowed tier is a STORE tier.
        return (f"{name} declares supports_streaming=False; "
                f"{tier} streams window superbatches from a "
                "FederatedStore — use the resident on-device scan or "
                "the per-round host loop")
    if rec.protocol is None:
        why = rec.excluded or "no reason declared"
        return (f"{name} opts out of the windowed carry protocol "
                f"(window_protocol=None): {why} — use the per-round "
                "host loop")
    if rec.protocol == "round":
        if rec.custom_round:
            return (f"{name} customizes the round itself; {tier} only "
                    "serves algorithms whose per-round procedure is "
                    "run_round + _server_update (declare the 'custom' "
                    "windowed carry protocol with a _build_fused_step "
                    "for a bespoke one-dispatch round)")
        if not rec.pure_server_update:
            return (f"{name} overrides _server_update without providing "
                    f"its pure windowed form; {tier} needs the pure "
                    "carry record — override _window_server_update (and "
                    "the carry init/commit hooks) or set "
                    "window_protocol = None")
        if tier == "train_rounds_on_device" and rec.round_aux:
            return (f"{name} feeds its round per-round host-computed aux "
                    "operands (_round_aux/_window_scan_extras), which "
                    "the on-device scan — sampling inside the jit — has "
                    "no slot for; use the windowed streaming scan or "
                    "the host loop")
        return (f"{name} does not ride {tier} "
                f"(capability record: {rec})")
    # protocol == "custom"
    if not rec.custom_step and tier != "train_rounds_windowed":
        return (f"{name} declares window_protocol='custom' but does not "
                f"provide _build_fused_step; {tier} replays the fused "
                "one-dispatch round, which only the step hook defines")
    if tier == "train_rounds_on_device":
        return (f"{name} carries client-stacked state through a custom "
                "scan body; the on-device scan serves 'round'-protocol "
                "algorithms — use the windowed streaming scan")
    return (f"{name} declares window_protocol='custom' but provides "
            "neither _build_fused_step nor _build_window_scan; the "
            "custom carry protocol needs the scan body (plus the carry "
            "init/commit hooks)")


class ExcludedScanTiers:
    """The scan-tier entry points as record-derived refusals — the ONE
    implementation behind both ``FederatedLoop`` (so every loop-family
    algorithm that doesn't override them fails with its declared reason)
    and the standalone training loops outside it (FedGKT's alternating
    distillation, SplitNN's relay ring, vertical FL), instead of an
    AttributeError that says nothing. FedAvgAPI overrides all three with
    the real tiers."""

    #: Carry capability declarations (see module docstring): subclasses
    #: publish explicit tiers (``capability_tiers``) or declare WHY they
    #: sit the scan tiers out (``window_exclusion``).
    window_protocol = None
    window_exclusion = None

    def train_rounds_windowed(self, *a, **k):
        raise NotImplementedError(refusal(type(self),
                                          "train_rounds_windowed"))

    def train_rounds_pipelined(self, *a, **k):
        raise NotImplementedError(refusal(type(self),
                                          "train_rounds_pipelined"))

    def train_rounds_on_device(self, *a, **k):
        raise NotImplementedError(refusal(type(self),
                                          "train_rounds_on_device"))


def zoo_records():
    """``[(display_name, cls, CarryCapability)]`` for the whole zoo, in
    matrix order. Imports lazily — this walks every algorithm module."""
    import importlib

    out = []
    for name, module, clsname in ZOO:
        mod = importlib.import_module(f"fedml_tpu.algos.{module}")
        cls = getattr(mod, clsname)
        out.append((name, cls, record_for(cls)))
    return out


def _cell(flag: bool) -> str:
    return "✓" if flag else "✗"


def render_matrix() -> str:
    """The EXECUTION.md algorithm × tier support matrix, generated from
    the capability records (drift-tested by tests/test_zoo_windowed.py;
    regenerate with ``python scripts/gen_support_matrix.py --write``).
    Every ✓ is backed by the record the tier guards consume — the table
    CANNOT say yes where the guard says no."""
    lines = [
        "| algorithm | protocol | carry | pipelined | fused round | "
        "windowed scan | on-device scan |",
        "|---|---|---|---|---|---|---|",
    ]
    excluded = []
    for name, cls, rec in zoo_records():
        proto = rec.protocol if rec.protocol else "—"
        lines.append(
            f"| {name} | {proto} | {rec.carry} | {_cell(rec.pipelined)} | "
            f"{_cell(rec.fused)} | {_cell(rec.windowed)} | "
            f"{_cell(rec.on_device)} |")
        if rec.excluded:
            excluded.append(f"- **{name}** — {rec.excluded}")
    out = "\n".join(lines)
    if excluded:
        out += ("\n\nRecord-derived exclusions (the refusal each guard "
                "raises):\n\n" + "\n".join(excluded))
    return out


#: Markers bounding the generated region inside docs/EXECUTION.md.
MATRIX_BEGIN = ("<!-- BEGIN GENERATED capability-matrix "
                "(python scripts/gen_support_matrix.py --write) -->")
MATRIX_END = "<!-- END GENERATED capability-matrix -->"


def matrix_block() -> str:
    """The full marker-bounded block embedded in docs/EXECUTION.md."""
    return f"{MATRIX_BEGIN}\n{render_matrix()}\n{MATRIX_END}"
