"""Serverless (decentralized) message-passing template.

Parity with the reference's ``decentralized_framework``
(fedml_api/distributed/decentralized_framework/algorithm_api.py:15,
decentralized_worker_manager.py:29-39, decentralized_worker.py:19): each
worker pushes its local result to its topology out-neighbors, waits for all
in-neighbors, mixes with the topology weights, and advances to the next
round — no server rank.
"""

from __future__ import annotations

import numpy as np

from fedml_tpu.comm.loopback import LoopbackNetwork, run_workers
from fedml_tpu.comm.managers import ClientManager
from fedml_tpu.comm.message import Message
from fedml_tpu.core.topology import SymmetricTopologyManager

MSG_TYPE_NEIGHBOR_RESULT = 11

MSG_ARG_KEY_RESULT = "result"
MSG_ARG_KEY_ROUND = "round"


class DecentralizedWorker:
    """Per-worker state: in-neighbor results for the current round; mixing
    is the topology-weighted average (decentralized_worker.py:19-39)."""

    def __init__(self, worker_index: int, topology):
        self.worker_index = worker_index
        self.topology = topology
        self.in_neighbors = list(topology.get_in_neighbor_idx_list(worker_index))
        self.weights = np.asarray(topology.get_in_neighbor_weights(worker_index))
        self._buffer = {}

    def add_result(self, sender: int, result: float) -> None:
        self._buffer[sender] = result

    def check_whether_all_receive(self) -> bool:
        return all(n in self._buffer for n in self.in_neighbors)

    def mix(self, own_result: float) -> float:
        mixed = self.weights[self.worker_index] * own_result
        for n in self.in_neighbors:
            mixed += self.weights[n] * self._buffer[n]
        self._buffer.clear()
        return float(mixed)


class DecentralizedWorkerManager(ClientManager):
    def __init__(self, args, worker: DecentralizedWorker, rank: int, size: int,
                 comm_round: int, local_fn, backend: str = "LOOPBACK"):
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.worker = worker
        self.comm_round = comm_round
        self.local_fn = local_fn
        self.round_idx = 0
        self.history = []
        self.current = None
        # Out-of-order rounds: a fast neighbor may send round r+1 before we
        # finish r; park those until we advance.
        self._future = []

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.start_round()
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_NEIGHBOR_RESULT, self.handle_msg_from_neighbor
        )

    def start_round(self) -> None:
        self.current = self.local_fn(self.round_idx, self.current)
        for neighbor in self.worker.topology.get_out_neighbor_idx_list(self.rank):
            msg = Message(MSG_TYPE_NEIGHBOR_RESULT, self.rank, int(neighbor))
            msg.add(MSG_ARG_KEY_RESULT, self.current)
            msg.add(MSG_ARG_KEY_ROUND, self.round_idx)
            self.send_message(msg)
        self._check_advance()

    def handle_msg_from_neighbor(self, msg: Message) -> None:
        if msg.get(MSG_ARG_KEY_ROUND) != self.round_idx:
            self._future.append(msg)
            return
        self.worker.add_result(msg.get_sender_id(), msg.get(MSG_ARG_KEY_RESULT))
        self._check_advance()

    def _check_advance(self) -> None:
        while self.worker.check_whether_all_receive():
            self.current = self.worker.mix(self.current)
            self.history.append(self.current)
            self.round_idx += 1
            if self.round_idx >= self.comm_round:
                self.finish()
                return
            self.current = self.local_fn(self.round_idx, self.current)
            for neighbor in self.worker.topology.get_out_neighbor_idx_list(self.rank):
                out = Message(MSG_TYPE_NEIGHBOR_RESULT, self.rank, int(neighbor))
                out.add(MSG_ARG_KEY_RESULT, self.current)
                out.add(MSG_ARG_KEY_ROUND, self.round_idx)
                self.send_message(out)
            pending, self._future = self._future, []
            for m in pending:
                self.handle_msg_from_neighbor(m)


def FedML_Decentralized_Demo_distributed(worker_num: int, comm_round: int, local_fn,
                                         neighbor_num: int = 2):
    """Build a ring(+random) symmetric topology and run the gossip template
    (algorithm_api.py:15 analogue). Returns each worker's mixing history."""
    topology = SymmetricTopologyManager(worker_num, neighbor_num, seed=0)
    network = LoopbackNetwork(worker_num)

    class Args:
        pass

    args = Args()
    args.network = network
    managers = [
        DecentralizedWorkerManager(
            args, DecentralizedWorker(rank, topology), rank, worker_num,
            comm_round, local_fn,
        )
        for rank in range(worker_num)
    ]
    run_workers([m.run for m in managers])
    return [m.history for m in managers]
