"""Minimal message-passing FL template (didactic skeleton).

Parity with the reference's ``base_framework``
(fedml_api/distributed/base_framework/algorithm_api.py:16,
central_worker.py:28-33): a central worker sums scalar "local results" from
every client each round, then broadcasts the global result. New algorithms
that need true multi-process federation start from this skeleton; simulated
algorithms start from ``FederatedLoop`` instead.
"""

from __future__ import annotations

from fedml_tpu.comm.loopback import LoopbackNetwork, run_workers
from fedml_tpu.comm.managers import ClientManager, ServerManager
from fedml_tpu.comm.message import Message

MSG_TYPE_S2C_INIT = 1
MSG_TYPE_C2S_RESULT = 2
MSG_TYPE_S2C_GLOBAL = 3

MSG_ARG_KEY_RESULT = "result"
MSG_ARG_KEY_ROUND = "round"


class BaseCentralWorker:
    """Server state: collect one scalar per client, aggregate by sum
    (central_worker.py:28-33)."""

    def __init__(self, client_num: int):
        self.client_num = client_num
        self._results = {}

    def add_client_local_result(self, index: int, result: float) -> None:
        self._results[index] = result

    def check_whether_all_receive(self) -> bool:
        return len(self._results) == self.client_num

    def aggregate(self) -> float:
        total = float(sum(self._results.values()))
        self._results.clear()
        return total


class BaseServerManager(ServerManager):
    def __init__(self, args, worker: BaseCentralWorker, comm_round: int, size: int,
                 backend: str = "LOOPBACK"):
        super().__init__(args, rank=0, size=size, backend=backend)
        self.worker = worker
        self.comm_round = comm_round
        self.round_idx = 0
        self.global_results = []

    def run(self) -> None:
        self.register_message_receive_handlers()
        for client_id in range(1, self.size):
            msg = Message(MSG_TYPE_S2C_INIT, 0, client_id)
            msg.add(MSG_ARG_KEY_ROUND, 0)
            self.send_message(msg)
        self.com_manager.handle_receive_message()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MSG_TYPE_C2S_RESULT, self.handle_message_receive_result
        )

    def handle_message_receive_result(self, msg: Message) -> None:
        self.worker.add_client_local_result(
            msg.get_sender_id(), msg.get(MSG_ARG_KEY_RESULT)
        )
        if not self.worker.check_whether_all_receive():
            return
        global_result = self.worker.aggregate()
        self.global_results.append(global_result)
        self.round_idx += 1
        done = self.round_idx >= self.comm_round
        for client_id in range(1, self.size):
            out = Message(MSG_TYPE_S2C_GLOBAL, 0, client_id)
            out.add(MSG_ARG_KEY_RESULT, global_result)
            out.add(MSG_ARG_KEY_ROUND, self.round_idx)
            out.add("done", done)
            self.send_message(out)
        if done:
            self.finish()


class BaseClientManager(ClientManager):
    def __init__(self, args, rank: int, size: int, local_fn,
                 backend: str = "LOOPBACK"):
        """``local_fn(round_idx, global_result) -> float`` is the client's
        local computation."""
        super().__init__(args, rank=rank, size=size, backend=backend)
        self.local_fn = local_fn

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_TYPE_S2C_INIT, self.handle_init)
        self.register_message_receive_handler(MSG_TYPE_S2C_GLOBAL, self.handle_global)

    def _train_and_send(self, round_idx: int, global_result) -> None:
        result = self.local_fn(round_idx, global_result)
        msg = Message(MSG_TYPE_C2S_RESULT, self.rank, 0)
        msg.add(MSG_ARG_KEY_RESULT, result)
        self.send_message(msg)

    def handle_init(self, msg: Message) -> None:
        self._train_and_send(msg.get(MSG_ARG_KEY_ROUND), None)

    def handle_global(self, msg: Message) -> None:
        if msg.get("done"):
            self.finish()
            return
        self._train_and_send(msg.get(MSG_ARG_KEY_ROUND), msg.get(MSG_ARG_KEY_RESULT))


def FedML_Base_distributed(client_num: int, comm_round: int, local_fn):
    """Run the template end-to-end on the loopback network; returns the
    list of per-round aggregated results (algorithm_api.py:16 analogue)."""
    network = LoopbackNetwork(client_num + 1)

    class Args:
        pass

    args = Args()
    args.network = network
    worker = BaseCentralWorker(client_num)
    server = BaseServerManager(args, worker, comm_round, client_num + 1)
    clients = [
        BaseClientManager(args, rank, client_num + 1, local_fn)
        for rank in range(1, client_num + 1)
    ]
    run_workers([server.run] + [c.run for c in clients])
    return server.global_results
