"""FedProx — FedAvg with a proximal term μ/2·‖w − w_global‖² in the local
objective (Li et al., MLSys'20).

NOTE: the reference's fedprox snapshot does NOT actually implement the μ
term — its train loop is a verbatim FedAvg copy (SURVEY.md §2.3,
fedml_api/distributed/fedprox/MyModelTrainer.py:19-49 has no ``mu``). We
implement it properly: the proximal gradient μ(w − w_global) is added to
every local step via the trainer's ``extra_grad_fn`` hook, with ``w_global``
the round's broadcast parameters.
"""

from __future__ import annotations

import jax

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.trainer.local import make_local_train_fn_from_cfg


class FedProxAPI(FedAvgAPI):
    """FedAvg whose LOCAL objective carries the proximal term — nothing
    else changes, so FedProx rides every execution tier FedAvg does
    (pipelined, windowed streaming) through the inherited "round" carry
    protocol with NO carry at all: the μ term lives inside
    ``round_fn``'s local trainer, which the windowed scan replays as-is
    (docs/EXECUTION.md support matrix; bit-equality pinned in
    tests/test_windowed.py)."""

    window_carry = "— (μ term lives in the local step)"

    def _build_local_train(self, optimizer, loss_fn):
        mu = self.cfg.fedprox_mu

        def prox_grad(params, global_params):
            return jax.tree.map(lambda p, g: mu * (p - g), params, global_params)

        return make_local_train_fn_from_cfg(
            self.fns.apply,
            optimizer,
            self.cfg,
            loss_fn,
            extra_grad_fn=prox_grad if mu > 0 else None,
        )
