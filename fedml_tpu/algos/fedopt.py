"""FedOpt family — FedAvgM / FedAdam / FedYogi / FedAdagrad.

Parity: fedml_api/distributed/fedopt/FedOptAggregator.py:70-109 — aggregate
client models, form the server pseudo-gradient ``w_old − w_avg``, and apply a
server optimizer step. The reference looks optimizers up by name in the
torch.optim registry (fedopt/optrepo.py:7); here the registry is optax, and
the server step is a jitted optax update on the params pytree.

Hyperparameter names follow the reference's flags ``--server_optimizer`` /
``--server_lr`` / ``--server_momentum``
(fedml_experiments/distributed/fedopt/main_fedopt.py:54-66).
"""

from __future__ import annotations

import jax
import optax

from fedml_tpu.algos.fedavg import FedAvgAPI
from fedml_tpu.core.aggregate import pseudo_gradient
from fedml_tpu.trainer.local import NetState


def make_server_optimizer(name: str, lr: float, momentum: float = 0.9):
    """Server optimizers from "Adaptive Federated Optimization" (Reddi'20),
    the paper the reference's benchmark table follows (benchmark/README.md:60-101)."""
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum > 0 else None)
    if name == "adam":
        return optax.adam(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name == "yogi":
        return optax.yogi(lr, b1=0.9, b2=0.99, eps=1e-3)
    if name == "adagrad":
        return optax.adagrad(lr, eps=1e-3)
    raise ValueError(f"unknown server optimizer {name!r}")


class FedOptAPI(FedAvgAPI):
    window_carry = "server optimizer state"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.cfg
        self.server_opt = make_server_optimizer(
            cfg.server_optimizer, cfg.server_lr, cfg.server_momentum
        )
        self.server_opt_state = self.server_opt.init(self.net.params)

        def server_step(params, avg_params, opt_state):
            # Reference sets param.grad = old − avg then opt.step()
            # (FedOptAggregator.set_model_global_grads:109).
            pg = pseudo_gradient(params, avg_params)
            updates, opt_state = self.server_opt.update(pg, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._server_step = jax.jit(server_step)

    def _server_update(self, old_net, avg_net):
        new_params, self.server_opt_state = self._server_step(
            old_net.params, avg_net.params, self.server_opt_state
        )
        # Non-trainable state (BN stats) keeps the plain client average.
        return NetState(new_params, avg_net.model_state)

    # --- windowed carry protocol: thread the server optimizer state ------
    # _server_step is already a pure jitted optax step, so the windowed
    # scan folds the SAME function between rounds (jit-under-scan
    # inlines) with the optimizer state as the carried extra — FedOpt
    # runs W rounds per dispatch bit-equal to its host loop.
    def _window_server_update(self):
        server_step = self._server_step

        def update(net, avg, opt_state, key):
            # key: the round's rng key (protocol slot for randomized
            # server updates) — the optax step is deterministic.
            del key
            new_params, opt_state = server_step(
                net.params, avg.params, opt_state)
            return NetState(new_params, avg.model_state), opt_state

        return update

    def _window_carry_init(self):
        return self.server_opt_state

    def _window_carry_commit(self, extra) -> None:
        self.server_opt_state = extra
