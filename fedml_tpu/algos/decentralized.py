"""Decentralized (serverless) federated optimization: DSGD and PushSum.

Parity:
- fedml_api/standalone/decentralized/ — ``ClientDSGD``
  (client_dsgd.py:6-100: local step then topology-weighted neighbor mixing)
  and ``ClientPushsum`` (client_pushsum.py:7: push-sum gossip with
  column-stochastic weights for directed graphs).
- fedml_api/distributed/decentralized_framework/ — the neighbor
  send/await message loop (decentralized_worker_manager.py:29-39).

TPU design: all n clients' models live as ONE client-stacked pytree
``[n, ...]``; local training is vmapped, and a full gossip exchange is a
single mixing-matrix einsum ``W @ stacked`` — the MXU does the message
passing that the reference does with per-edge MPI sends.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algos.config import FedConfig
from fedml_tpu.algos.loop import FederatedLoop
from fedml_tpu.core.topology import BaseTopologyManager, column_stochastic
from fedml_tpu.data.batching import FederatedArrays
from fedml_tpu.parallel.shard import client_rngs
from fedml_tpu.trainer.local import (
    make_client_optimizer,
    make_eval_fn,
    make_local_train_fn_from_cfg,
    model_fns,
    softmax_ce,
)


def _per_client(omega, p):
    """Broadcast a per-client vector ``omega [n]`` against a client-stacked
    leaf ``p [n, ...]`` (one reshape rule for every ω·tree operation)."""
    return omega.reshape((-1,) + (1,) * (p.ndim - 1)).astype(p.dtype)


def _debias_tree(stacked, omega):
    """PushSum de-bias x_i = z_i / ω_i over a client-stacked pytree."""
    return jax.tree.map(lambda p: p / _per_client(omega, p), stacked)


class DecentralizedAPI(FederatedLoop):
    """Every client participates every round (decentralized has no server to
    sample); ``mode`` is ``"dsgd"`` (symmetric, row-stochastic) or
    ``"pushsum"`` (directed, column-stochastic with weight de-biasing:
    gradients are taken at the de-biased iterate x_i = z_i/ω_i, matching
    the reference's ClientPushsum semantics, client_pushsum.py:7-100).

    Carry capability record: the gossip state ``(nets, push_weights)``
    is a pure carry and the round is already ONE dispatch, so the scan
    tiers that apply to a full-participation resident federation ride:
    :meth:`train_rounds_on_device` scans n rounds in one donated
    dispatch (zero host round-trips between gossip exchanges — the
    mixing einsum chains on device), and :meth:`train_rounds_pipelined`
    enqueues per-round dispatches without the per-round loss sync. The
    windowed STORE tier does not apply — nothing streams (every client
    trains on its resident shard every round), which the record-derived
    refusal explains."""

    window_protocol = "custom"
    window_carry = "client-stacked models + push weights"
    window_exclusion = (
        "full-participation gossip over device-resident client stacks — "
        "no cohort ever streams from a store, so the windowed store tier "
        "does not apply; train_rounds_on_device IS the multi-round scan "
        "fast path here")
    capability_tiers = {"fused": True, "pipelined": True,
                        "windowed": False, "on_device": True}

    def __init__(
        self,
        model,
        train_fed: FederatedArrays,
        test_global,
        cfg: FedConfig,
        topology: BaseTopologyManager,
        mode: str = "dsgd",
        loss_fn=softmax_ce,
    ):
        if mode not in ("dsgd", "pushsum"):
            raise ValueError(f"unknown decentralized mode {mode!r}")
        self.cfg = cfg
        self.mode = mode
        self.train_fed = train_fed
        self.test_global = test_global
        self.fns = model_fns(model)
        n = train_fed.num_clients

        W = topology.mixing_matrix()
        if W.shape != (n, n):
            raise ValueError(f"topology is {W.shape}, need ({n}, {n})")
        self.W = jnp.asarray(
            column_stochastic(W) if mode == "pushsum" else W, jnp.float32
        )

        optimizer = make_client_optimizer(cfg.client_optimizer, cfg.lr, cfg.wd)
        local_train = make_local_train_fn_from_cfg(self.fns.apply, optimizer,
                                                   cfg, loss_fn)

        def mix(stacked):
            return jax.tree.map(
                lambda p: jnp.einsum(
                    "ij,j...->i...", self.W, p.astype(jnp.float32)
                ).astype(p.dtype),
                stacked,
            )

        def round_fn(nets, omega, x, y, mask, rng):
            rngs = client_rngs(rng, n, 0)
            if self.mode == "pushsum":
                # Train at the de-biased iterate x = z/ω; fold the update
                # back into z-space (Δz = ω·Δx), then gossip z and ω with
                # the column-stochastic matrix.
                xs = _debias_tree(nets, omega)
                trained, losses = jax.vmap(local_train)(xs, x, y, mask, rngs)
                z = jax.tree.map(
                    lambda zl, xl, tl: zl + _per_client(omega, xl) * (tl - xl),
                    nets, xs, trained,
                )
                return mix(z), self.W @ omega, jnp.mean(losses)
            trained, losses = jax.vmap(local_train)(nets, x, y, mask, rngs)
            return mix(trained), omega, jnp.mean(losses)

        self.round_fn = jax.jit(round_fn)
        self.eval_fn = jax.jit(make_eval_fn(self.fns.apply, loss_fn))

        self.rng, init_rng = jax.random.split(jax.random.PRNGKey(cfg.seed))
        net0 = self.fns.init(init_rng, np.asarray(train_fed.x[0, 0]))
        # Every client starts from the same model (reference does likewise).
        self.nets = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), net0
        )
        self.push_weights = jnp.ones((n,), jnp.float32)

    def _debiased(self):
        """PushSum estimate x_i = z_i / w_i; DSGD uses params directly."""
        if self.mode == "dsgd":
            return self.nets
        return _debias_tree(self.nets, self.push_weights)

    def consensus_net(self):
        """Uniform average over clients — the quantity decentralized SGD
        drives to the optimum."""
        return jax.tree.map(lambda p: jnp.mean(p, axis=0), self._debiased())

    def train_one_round(self, round_idx: int) -> Dict[str, float]:
        f = self.train_fed
        self.rng, rnd_rng = jax.random.split(self.rng)
        self.nets, self.push_weights, loss = self.round_fn(
            self.nets, self.push_weights, f.x, f.y, f.mask, rnd_rng
        )
        return {"round": round_idx, "train_loss": float(loss)}

    def train_rounds_pipelined(self, n_rounds: int, start_round: int = 0):
        """``n_rounds`` gossip rounds with the per-round ``float(loss)``
        sync deferred to the end — per-round semantics identical to
        :meth:`train_one_round` in a loop (the rng chain and round math
        are the same; only the host sync moves)."""
        f = self.train_fed
        losses = []
        for _ in range(n_rounds):
            # fedlint: disable=R1(deliberate round-order chain: identical to train_one_round's per-round split so the pipelined loop is bit-equal to the host loop)
            self.rng, rnd_rng = jax.random.split(self.rng)
            self.nets, self.push_weights, loss = self.round_fn(
                self.nets, self.push_weights, f.x, f.y, f.mask, rnd_rng)
            losses.append(loss)
        return [float(l) for l in losses]

    def train_rounds_on_device(self, n_rounds: int):
        """``n_rounds`` WHOLE gossip rounds in one jitted ``lax.scan``
        with the donated carry ``(nets, push_weights)`` — zero host
        round-trips between rounds, bit-equal to the host loop (full
        participation means the per-round rng chain is the only host
        state, and it is reproduced exactly). The incoming stacks are
        DONATED: host-copy ``api.nets`` before calling if you need the
        pre-scan values."""
        scan_fn = getattr(self, "_rounds_scan_fn", None)
        if scan_fn is None:
            round_fn = self.round_fn  # jitted; inlines under the scan

            def scan_fn(nets, omega, fed_x, fed_y, fed_mask, keys):
                def body(carry, key):
                    nets, omega = carry
                    nets, omega, loss = round_fn(
                        nets, omega, fed_x, fed_y, fed_mask, key)
                    return (nets, omega), loss

                return jax.lax.scan(body, (nets, omega), keys)

            scan_fn = jax.jit(scan_fn, donate_argnums=(0, 1))
            self._rounds_scan_fn = scan_fn

        keys = []
        for _ in range(n_rounds):
            # fedlint: disable=R1(round-order chain reproduced on purpose: bit-equality with the host loop is tested)
            self.rng, rnd = jax.random.split(self.rng)
            keys.append(rnd)
        f = self.train_fed
        # Distinct names for the donated stacks (fedlint R5 discipline —
        # the donated buffers are dead after the call).
        nets0, omega0 = self.nets, self.push_weights
        carry, losses = scan_fn(nets0, omega0, f.x, f.y, f.mask,
                                jnp.stack(keys))
        self.nets, self.push_weights = carry
        return losses

    def _eval_net(self):
        return self.consensus_net()
